"""Example: full pipeline into a local Parquet lake, no Postgres required.

Runs the in-process fake walsender, copies two tables, streams CDC, then
prints the lake's collapsed current rows."""

import asyncio
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from etl_tpu.config import BatchConfig, BatchEngine, PipelineConfig
from etl_tpu.destinations.lake import LakeConfig, LakeDestination
from etl_tpu.models import (ColumnSchema, Oid, TableName, TableSchema)
from etl_tpu.postgres.fake import FakeDatabase, FakeSource
from etl_tpu.runtime import Pipeline, TableStateType
from etl_tpu.store import NotifyingStore

ACCOUNTS = 16384


async def main() -> None:
    db = FakeDatabase()
    db.create_table(TableSchema(
        ACCOUNTS, TableName("public", "accounts"),
        (ColumnSchema("id", Oid.INT8, nullable=False, primary_key_ordinal=1),
         ColumnSchema("email", Oid.TEXT),
         ColumnSchema("balance", Oid.NUMERIC),
         ColumnSchema("created", Oid.TIMESTAMPTZ))),
        rows=[[str(i), f"user{i}@example.com", f"{i}.50",
               "2024-01-01 00:00:00+00"] for i in range(1, 101)])
    db.create_publication("pub", [ACCOUNTS])

    warehouse = tempfile.mkdtemp(prefix="etl-lake-")
    dest = LakeDestination(LakeConfig(warehouse))
    store = NotifyingStore()
    pipeline = Pipeline(
        config=PipelineConfig(
            pipeline_id=1, publication_name="pub",
            batch=BatchConfig(max_fill_ms=50, batch_engine=BatchEngine.TPU)),
        store=store, destination=dest,
        source_factory=lambda: FakeSource(db))

    await pipeline.start()
    await asyncio.wait_for(store.notify_on(ACCOUNTS, TableStateType.READY), 30)
    print(f"initial copy done → {warehouse}")

    async with db.transaction() as tx:
        tx.insert(ACCOUNTS, ["101", "new@example.com", "9.99",
                             "2024-06-01 12:00:00+00"])
        tx.update(ACCOUNTS, ["1", None, None, None],
                  ["1", "user1@example.com", "1000.00",
                   "2024-01-01 00:00:00+00"])
        tx.delete(ACCOUNTS, ["2", None, None, None])
    await asyncio.sleep(0.5)
    await pipeline.shutdown_and_wait()

    # read back as a consumer would: fresh handle onto the warehouse
    reader = LakeDestination(LakeConfig(warehouse))
    await reader.startup()
    current = reader.read_current(ACCOUNTS)
    print(f"lake current rows: {current.num_rows} "
          f"(copied 100, +1 insert, -1 delete)")
    row1 = [r for r in current.to_pylist() if r["id"] == 1][0]
    print(f"updated row 1 balance: {row1['balance']}")


if __name__ == "__main__":
    asyncio.run(main())
