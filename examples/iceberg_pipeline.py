"""Example: full pipeline into an Iceberg table, no Postgres required.

Runs the in-process fake walsender against the protocol-enforcing fake
REST catalog, copies a table, streams CDC, then independently walks the
committed snapshot chain: Avro manifest list → manifest → Parquet data
files → CDC collapse — the same read path any Iceberg engine takes.

Point `IcebergConfig.catalog_url` at a real REST catalog (Lakekeeper,
Polaris, Nessie…) to commit against it instead.
"""

import asyncio
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from etl_tpu.config import BatchConfig, BatchEngine, PipelineConfig
from etl_tpu.destinations.iceberg import IcebergConfig, IcebergDestination
from etl_tpu.models import ColumnSchema, Oid, TableName, TableSchema
from etl_tpu.postgres.fake import FakeDatabase, FakeSource
from etl_tpu.runtime import Pipeline, TableStateType
from etl_tpu.store import NotifyingStore
from etl_tpu.testing.avro_reader import read_avro_ocf
from etl_tpu.testing.fake_iceberg import FakeIcebergCatalog

ORDERS = 16384


async def main() -> None:
    db = FakeDatabase()
    db.create_table(TableSchema(
        ORDERS, TableName("public", "orders"),
        (ColumnSchema("id", Oid.INT8, nullable=False, primary_key_ordinal=1),
         ColumnSchema("sku", Oid.TEXT),
         ColumnSchema("qty", Oid.INT4))),
        rows=[[str(i), f"sku-{i % 7}", str(1 + i % 5)]
              for i in range(1, 51)])
    db.create_publication("pub", [ORDERS])

    catalog = FakeIcebergCatalog()
    await catalog.start()
    warehouse = tempfile.mkdtemp(prefix="etl-iceberg-")
    dest = IcebergDestination(IcebergConfig(
        catalog_url=catalog.url(), warehouse_path=warehouse))
    store = NotifyingStore()
    pipeline = Pipeline(
        config=PipelineConfig(
            pipeline_id=1, publication_name="pub",
            batch=BatchConfig(max_fill_ms=50, batch_engine=BatchEngine.TPU)),
        store=store, destination=dest,
        source_factory=lambda: FakeSource(db))

    await pipeline.start()
    await asyncio.wait_for(store.notify_on(ORDERS, TableStateType.READY), 30)
    print(f"initial copy committed as an Iceberg snapshot → {warehouse}")

    async with db.transaction() as tx:
        tx.insert(ORDERS, ["51", "sku-new", "3"])
        tx.update(ORDERS, ["1", None, None], ["1", "sku-0", "99"])
        tx.delete(ORDERS, ["2", None, None])
    await asyncio.sleep(0.5)
    await pipeline.shutdown_and_wait()
    await catalog.stop()

    # read back the way an Iceberg engine would: snapshot chain →
    # manifest lists → manifests → data files → CDC collapse
    import pyarrow.parquet as pq

    table = catalog.table("etl", "public_orders")
    print(f"snapshots: {len(table.snapshots)}, "
          f"head = {table.refs['main']}")
    state: dict = {}
    for snap in table.snapshots:
        _, manifests, _ = read_avro_ocf(snap["manifest-list"])
        for m in manifests:
            _, entries, _ = read_avro_ocf(m["manifest_path"])
            for e in entries:
                for row in pq.read_table(
                        e["data_file"]["file_path"]).to_pylist():
                    seq = row.get("_CHANGE_SEQUENCE_NUMBER") or ""
                    cur = state.get(row["id"])
                    if cur is None or seq >= cur[0]:
                        state[row["id"]] = (seq, row)
    live = {k: v[1] for k, v in state.items()
            if v[1]["_CHANGE_TYPE"] != "DELETE"}
    print(f"live rows after CDC collapse: {len(live)}")
    print("id=1 →", {k: live[1][k] for k in ("sku", "qty")})
    assert len(live) == 50 and live[1]["qty"] == 99 and 2 not in live
    print("ok")


if __name__ == "__main__":
    asyncio.run(main())
