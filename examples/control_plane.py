"""Example: run the control-plane API with the local orchestrator.

POST tenants/sources/destinations/pipelines, then
POST /v1/pipelines/1/start to launch a replicator subprocess."""

import asyncio
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from aiohttp import web

from etl_tpu.api.app import ApiState, build_app
from etl_tpu.api.crypto import ConfigCipher, EncryptionKey
from etl_tpu.api.orchestrator import LocalOrchestrator


async def main() -> None:
    import os
    import secrets

    work = tempfile.mkdtemp(prefix="etl-api-")
    api_key = os.environ.get("ETL_API_KEY") or secrets.token_urlsafe(24)
    state = ApiState(f"{work}/api.db", ConfigCipher(EncryptionKey.generate()),
                     LocalOrchestrator(work), api_key=api_key)
    runner = web.AppRunner(build_app(state))
    await runner.setup()
    await web.TCPSite(runner, "127.0.0.1", 8080).start()
    print("control plane on http://127.0.0.1:8080 (see /openapi.json)")
    print(f"Authorization: Bearer {api_key}")
    await asyncio.Event().wait()


if __name__ == "__main__":
    asyncio.run(main())
