"""etl-lint: AST-based async-safety & device-sync static analysis.

The TPU decode path wins (BENCH_r05: 14-17x CPU baseline) are fragile in
exactly the ways a human reviewer keeps missing: a synchronous
jit-compiling probe inside the asyncio apply loop, a dropped
`asyncio.create_task` handle, a broad `except` that eats a
`CancelledError` mid-shutdown. This package enforces those invariants by
machinery instead of post-hoc advice — lexically per module AND
interprocedurally over the whole program (wrapping the sink in a helper
one file away no longer defeats a rule):

  - `rules`      — the per-module rule set (see docs/static-analysis.md)
  - `visitor`    — scope/context-tracking AST walk the rules plug into
  - `callgraph`  — whole-program symbol tables + resolved call graph
  - `contexts`   — async/hot-loop context propagation along call edges
  - `cfg`        — per-function CFG + forward dataflow
  - `interproc`  — transitive rule upgrades + resource/deadlock rules
  - `findings`   — the finding model + stable fingerprints + chains
  - `baseline`   — suppression file I/O for grandfathered findings
  - `cli`        — `python -m etl_tpu.analysis [paths]`
  - `annotations`— the runtime-visible `@hot_loop` marker

Everything here is stdlib-only so hot modules (ops/engine, runtime/
assembler) can import `hot_loop` without pulling analysis machinery.
"""

from __future__ import annotations

from .annotations import hot_loop
from .findings import Finding

__all__ = ["Finding", "analyze_paths", "analyze_source", "hot_loop"]


def analyze_source(source: str, rel_path: str):
    """Lint one module's source; `rel_path` drives path-scoped rules."""
    from .rules import analyze_source as _impl

    return _impl(source, rel_path)


def analyze_paths(paths, root=None):
    from .rules import analyze_paths as _impl

    return _impl(paths, root=root)
