"""Whole-program symbol tables and call graph.

One `Project` holds every scanned module's AST plus the resolution
tables the interprocedural rules key on:

  - per-module import tables mapping local aliases to qualified names
    (`np` → `numpy`, `sleep` → `time.sleep`, `hl` →
    `analysis.annotations.hot_loop`, `eng` → project module `ops.engine`);
  - per-function call sites with both the LEXICAL dotted target and the
    RESOLVED target — a project `FunctionInfo` when the call lands on a
    function we can see, else the fully qualified external name;
  - class tables (methods, base names, lock-valued attributes) so
    `self.method()` and `ClassName()` construction resolve.

Resolution rules (the documented contract — see docs/static-analysis.md
for the precision limits):

  - bare `foo()`: enclosing functions' nested defs, then module-level
    defs, then classes (→ `__init__`), then imports;
  - `self.m()` / `cls.m()`: the enclosing class, then base classes
    resolvable in module scope (single-pass, depth-first);
  - `alias.attr()`: follow the import table; project modules resolve to
    their symbols (chasing at most `_MAX_CHASE` re-export hops), other
    modules produce a qualified external name for sink matching;
  - anything receiver-typed (`obj.method()` on a parameter or local of
    unknown type) stays unresolved — reported only when a lexical rule
    sees it.

Paths are canonical (findings.canonical_path): the module key for
`runtime/copy.py` is `runtime.copy`, and absolute `etl_tpu.x.y` imports
strip the package prefix, so fixture trees mirroring the package layout
resolve exactly like the real tree.
"""

from __future__ import annotations

import ast

from .findings import canonical_path
from .visitor import dotted_name, terminal_name

#: decorator terminal names carrying analysis context (annotations.py);
#: matched on the RESOLVED name's terminal component so import aliases
#: (`from ...annotations import hot_loop as hl`) no longer defeat them
HOT_DECORATOR = "hot_loop"
DISPATCH_DECORATOR = "dispatch_stage"

#: wrappers that forward an await into their argument coroutines:
#: `await wait_for(helper(), t)` runs helper()'s body on this task
AWAIT_FORWARDERS = frozenset({"wait_for", "shield", "gather"})

#: constructors whose result is an asyncio lock-ish resource
_LOCK_CTORS = frozenset({"asyncio.Lock", "asyncio.Semaphore",
                         "asyncio.BoundedSemaphore", "asyncio.Condition"})
#: a threading.Condition IS a mutex (acquire/release around its lock)
#: — holding it guards state for both the lock rules and the
#: concurrency tier's lockset analysis (the queue/Condition-handoff
#: sanction in docs/CONCURRENCY.md rides on this)
_THREAD_LOCK_CTORS = frozenset({"threading.Lock", "threading.RLock",
                                "threading.Condition"})

_MAX_CHASE = 5  # re-export hops followed before giving up


def module_key(path: str) -> str:
    """Canonical path → dotted module key: `ops/engine.py` → `ops.engine`,
    `runtime/__init__.py` → `runtime`."""
    p = canonical_path(path)
    if p.endswith(".py"):
        p = p[:-3]
    parts = [x for x in p.split("/") if x]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def strip_package(dotted: str) -> str:
    """`etl_tpu.ops.engine` → `ops.engine` (project-root names)."""
    if dotted == "etl_tpu":
        return ""
    if dotted.startswith("etl_tpu."):
        return dotted[len("etl_tpu."):]
    return dotted


class CallSite:
    """One `Call` node inside a function body."""

    __slots__ = ("node", "lexical", "resolved", "external", "awaited")

    def __init__(self, node: ast.Call, lexical: "str | None",
                 awaited: bool):
        self.node = node
        self.lexical = lexical  # dotted source text, e.g. "eng.decode"
        self.resolved: "FunctionInfo | None" = None  # project target
        self.external: "str | None" = None  # qualified external name
        self.awaited = awaited

    @property
    def line(self) -> int:
        return self.node.lineno

    @property
    def col(self) -> int:
        return self.node.col_offset + 1


class FunctionInfo:
    """One def/async-def (or a lambda bound to a simple name)."""

    __slots__ = ("module", "qualname", "node", "is_async", "class_name",
                 "parent", "nested", "calls", "decorators",
                 "lex_decorators", "is_hot", "is_dispatch")

    def __init__(self, module: "ModuleInfo", qualname: str, node,
                 is_async: bool, class_name: "str | None",
                 parent: "FunctionInfo | None"):
        self.module = module
        self.qualname = qualname
        self.node = node
        self.is_async = is_async
        self.class_name = class_name
        self.parent = parent
        self.nested: dict[str, FunctionInfo] = {}
        self.calls: list[CallSite] = []
        self.decorators: set[str] = set()  # resolved terminal names
        self.lex_decorators: set[str] = set()  # as written in source
        self.is_hot = False
        self.is_dispatch = False

    @property
    def line(self) -> int:
        return self.node.lineno

    @property
    def label(self) -> str:
        """Display name for chains: `path::qualname` only when ambiguity
        needs it; chains render qualnames (module given by chain_sites)."""
        return self.qualname

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<fn {self.module.path}::{self.qualname}>"


class ClassInfo:
    __slots__ = ("module", "name", "node", "methods", "bases",
                 "lock_attrs", "thread_lock_attrs", "lock_getters")

    def __init__(self, module: "ModuleInfo", name: str, node: ast.ClassDef):
        self.module = module
        self.name = name
        self.node = node
        self.methods: dict[str, FunctionInfo] = {}
        self.bases: list[str] = [d for d in
                                 (dotted_name(b) for b in node.bases)
                                 if d is not None]
        self.lock_attrs: set[str] = set()  # self.X = asyncio.Lock()
        self.thread_lock_attrs: set[str] = set()
        self.lock_getters: set[str] = set()  # methods returning a Lock


class ModuleInfo:
    __slots__ = ("path", "key", "tree", "source", "imports", "top",
                 "classes", "functions", "module_locks",
                 "module_thread_locks", "donating")

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = canonical_path(path)
        self.key = module_key(path)
        self.source = source
        self.tree = tree
        #: local alias -> qualified dotted target. Project targets are
        #: package-stripped (`ops.engine`, `analysis.annotations.hot_loop`);
        #: external targets keep their import name (`numpy`, `time.sleep`).
        self.imports: dict[str, str] = {}
        self.top: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}  # all, incl. nested
        self.module_locks: set[str] = set()
        self.module_thread_locks: set[str] = set()
        #: name -> donated positional indices, for names bound to
        #: `jax.jit(..., donate_argnums=...)` at module level
        self.donating: dict[str, tuple[int, ...]] = {}


class Project:
    """All scanned modules + the resolved call graph."""

    def __init__(self):
        self.modules: dict[str, ModuleInfo] = {}  # by canonical path
        self.by_key: dict[str, ModuleInfo] = {}

    # -- construction --------------------------------------------------------

    @classmethod
    def build(cls, sources: "list[tuple[str, str, ast.Module]]") -> "Project":
        """`sources` = (rel_path, source, parsed tree) triples."""
        proj = cls()
        for path, source, tree in sources:
            m = ModuleInfo(path, source, tree)
            proj.modules[m.path] = m
            # first module wins a key collision (e.g. two fixture trees
            # with the same layout scanned together): determinism over
            # completeness, and real trees never collide
            proj.by_key.setdefault(m.key, m)
        for m in proj.modules.values():
            proj._collect_imports(m)
            proj._collect_defs(m)
        for m in proj.modules.values():
            proj._collect_lock_tables(m)
            proj._collect_donating(m)
        for m in proj.modules.values():
            for fn in m.functions.values():
                proj._collect_calls(fn)
                proj._resolve_decorators(fn)
        return proj

    def _collect_imports(self, m: ModuleInfo) -> None:
        pkg_parts = m.key.split(".")[:-1] if m.key else []
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.name
                    asname = alias.asname or name.split(".")[0]
                    if alias.asname is None and "." in name:
                        # `import a.b.c` binds `a`; dotted access chases
                        # from the root name
                        m.imports[asname] = strip_package(
                            name.split(".")[0])
                    else:
                        m.imports[asname] = strip_package(name)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)] \
                        if node.level - 1 <= len(pkg_parts) else []
                    prefix = ".".join(base)
                    if node.module:
                        prefix = f"{prefix}.{node.module}" if prefix \
                            else node.module
                else:
                    prefix = strip_package(node.module or "")
                for alias in node.names:
                    if alias.name == "*":
                        continue  # star imports: unresolvable, skip
                    asname = alias.asname or alias.name
                    m.imports[asname] = f"{prefix}.{alias.name}" \
                        if prefix else alias.name

    def _collect_defs(self, m: ModuleInfo) -> None:
        def walk_body(body, class_name, parent, prefix):
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{node.name}"
                    fn = FunctionInfo(m, qual,
                                      node, isinstance(
                                          node, ast.AsyncFunctionDef),
                                      class_name, parent)
                    m.functions[qual] = fn
                    if parent is not None:
                        parent.nested[node.name] = fn
                    elif class_name is None:
                        m.top[node.name] = fn
                    else:
                        m.classes[class_name].methods[node.name] = fn
                    walk_body(node.body, None, fn, f"{qual}.")
                elif isinstance(node, ast.ClassDef):
                    if parent is None and class_name is None:
                        m.classes[node.name] = ClassInfo(m, node.name, node)
                        walk_body(node.body, node.name, None,
                                  f"{node.name}.")
                    else:
                        # nested class: methods tracked under the quali-
                        # fied name but not self-resolvable (rare)
                        walk_body(node.body, None, parent,
                                  f"{prefix}{node.name}.")
                elif isinstance(node, ast.Assign) and parent is None \
                        and class_name is None \
                        and isinstance(node.value, ast.Lambda) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    name = node.targets[0].id
                    fn = FunctionInfo(m, name, node.value, False,
                                      None, None)
                    m.functions.setdefault(name, fn)
                    m.top.setdefault(name, fn)
                else:
                    for sub in ast.iter_child_nodes(node):
                        if isinstance(sub, (ast.stmt,)):
                            walk_body([sub], class_name, parent, prefix)

        walk_body(m.tree.body, None, None, "")
        # lambdas bound inside functions: resolvable as locals
        for fn in list(m.functions.values()):
            body = getattr(fn.node, "body", None)
            if not isinstance(body, list):
                continue
            for node in body:
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Lambda) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    name = node.targets[0].id
                    lam = FunctionInfo(
                        m, f"{fn.qualname}.<lambda:{name}>",
                        node.value, False, fn.class_name, fn)
                    m.functions[lam.qualname] = lam
                    fn.nested.setdefault(name, lam)

    def _ctor_name(self, m: ModuleInfo, call: ast.Call) -> "str | None":
        """Qualified name of a constructor-ish call, import-resolved."""
        d = dotted_name(call.func)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        target = m.imports.get(head)
        if target is not None:
            return f"{target}.{rest}" if rest else target
        return d

    def _collect_lock_tables(self, m: ModuleInfo) -> None:
        def is_lock_ctor(node, ctors) -> bool:
            return (isinstance(node, ast.Call)
                    and (self._ctor_name(m, node) or "") in ctors)

        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    if is_lock_ctor(node.value, _LOCK_CTORS):
                        m.module_locks.add(tgt.id)
                    elif is_lock_ctor(node.value, _THREAD_LOCK_CTORS):
                        m.module_thread_locks.add(tgt.id)
                elif isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    cls = self._class_of_assign(m, node)
                    if cls is None:
                        continue
                    if is_lock_ctor(node.value, _LOCK_CTORS):
                        cls.lock_attrs.add(tgt.attr)
                    elif is_lock_ctor(node.value, _THREAD_LOCK_CTORS):
                        cls.thread_lock_attrs.add(tgt.attr)
        # lock getters: methods whose return expression CONTAINS an
        # asyncio lock constructor (`return self._locks.setdefault(k,
        # asyncio.Lock())` — the per-key lock factory idiom)
        for cls in m.classes.values():
            for name, fn in cls.methods.items():
                for node in ast.walk(fn.node):
                    if isinstance(node, ast.Return) \
                            and node.value is not None \
                            and any(is_lock_ctor(c, _LOCK_CTORS)
                                    for c in ast.walk(node.value)
                                    if isinstance(c, ast.Call)):
                        cls.lock_getters.add(name)
                        break

    def _class_of_assign(self, m: ModuleInfo,
                         node: ast.Assign) -> "ClassInfo | None":
        # attribute assigns live inside methods; find the class whose
        # span contains the assignment (top-level classes only)
        for cls in m.classes.values():
            if cls.node.lineno <= node.lineno \
                    <= (cls.node.end_lineno or cls.node.lineno):
                return cls
        return None

    def _collect_donating(self, m: ModuleInfo) -> None:
        for node in m.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                pos = donated_argnums(m, node.value, self)
                if pos is not None:
                    m.donating[node.targets[0].id] = pos

    def _collect_calls(self, fn: FunctionInfo) -> None:
        body = getattr(fn.node, "body", None)
        nodes = body if isinstance(body, list) else [body]
        stack = [(n, False) for n in nodes]
        while stack:
            node, awaited = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # nested callables own their call sites
            if isinstance(node, ast.Call):
                site = CallSite(node, dotted_name(node.func), awaited)
                self._resolve_call(fn, site)
                fn.calls.append(site)
            # `await asyncio.wait_for(helper(), 5)` executes helper()'s
            # coroutine — the wrapper forwards the await, so argument
            # call sites stay "awaited" through it (the repo's own
            # unbounded-await rule TELLS authors to wrap awaits this
            # way; the edge must not vanish when they comply)
            propagate = isinstance(node, ast.Await) or (
                awaited and isinstance(node, ast.Call)
                and terminal_name(node.func) in AWAIT_FORWARDERS)
            stack.extend((c, propagate)
                         for c in ast.iter_child_nodes(node))
        fn.calls.sort(key=lambda s: (s.line, s.col))

    def _resolve_decorators(self, fn: FunctionInfo) -> None:
        for dec in getattr(fn.node, "decorator_list", []):
            target = dec.func if isinstance(dec, ast.Call) else dec
            d = dotted_name(target)
            if d is None:
                continue
            fn.lex_decorators.add(d.rsplit(".", 1)[-1])
            head, _, rest = d.partition(".")
            imported = fn.module.imports.get(head)
            resolved = (f"{imported}.{rest}" if rest else imported) \
                if imported is not None else d
            fn.decorators.add(resolved.rsplit(".", 1)[-1])
        fn.is_hot = HOT_DECORATOR in fn.decorators
        fn.is_dispatch = DISPATCH_DECORATOR in fn.decorators

    # -- resolution ----------------------------------------------------------

    def _lookup_symbol(self, modkey: str, parts: list[str],
                       depth: int = 0) -> "FunctionInfo | None":
        """Resolve `parts` inside project module `modkey`."""
        m = self.by_key.get(modkey)
        if m is None or not parts or depth > _MAX_CHASE:
            return None
        head, rest = parts[0], parts[1:]
        if not rest:
            fn = m.top.get(head)
            if fn is not None:
                return fn
            cls = m.classes.get(head)
            if cls is not None:
                return cls.methods.get("__init__")
            # re-exported name (`from .x import f` then callers do m.f())
            target = m.imports.get(head)
            if target is not None:
                return self._resolve_qualified(target, depth + 1)
            return None
        cls = m.classes.get(head)
        if cls is not None and len(rest) == 1:
            return cls.methods.get(rest[0])
        target = m.imports.get(head)
        if target is not None:
            return self._resolve_qualified(
                f"{target}.{'.'.join(rest)}", depth + 1)
        return None

    def _resolve_qualified(self, qualified: str,
                           depth: int = 0) -> "FunctionInfo | None":
        """Resolve a package-stripped dotted name against project
        modules, trying the longest module-key prefix first."""
        parts = qualified.split(".")
        for i in range(len(parts), 0, -1):
            key = ".".join(parts[:i])
            if key in self.by_key:
                if i == len(parts):
                    return None  # names a module, not a callable
                return self._lookup_symbol(key, parts[i:], depth)
        return None

    def resolve_class(self, m: ModuleInfo, name: str) -> "ClassInfo | None":
        """A class name (possibly dotted through imports) → ClassInfo."""
        head, _, rest = name.partition(".")
        cls = m.classes.get(head)
        if cls is not None and not rest:
            return cls
        target = m.imports.get(head)
        if target is None:
            return None
        qualified = f"{target}.{rest}" if rest else target
        parts = qualified.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = self.by_key.get(".".join(parts[:i]))
            if mod is not None and len(parts) - i == 1:
                return mod.classes.get(parts[-1])
        return None

    def resolve_method(self, cls: ClassInfo, name: str,
                       depth: int = 0) -> "FunctionInfo | None":
        """`self.name` in `cls`, walking project-resolvable bases."""
        fn = cls.methods.get(name)
        if fn is not None or depth > _MAX_CHASE:
            return fn
        for base in cls.bases:
            parent = self.resolve_class(cls.module, base)
            if parent is not None:
                fn = self.resolve_method(parent, name, depth + 1)
                if fn is not None:
                    return fn
        return None

    def _resolve_call(self, fn: FunctionInfo, site: CallSite) -> None:
        d = site.lexical
        if d is None:
            return
        m = fn.module
        head, _, rest = d.partition(".")
        # nested defs / lambda locals of enclosing functions
        if not rest:
            scope = fn
            while scope is not None:
                if head in scope.nested:
                    site.resolved = scope.nested[head]
                    return
                scope = scope.parent
        # self/cls method
        if head in ("self", "cls") and rest and "." not in rest:
            cls = m.classes.get(fn.class_name or "")
            if cls is not None:
                site.resolved = self.resolve_method(cls, rest)
            return
        # module-level def / class constructor / ClassName.method
        if not rest and head in m.top:
            site.resolved = m.top[head]
            return
        cls = m.classes.get(head)
        if cls is not None:
            site.resolved = cls.methods.get(rest) if rest and "." not in rest \
                else (cls.methods.get("__init__") if not rest else None)
            return
        # imports
        target = m.imports.get(head)
        if target is not None:
            qualified = f"{target}.{rest}" if rest else target
            resolved = self._resolve_qualified(qualified)
            if resolved is not None:
                site.resolved = resolved
            else:
                site.external = qualified
            return
        # unknown receiver: leave lexical-only

    # -- introspection -------------------------------------------------------

    def iter_functions(self):
        for path in sorted(self.modules):
            m = self.modules[path]
            for qual in sorted(m.functions):
                yield m.functions[qual]

    def edges(self) -> "list[tuple[str, str]]":
        """Resolved caller → callee pairs (for `--callgraph`)."""
        out = []
        for fn in self.iter_functions():
            src = f"{fn.module.path}::{fn.qualname}"
            for site in fn.calls:
                if site.resolved is not None:
                    out.append((src, f"{site.resolved.module.path}::"
                                     f"{site.resolved.qualname}"))
        return sorted(set(out))


def donated_argnums(m: ModuleInfo, value: ast.AST,
                    proj: "Project | None" = None) -> "tuple[int, ...] | None":
    """Donated positional indices when `value` is a
    `jax.jit(..., donate_argnums=...)` call (import-aliased `jit` counts),
    else None."""
    if not isinstance(value, ast.Call):
        return None
    d = dotted_name(value.func)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    target = m.imports.get(head)
    qualified = (f"{target}.{rest}" if rest else target) \
        if target is not None else d
    if qualified not in ("jax.jit", "jit"):
        return None
    for kw in value.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = tuple(e.value for e in v.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, int))
                return out or None
    return None
