"""Scope/context-tracking AST walk the lint rules plug into.

One traversal per module serves every rule. The visitor maintains the
lexical facts rules key on:

  - `in_async`: inside an `async def` body — reset by a nested sync
    `def`/`lambda`, because that is exactly how blocking work is legally
    routed off the loop (`run_in_executor(None, nested_fn)`);
  - `in_hot_loop`: inside a function decorated `@hot_loop` (inherited by
    nested defs — a closure defined in a hot loop runs in the hot loop);
  - `scope`: dotted qualname for fingerprints;
  - ancestor stack: lets a rule inspect enclosing statements — e.g.
    CancellationSwallow finds the governing `try` and enclosing function
    to recognize the cancel-then-drain idiom;
  - inline suppressions: `# etl-lint: ignore[rule-a,rule-b]` on the
    finding's line (or on the first line of its enclosing multi-line
    statement) drops the finding at collection time; usage is tracked
    so `--check-baseline` can flag ignores that suppress nothing.

Rules subclass `Rule` and receive `on_*` callbacks with the visitor as
context. They report via `ctx.report(...)`, which applies suppressions.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from typing import Callable

from .findings import Finding, canonical_path

_IGNORE_RE = re.compile(r"#\s*etl-lint:\s*ignore\[([a-z0-9_,\s-]+)\]")

#: compound statements: only their HEADER lines (condition / with-items /
#: signature) belong to the statement for suppression purposes — a
#: suppression on `with ...:` must not blanket the whole body
_COMPOUND_STMTS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                   ast.For, ast.AsyncFor, ast.While, ast.If, ast.With,
                   ast.AsyncWith, ast.Try)


class Suppressions:
    """One module's inline `# etl-lint: ignore[...]` comments.

    Three jobs:
      - parse COMMENT tokens only (a docstring or log string QUOTING the
        ignore syntax must not suppress findings on its line);
      - map continuation lines of a multi-line statement back to the
        statement's first line, so a suppression on the line a human
        reads as "the statement" covers findings the AST anchors on a
        continuation line (a nested call's own lineno);
      - track which ignores actually suppressed something, so
        `--check-baseline` can flag stale ones.
    """

    def __init__(self, source: str):
        #: comment line -> set of rule names (or "all")
        self.by_line: dict[int, set[str]] = {}
        #: continuation line -> first line of its enclosing statement
        self._stmt_first: dict[int, int] = {}
        #: (comment line, rule) pairs that suppressed >=1 finding
        self._used: set[tuple[int, str]] = set()
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(source).readline):
                if tok.type != tokenize.COMMENT:
                    continue
                m = _IGNORE_RE.search(tok.string)
                if m:
                    self.by_line[tok.start[0]] = {
                        r.strip() for r in m.group(1).split(",")
                        if r.strip()}
        except (tokenize.TokenError, IndentationError):
            pass  # unparseable source fails in ast.parse anyway

    def attach_tree(self, tree: ast.Module) -> None:
        """Build the continuation-line map. Simple statements span their
        full extent; compound statements contribute only their header
        (first line through the line before their first body statement)."""
        for node in ast.walk(tree):
            if not isinstance(node, ast.stmt):
                continue
            end = getattr(node, "end_lineno", None) or node.lineno
            if isinstance(node, _COMPOUND_STMTS):
                body = getattr(node, "body", None)
                if body:
                    end = min(end, body[0].lineno - 1)
            for line in range(node.lineno + 1, end + 1):
                # innermost statement wins: walk yields outer before
                # inner, so later (inner) writes override
                self._stmt_first[line] = node.lineno

    def _match_line(self, rule: str, line: int) -> "int | None":
        rules = self.by_line.get(line)
        if rules is not None and (rule in rules or "all" in rules):
            return line
        return None

    def suppresses(self, rule: str, line: int) -> bool:
        """True (and marks the ignore used) when an ignore on `line` or
        on the first line of `line`'s enclosing statement names `rule`."""
        hit = self._match_line(rule, line)
        if hit is None:
            first = self._stmt_first.get(line)
            if first is not None:
                hit = self._match_line(rule, first)
        if hit is None:
            return False
        named = self.by_line[hit]
        self._used.add((hit, rule if rule in named else "all"))
        return True

    def unused(self) -> list[tuple[int, str]]:
        """(line, rule) of every ignore entry that suppressed nothing —
        sorted, deterministic."""
        out = []
        for line, rules in self.by_line.items():
            for rule in rules:
                if (line, rule) not in self._used:
                    out.append((line, rule))
        return sorted(out)

#: decorator names that mark a hot-path function (matched on the
#: terminal name so `@hot_loop`, `@annotations.hot_loop`, and
#: `@analysis.hot_loop` all count)
HOT_LOOP_DECORATORS = frozenset({"hot_loop"})

#: decorator marking the decode pipeline's dispatch stage
#: (annotations.dispatch_stage): a hot-loop function where host→device
#: UPLOADS are the point — the hot-loop-host-transfer rule permits
#: `jax.device_put` there while still forbidding fetch-side transfers
DISPATCH_STAGE_DECORATORS = frozenset({"dispatch_stage"})

#: decorator marking the admission scheduler's grant path
#: (annotations.admission_path): the admission-blocking-fetch rule
#: forbids ALL device traffic there — a fetch under the scheduler lock
#: head-of-line-blocks every tenant's admission. Same sanctioning
#: machinery as @dispatch_stage: a lexical frame flag inherited by
#: nested defs/lambdas (lag/weight providers defined inline).
ADMISSION_PATH_DECORATORS = frozenset({"admission_path"})

#: decorator marking shard-scoped replication code
#: (annotations.shard_scoped): the cross-shard-table-access rule forbids
#: unfiltered full-table-list store reads there — against a shared store
#: they return every shard's tables. Same sanctioning machinery as
#: @dispatch_stage: a lexical frame flag inherited by nested
#: defs/lambdas.
SHARD_SCOPED_DECORATORS = frozenset({"shard_scoped"})

#: decorator marking destination flush/dispatch paths
#: (annotations.flush_path): the inline-durability-wait rule forbids a
#: bare `await ack.wait_durable()` there — the bounded ack window
#: (runtime/ack_window.py) owns durability waits, and an inline wait
#: re-serializes the pipeline to one ack round-trip per batch. Same
#: sanctioning machinery as @dispatch_stage: a lexical frame flag
#: inherited by nested defs/lambdas (the flush submit closures).
FLUSH_PATH_DECORATORS = frozenset({"flush_path"})

#: decorator marking transactional-commit destination entry points
#: (annotations.transactional_commit): the seam through which a
#: destination atomically records the acked WAL coordinate range
#: alongside the data (docs/destinations.md). The
#: uncoordinated-transactional-write rule requires a marked function
#: that performs CDC writes to consult its commit-range parameter —
#: data landing without its coordinates silently downgrades the sink
#: to at-least-once. Same sanctioning machinery as @dispatch_stage: a
#: lexical frame flag inherited by nested defs/lambdas (the retried
#: write closures).
TRANSACTIONAL_COMMIT_DECORATORS = frozenset({"transactional_commit"})

#: decorator marking the autoscaling control loop's decision path
#: (annotations.control_loop): the control-loop-blocking-io rule forbids
#: blocking I/O and ALL device traffic there — the policy must stay a
#: pure function of (signal history, config). Same sanctioning machinery
#: as @dispatch_stage: a lexical frame flag inherited by nested
#: defs/lambdas (inline capacity estimators, comparator keys).
CONTROL_LOOP_DECORATORS = frozenset({"control_loop"})


def dotted_name(node: ast.AST) -> str | None:
    """`a.b.c` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> str | None:
    """Last component of a call target: `loop.create_task` -> create_task."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _contains_raise(node: ast.AST) -> bool:
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue  # prune nested callables, keep walking siblings
        if isinstance(child, ast.Raise) or _contains_raise(child):
            return True
    return False


def has_raise(handler: ast.ExceptHandler) -> bool:
    """Any `raise` lexically inside the handler body (nested defs don't
    count — a raise inside a closure doesn't re-raise the handler's
    exception)."""
    return any(isinstance(stmt, ast.Raise) or _contains_raise(stmt)
               for stmt in handler.body)


def handler_type_names(handler: ast.ExceptHandler) -> tuple[str, ...]:
    """Terminal names of the caught types; `("<bare>",)` for `except:`."""
    t = handler.type
    if t is None:
        return ("<bare>",)
    nodes = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for n in nodes:
        name = terminal_name(n)
        out.append(name if name is not None else "<unknown>")
    return tuple(out)


class Rule:
    """Base class: override the hooks a rule cares about."""

    name: str = ""

    def applies_to(self, rel_path: str) -> bool:
        return True

    def before_module(self, ctx: "LintContext", tree: ast.Module) -> None:
        """One pre-pass hook (e.g. collect locally-defined async names)."""

    def on_call(self, ctx: "LintContext", node: ast.Call) -> None:
        pass

    def on_expr_statement(self, ctx: "LintContext", node: ast.Expr) -> None:
        pass

    def on_except_handler(self, ctx: "LintContext",
                          node: ast.ExceptHandler) -> None:
        pass

    def on_function(self, ctx: "LintContext",
                    node: "ast.FunctionDef | ast.AsyncFunctionDef") -> None:
        pass

    def on_while(self, ctx: "LintContext", node: ast.While) -> None:
        pass


class _Frame:
    __slots__ = ("name", "is_async", "is_hot", "is_dispatch",
                 "is_admission", "is_shard_scoped", "is_control",
                 "is_flush", "is_transactional")

    def __init__(self, name: str, is_async: bool, is_hot: bool,
                 is_dispatch: bool = False, is_admission: bool = False,
                 is_shard_scoped: bool = False, is_control: bool = False,
                 is_flush: bool = False, is_transactional: bool = False):
        self.name = name
        self.is_async = is_async
        self.is_hot = is_hot
        self.is_dispatch = is_dispatch
        self.is_admission = is_admission
        self.is_shard_scoped = is_shard_scoped
        self.is_control = is_control
        self.is_flush = is_flush
        self.is_transactional = is_transactional


class LintContext(ast.NodeVisitor):
    """One module's traversal state, shared by every active rule."""

    def __init__(self, source: str, rel_path: str, rules: list[Rule],
                 suppressions: "Suppressions | None" = None):
        self.rel_path = canonical_path(rel_path)
        self.source = source
        self.rules = [r for r in rules if r.applies_to(self.rel_path)]
        self.findings: list[Finding] = []
        self.suppressions = suppressions if suppressions is not None \
            else Suppressions(source)
        # lexical scope stacks
        self._frames: list[_Frame] = []
        self._class_stack: list[str] = []
        self._ancestors: list[ast.AST] = []

    # -- facts rules query ---------------------------------------------------

    @property
    def in_async(self) -> bool:
        return bool(self._frames) and self._frames[-1].is_async

    @property
    def in_hot_loop(self) -> bool:
        return bool(self._frames) and self._frames[-1].is_hot

    @property
    def in_dispatch_stage(self) -> bool:
        return bool(self._frames) and self._frames[-1].is_dispatch

    @property
    def in_admission_path(self) -> bool:
        return bool(self._frames) and self._frames[-1].is_admission

    @property
    def in_shard_scoped(self) -> bool:
        return bool(self._frames) and self._frames[-1].is_shard_scoped

    @property
    def in_control_loop(self) -> bool:
        return bool(self._frames) and self._frames[-1].is_control

    @property
    def in_flush_path(self) -> bool:
        return bool(self._frames) and self._frames[-1].is_flush

    @property
    def in_transactional_commit(self) -> bool:
        return bool(self._frames) and self._frames[-1].is_transactional

    @property
    def current_class(self) -> "str | None":
        return self._class_stack[-1] if self._class_stack else None

    @property
    def scope(self) -> str:
        parts = list(self._class_stack)
        parts += [f.name for f in self._frames]
        return ".".join(parts) if parts else "<module>"

    def ancestors(self) -> list[ast.AST]:
        """Enclosing nodes, innermost last (excludes the current node)."""
        return self._ancestors

    def report(self, rule: str, node: ast.AST, detail: str,
               message: str) -> None:
        line = getattr(node, "lineno", 0)
        if self.suppressions.suppresses(rule, line):
            return
        self.findings.append(Finding(
            rule=rule, path=self.rel_path, line=line,
            col=getattr(node, "col_offset", 0) + 1,
            scope=self.scope, detail=detail, message=message))

    # -- traversal -----------------------------------------------------------

    def run(self, tree: ast.Module) -> list[Finding]:
        self.suppressions.attach_tree(tree)
        for rule in self.rules:
            rule.before_module(self, tree)
        self.visit(tree)
        self.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return self.findings

    def generic_visit(self, node: ast.AST) -> None:
        self._ancestors.append(node)
        try:
            super().generic_visit(node)
        finally:
            self._ancestors.pop()

    def _visit_function(self, node, is_async: bool) -> None:
        decorators = {terminal_name(d.func if isinstance(d, ast.Call) else d)
                      for d in node.decorator_list}
        is_hot = bool(decorators & HOT_LOOP_DECORATORS) or self.in_hot_loop
        is_dispatch = bool(decorators & DISPATCH_STAGE_DECORATORS) \
            or self.in_dispatch_stage
        is_admission = bool(decorators & ADMISSION_PATH_DECORATORS) \
            or self.in_admission_path
        is_shard_scoped = bool(decorators & SHARD_SCOPED_DECORATORS) \
            or self.in_shard_scoped
        is_control = bool(decorators & CONTROL_LOOP_DECORATORS) \
            or self.in_control_loop
        is_flush = bool(decorators & FLUSH_PATH_DECORATORS) \
            or self.in_flush_path
        is_transactional = bool(
            decorators & TRANSACTIONAL_COMMIT_DECORATORS) \
            or self.in_transactional_commit
        for rule in self.rules:
            rule.on_function(self, node)
        # decorators, default args, and annotations execute ONCE at def
        # time in the ENCLOSING scope — visiting them inside the new
        # frame would misclassify `@deco(time.sleep(0))` or
        # `async def f(x=open(p))` as running on the event loop
        self._ancestors.append(node)
        try:
            for dec in node.decorator_list:
                self.visit(dec)
            self.visit(node.args)
            if node.returns is not None:
                self.visit(node.returns)
            self._frames.append(_Frame(node.name, is_async, is_hot,
                                       is_dispatch, is_admission,
                                       is_shard_scoped, is_control,
                                       is_flush, is_transactional))
            try:
                for stmt in node.body:
                    self.visit(stmt)
            finally:
                self._frames.pop()
        finally:
            self._ancestors.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_function(node, is_async=False)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_function(node, is_async=True)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # a lambda body is a sync callable: blocking calls inside it are
        # (usually) executor-routed; hot-loop status still inherits.
        # Defaults evaluate at def time in the enclosing scope.
        self._ancestors.append(node)
        try:
            self.visit(node.args)
            self._frames.append(_Frame("<lambda>", False, self.in_hot_loop,
                                       self.in_dispatch_stage,
                                       self.in_admission_path,
                                       self.in_shard_scoped,
                                       self.in_control_loop,
                                       self.in_flush_path))
            try:
                self.visit(node.body)
            finally:
                self._frames.pop()
        finally:
            self._ancestors.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self._class_stack.pop()

    def visit_Call(self, node: ast.Call) -> None:
        for rule in self.rules:
            rule.on_call(self, node)
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr) -> None:
        for rule in self.rules:
            rule.on_expr_statement(self, node)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        for rule in self.rules:
            rule.on_except_handler(self, node)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        for rule in self.rules:
            rule.on_while(self, node)
        self.generic_visit(node)


def collect_async_defs(
        tree: ast.Module) -> tuple[set[str], dict[str, set[str]]]:
    """(module-level-resolvable async def names, async method names keyed
    by enclosing class name).

    Plain names resolve bare calls `foo()`; method names resolve
    `self.foo()` / `cls.foo()` receivers only, and only within the SAME
    class — a flat module-wide method set would false-positive a sync
    `self.flush()` because some unrelated class defines `async def
    flush` (common names like close/stop/flush make that likely).
    """
    plain: set[str] = set()
    methods: dict[str, set[str]] = {}

    def walk(node: ast.AST, class_name: "str | None") -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.AsyncFunctionDef):
                if class_name is None:
                    plain.add(child.name)
                else:
                    methods.setdefault(class_name, set()).add(child.name)
                walk(child, None)
            elif isinstance(child, ast.ClassDef):
                walk(child, child.name)
            elif isinstance(child, ast.FunctionDef):
                walk(child, None)
            else:
                walk(child, class_name)

    walk(tree, None)
    return plain, methods


Visitor = Callable[[str, str, list[Rule]], list[Finding]]


def lint_module(source: str, rel_path: str, rules: list[Rule],
                tree: "ast.Module | None" = None,
                suppressions: "Suppressions | None" = None) -> list[Finding]:
    if tree is None:
        tree = ast.parse(source, filename=rel_path)
    return LintContext(source, rel_path, rules, suppressions).run(tree)
