"""Execution-domain inference for the concurrency tier.

The runtime spans five execution domains, and every concurrency rule
starts from knowing which of them can reach a given function:

  loop        — the asyncio event loop: every `async def`, plus every
                sync function a loop task calls inline.
  worker      — a dedicated `threading.Thread(target=…)`: the decode
                pipeline worker, the bg-compile threads.
  executor    — `loop.run_in_executor(…)` / `asyncio.to_thread(…)`
                offloads: pool threads running one callable.
  sweep       — supervision-owned threads (a thread spawned from a
                `supervision/` module): liveness sweeps, monitors.
  coordinator — out-of-process control loops (fleet/autoscale/shard)
                acting on shared state THROUGH the StateStore; rooted
                at `@control_loop` ticks and `@domain("coordinator")`
                pins, since the spawning process manager is outside
                the scanned tree.

Inference propagates from roots along RESOLVED call edges, exactly the
edge semantics of contexts.py: a call into a sync project function
executes in the caller's domain; a call into an async function runs in
the caller's domain only when awaited; function REFERENCES are never
edges — handing a callable to `Thread(target=…)`/`to_thread` does not
leak the spawner's domain into the target, it roots the target in the
spawned domain instead. Spawn targets resolve through
`functools.partial` wrappers, and INLINE lambda targets — which the
callgraph deliberately leaves unowned — get a synthesized FunctionInfo
here so the lambda's body propagates like any other function.

`@domain("…")` (analysis/annotations.py) pins a function: incoming
propagation of any OTHER domain is ignored (recorded as a conflict for
introspection), while the pinned domain still propagates outward.

Domains are not exclusive — a function called from a loop task and a
worker thread holds both, which is precisely the situation the race
rules exist to interrogate. Traversal is BFS with per-(function,
domain) visited marking, so each witness chain is shortest and
deterministic (call sites visit in (line, col) order, roots in
project iteration order); cycles — including cycles through a
thread-spawn edge back into the spawner — terminate via the visited
set.
"""

from __future__ import annotations

import ast
from collections import deque

from .callgraph import CallSite, FunctionInfo, Project
from .visitor import dotted_name, terminal_name

LOOP = "loop"
WORKER = "worker"
EXECUTOR = "executor"
SWEEP = "sweep"
COORDINATOR = "coordinator"

#: stable presentation/priority order (thread domains first so witness
#: selection for race findings prefers the thread side of a conflict)
DOMAIN_ORDER = (WORKER, EXECUTOR, SWEEP, LOOP, COORDINATOR)

#: domains whose code runs on a real OS thread other than the loop's —
#: a write reachable from one of these plus any second domain is a
#: cross-thread write and needs a THREAD lock (asyncio locks only
#: serialize loop tasks)
THREAD_DOMAINS = frozenset({WORKER, EXECUTOR, SWEEP})

#: chain-length bound: propagation beyond this depth adds no new
#: information (the repo's deepest real chains are < 15 hops)
_MAX_DEPTH = 25


class DomainInfo:
    """Why one function holds one domain: the witness chain proving it."""

    __slots__ = ("domain", "chain", "chain_sites", "origin")

    def __init__(self, domain: str, chain: tuple, chain_sites: tuple,
                 origin: str):
        self.domain = domain
        self.chain = chain  # qualnames, root first, this fn last
        self.chain_sites = chain_sites  # (path, line) per hop
        self.origin = origin  # human-readable root cause


class DomainMap:
    """fn → {domain → DomainInfo}, plus pins and override conflicts."""

    def __init__(self):
        self._info: dict[int, dict[str, DomainInfo]] = {}
        self._fns: dict[int, FunctionInfo] = {}
        #: id(fn) → pinned domain name (from @domain("…"))
        self.pins: dict[int, str] = {}
        #: (fn, pinned, rejected domain, witness chain) — incoming
        #: propagation a pin overrode; introspection only, not findings
        self.conflicts: list = []

    def of(self, fn: FunctionInfo) -> frozenset:
        return frozenset(self._info.get(id(fn), ()))

    def info(self, fn: FunctionInfo, domain: str) -> "DomainInfo | None":
        return self._info.get(id(fn), {}).get(domain)

    def witness(self, fn: FunctionInfo,
                prefer=DOMAIN_ORDER) -> "DomainInfo | None":
        """One deterministic witness, thread domains preferred."""
        held = self._info.get(id(fn), {})
        for d in prefer:
            if d in held:
                return held[d]
        return None

    def items(self):
        """(fn, sorted domain names) in stable project order."""
        fns = sorted(self._fns.values(),
                     key=lambda f: (f.module.path, f.qualname))
        for fn in fns:
            yield fn, sorted(self._info[id(fn)])

    def _record(self, fn: FunctionInfo, info: DomainInfo) -> bool:
        cur = self._info.setdefault(id(fn), {})
        if info.domain in cur:
            return False
        self._fns[id(fn)] = fn
        cur[info.domain] = info
        return True


def pinned_domain(fn: FunctionInfo) -> "str | None":
    """The @domain("…") pin on `fn`, decorator name alias-resolved."""
    for dec in getattr(fn.node, "decorator_list", []):
        if not isinstance(dec, ast.Call) or not dec.args:
            continue
        d = dotted_name(dec.func)
        if d is None:
            continue
        head, _, rest = d.partition(".")
        imported = fn.module.imports.get(head)
        resolved = ((f"{imported}.{rest}" if rest else imported)
                    if imported is not None else d)
        if resolved.rsplit(".", 1)[-1] != "domain":
            continue
        arg = dec.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
    return None


def is_handoff(fn: FunctionInfo) -> bool:
    """`fn` or an enclosing def carries @handoff (alias-resolved)."""
    scope = fn
    while scope is not None:
        if "handoff" in scope.decorators:
            return True
        scope = scope.parent
    return False


def _kwarg(call: ast.Call, name: str) -> "ast.AST | None":
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _posarg(call: ast.Call, idx: int) -> "ast.AST | None":
    return call.args[idx] if len(call.args) > idx else None


def _qualify(fn: FunctionInfo, expr: ast.AST) -> "str | None":
    """Import-resolved dotted name of `expr` (like Project._ctor_name)."""
    d = dotted_name(expr)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    target = fn.module.imports.get(head)
    if target is not None:
        return f"{target}.{rest}" if rest else target
    return d


def spawn_targets(fn: FunctionInfo):
    """(domain, target expr, spawn site) per spawn/offload call in `fn`.

    A thread spawned from a supervision/ module is the SWEEP domain —
    supervision owns those threads and their restart discipline; every
    other `threading.Thread` is WORKER. `run_in_executor`/`to_thread`
    targets are EXECUTOR regardless of spawner."""
    head = fn.module.path.split("/", 1)[0]
    thread_domain = SWEEP if head == "supervision" else WORKER
    for site in fn.calls:
        node = site.node
        if site.external == "threading.Thread":
            expr = _kwarg(node, "target") or _posarg(node, 1)
            if expr is not None:
                yield thread_domain, expr, site
            continue
        if site.external == "asyncio.to_thread":
            expr = _posarg(node, 0) or _kwarg(node, "func")
            if expr is not None:
                yield EXECUTOR, expr, site
            continue
        term = terminal_name(node.func)
        if term == "run_in_executor" and isinstance(node.func, ast.Attribute):
            # loop.run_in_executor(executor, fn, *args)
            expr = _posarg(node, 1)
            if expr is not None:
                yield EXECUTOR, expr, site


def _synthesize_lambda(project: Project, fn: FunctionInfo,
                       expr: ast.Lambda) -> FunctionInfo:
    """Inline lambda spawn targets get a FunctionInfo of their own —
    the callgraph leaves anonymous lambdas unowned, but a lambda handed
    to a thread IS the thread's entry point and its body's calls must
    propagate the spawned domain."""
    qual = f"{fn.qualname}.<lambda@{expr.lineno}:{expr.col_offset}>"
    m = fn.module
    existing = m.functions.get(qual)
    if existing is not None:
        return existing
    lam = FunctionInfo(m, qual, expr, False, fn.class_name, fn)
    m.functions[qual] = lam
    project._collect_calls(lam)
    return lam


def resolve_target(project: Project, fn: FunctionInfo,
                   expr: "ast.AST | None",
                   depth: int = 0) -> "FunctionInfo | None":
    """A spawn-target expression → the project function it names, or
    None for externals/unresolvable receivers. Unwraps
    `functools.partial(f, …)` to `f`; synthesizes inline lambdas."""
    if expr is None or depth > 3:
        return None
    if isinstance(expr, ast.Lambda):
        return _synthesize_lambda(project, fn, expr)
    if isinstance(expr, ast.Call):
        qualified = _qualify(fn, expr.func)
        if qualified in ("functools.partial", "partial"):
            return resolve_target(project, fn, _posarg(expr, 0), depth + 1)
        return None
    if dotted_name(expr) is None:
        return None
    fake = ast.Call(func=expr, args=[], keywords=[])
    ast.copy_location(fake, expr)
    site = CallSite(fake, dotted_name(expr), False)
    project._resolve_call(fn, site)
    return site.resolved


def infer_domains(project: Project) -> DomainMap:
    """Classify every reachable function into execution domains."""
    dm = DomainMap()
    roots: list = []  # (fn, domain, origin)

    # intrinsic roots: pins, async defs, coordinator ticks
    for fn in list(project.iter_functions()):
        pin = pinned_domain(fn)
        if pin is not None:
            dm.pins[id(fn)] = pin
            roots.append((fn, pin, "@domain pin"))
        if fn.is_async:
            roots.append((fn, LOOP, "async def"))
        if "control_loop" in fn.decorators:
            roots.append((fn, COORDINATOR, "@control_loop"))

    # spawn roots: walk a worklist so targets synthesized along the way
    # (inline lambdas) get THEIR spawn sites scanned too
    processed: set = set()
    queue = deque(project.iter_functions())
    while queue:
        fn = queue.popleft()
        if id(fn) in processed:
            continue
        processed.add(id(fn))
        for domain, expr, site in spawn_targets(fn):
            target = resolve_target(project, fn, expr)
            if target is None:
                continue
            origin = f"spawned at {fn.module.path}:{site.line}"
            roots.append((target, domain, origin))
            queue.append(target)

    # BFS propagation with witness chains
    work = deque()
    for fn, domain, origin in roots:
        work.append((fn, domain,
                     (fn.qualname,), ((fn.module.path, fn.line),), origin))
    while work:
        fn, domain, chain, sites, origin = work.popleft()
        pin = dm.pins.get(id(fn))
        if pin is not None and domain != pin:
            dm.conflicts.append((fn, pin, domain, chain))
            continue
        if not dm._record(fn, DomainInfo(domain, chain, sites, origin)):
            continue
        if len(chain) > _MAX_DEPTH:
            continue
        for site in fn.calls:
            callee = site.resolved
            if callee is None or callee is fn:
                continue
            if callee.is_async and not site.awaited:
                continue  # builds a coroutine; does not run here
            work.append((
                callee, domain, chain + (callee.qualname,),
                sites[:-1] + ((fn.module.path, site.line),
                              (callee.module.path, callee.line)),
                origin))
    return dm
