"""Finding model + stable fingerprints for baseline matching.

A fingerprint deliberately excludes the line number: baselines must
survive unrelated edits above a grandfathered finding. Identity is
(rule, canonical path, enclosing scope, normalized subject) — when the
same subject appears N times in one scope, the baseline stores a count
and only occurrences beyond it are violations.
"""

from __future__ import annotations

import dataclasses
from pathlib import PurePosixPath

#: path segments stripped from the front of fingerprint paths so the
#: same file fingerprints identically whether the scan root was the repo
#: root, the package dir, or a mirrored fixtures tree
_PACKAGE_SEGMENT = "etl_tpu"


def canonical_path(path: str) -> str:
    """Posix-normalize and strip everything up to the package segment:
    `/root/repo/etl_tpu/runtime/x.py` and `runtime/x.py` both canonicalize
    to `runtime/x.py` (fixture trees mirror the package layout)."""
    parts = PurePosixPath(path.replace("\\", "/")).parts
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] == _PACKAGE_SEGMENT:
            parts = parts[i + 1:]
            break
    return "/".join(p for p in parts if p not in (".", ""))


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # kebab-case rule name
    path: str  # canonical posix path (see canonical_path)
    line: int
    col: int
    scope: str  # dotted qualname of the enclosing def/class, or <module>
    detail: str  # normalized subject, e.g. "time.sleep" / "except Exception"
    message: str

    @property
    def fingerprint(self) -> str:
        return "|".join((self.rule, self.path, self.scope, self.detail))

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule}: "
                f"{self.message} [{self.scope}]")

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint
        return d
