"""Finding model + stable fingerprints for baseline matching.

A fingerprint deliberately excludes the line number: baselines must
survive unrelated edits above a grandfathered finding. Identity is
(rule, canonical path, enclosing scope, normalized subject) — when the
same subject appears N times in one scope, the baseline stores a count
and only occurrences beyond it are violations.

Interprocedural findings additionally carry the call chain that reaches
the sink (`chain`, entry first) and the per-hop source locations
(`chain_sites`, for `--explain`). Neither participates in the
fingerprint: identity stays (rule, entry module, entry scope, sink
subject), so renaming or re-routing an INTERMEDIATE helper — the most
common refactor — does not invalidate a baselined entry, and a direct
finding that later becomes transitive (the sink moved into a helper)
keeps matching the same grandfathered fingerprint.
"""

from __future__ import annotations

import dataclasses
from pathlib import PurePosixPath

#: path segments stripped from the front of fingerprint paths so the
#: same file fingerprints identically whether the scan root was the repo
#: root, the package dir, or a mirrored fixtures tree
_PACKAGE_SEGMENT = "etl_tpu"


def canonical_path(path: str) -> str:
    """Posix-normalize and strip everything up to the package segment:
    `/root/repo/etl_tpu/runtime/x.py` and `runtime/x.py` both canonicalize
    to `runtime/x.py` (fixture trees mirror the package layout)."""
    parts = PurePosixPath(path.replace("\\", "/")).parts
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] == _PACKAGE_SEGMENT:
            parts = parts[i + 1:]
            break
    return "/".join(p for p in parts if p not in (".", ""))


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # kebab-case rule name
    path: str  # canonical posix path (see canonical_path)
    line: int
    col: int
    scope: str  # dotted qualname of the enclosing def/class, or <module>
    detail: str  # normalized subject, e.g. "time.sleep" / "except Exception"
    message: str
    #: interprocedural call chain, entry first, e.g.
    #: ("Pipeline.start", "_bootstrap", "helper") — empty for lexical
    #: findings (the "chain" is the scope itself)
    chain: tuple = ()
    #: (canonical path, line) of each hop in `chain`, same order
    chain_sites: tuple = ()

    @property
    def fingerprint(self) -> str:
        return "|".join((self.rule, self.path, self.scope, self.detail))

    def chain_text(self) -> str:
        """`a → b → c: time.sleep` — the trace the finding proves."""
        if not self.chain:
            return f"{self.scope}: {self.detail}"
        return " → ".join(self.chain) + f": {self.detail}"

    def render(self) -> str:
        base = (f"{self.path}:{self.line}:{self.col}: {self.rule}: "
                f"{self.message} [{self.scope}]")
        if self.chain:
            base += f" [via {self.chain_text()}]"
        return base

    def explain(self) -> str:
        """Multi-line chain trace: one resolvable file:line per hop."""
        if not self.chain:
            return f"    at {self.path}:{self.line} in {self.scope}"
        lines = []
        for hop, (path, line) in zip(self.chain, self.chain_sites):
            lines.append(f"    {path}:{line}: {hop}")
        lines.append(f"    sink: {self.detail}")
        return "\n".join(lines)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["chain"] = list(self.chain)
        d["chain_sites"] = [list(s) for s in self.chain_sites]
        d["fingerprint"] = self.fingerprint
        return d
