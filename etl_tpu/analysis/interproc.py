"""Whole-program (interprocedural) analysis pass.

Runs after the per-module lexical pass over the same parsed trees.
Three upgraded rules and four new ones:

  - `blocking-call-in-async`, `device-sync-in-async`,
    `hot-loop-host-transfer` go TRANSITIVE: a sink anywhere in the call
    closure of an event-loop `async def` / `@hot_loop` function is
    reported with the full call chain (`a → b → c: time.sleep`). Wrapping
    the sink in a helper one file away no longer defeats the rule, and
    import aliasing (`from time import sleep`) is resolved — the hole
    annotations.py used to document is closed.
  - `arena-lease-leak` — a `StagingArenaPool` lease acquired on a path
    that can exit the function without `release()` (the static twin of
    chaos's `ARENA_POOL.outstanding` invariant).
  - `donated-buffer-use` — a buffer passed in a donated position of a
    `jax.jit(..., donate_argnums=...)` callable is read afterwards: the
    device owns that buffer now; the read sees poisoned memory on TPU.
  - `lock-held-across-await` — an `await` while an asyncio
    Lock/Semaphore is held, outside the sanctioned own-resource idiom
    (docs/CONCURRENCY.md); plus ANY await under a sync `threading.Lock`,
    which parks the whole event loop on a mutex.
  - `lock-order-inversion` — two locks acquired in opposite orders on
    different call paths (lock-set reasoning over the call graph).

Precision contract (documented in docs/static-analysis.md): transitive
sink sets are restricted to calls that DEFINITELY synchronize
(`np.asarray` on arbitrary host data stays lexical-only); receiver-typed
calls (`obj.m()` on unknown `obj`) are not traversed; escape of a lease
variable (passed/returned/stored) transfers ownership and ends tracking.

Findings fingerprint as (rule, entry module, entry scope, sink subject)
— stable under intermediate-helper renames — and anchor at the entry
function's own call site, which is also where an inline
`# etl-lint: ignore[...]` applies.
"""

from __future__ import annotations

import ast

from .callgraph import HOT_DECORATOR, Project, donated_argnums
from .cfg import CFG, EXC_EXIT, EXIT, dataflow_forward
from .contexts import async_entries, hot_entries, reach_from
from .findings import Finding
from .visitor import Suppressions, dotted_name, terminal_name

#: transitive sinks for device-sync/hot-loop rules: DEFINITE device
#: synchronization only. np.asarray/np.array are host-ambiguous (most
#: sync numpy helpers reachable from async code legitimately build host
#: arrays) and stay lexical-only — the documented precision trade.
DEVICE_SYNC_TRANSITIVE = frozenset({
    "jax.device_get", "jax.device_put", "jax.jit",
    "autotune.measure", "autotune.resolve_device_min_rows",
})
HOT_TRANSFER_TRANSITIVE = frozenset({
    "jax.device_get", "jax.device_put",
    # the jit-compiling probe moves 2x8 MiB over the link — reaching it
    # from a @hot_loop function is a per-batch transfer storm
    "autotune.measure", "autotune.resolve_device_min_rows",
})
SYNC_METHOD_SINKS = frozenset({"block_until_ready"})

#: project-function sinks (module path, qualname): hit when a call
#: resolves to the function itself no matter how it was imported/aliased
DEVICE_SYNC_PROJECT_SINKS = frozenset({
    ("ops/autotune.py", "measure"),
    ("ops/autotune.py", "resolve_device_min_rows"),
})

#: awaits sanctioned while holding a lock when the awaited call's
#: receiver chain is rooted at one of these (after unwrapping wait_for)
_AWAIT_WRAPPERS = frozenset({"wait_for", "shield"})

#: directories whose locks the await-holding rule polices (testing/ and
#: chaos/ doubles deliberately hold locks in ways production must not)
LOCK_RULE_SCOPES = ("runtime", "destinations", "postgres", "store",
                    "supervision", "api", "ops")


class ModuleUnit:
    """One module's inputs to the whole-program pass."""

    __slots__ = ("path", "source", "tree", "suppressions")

    def __init__(self, path: str, source: str, tree: ast.Module,
                 suppressions: Suppressions):
        self.path = path
        self.source = source
        self.tree = tree
        self.suppressions = suppressions


def analyze_interprocedural(units: "list[ModuleUnit]") -> list[Finding]:
    from .concurrency import analyze_concurrency  # deferred: imports us

    project = Project.build([(u.path, u.source, u.tree) for u in units])
    supp = {u.path: u.suppressions for u in units}
    findings: list[Finding] = []
    findings += _transitive_blocking(project, supp)
    findings += _transitive_device_sync(project, supp)
    findings += _transitive_hot_transfer(project, supp)
    findings += _arena_lease_leak(project, supp)
    findings += _donated_buffer_use(project, supp)
    findings += _lock_held_across_await(project, supp)
    findings += _lock_order_inversion(project, supp)
    findings += analyze_concurrency(project, supp)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule, f.detail))
    return findings


# -- shared helpers -----------------------------------------------------------


def _sink_subject(site, lexical_set, transitive_set, bare_set=frozenset(),
                  method_set=frozenset(), project_sinks=frozenset(),
                  depth0: bool = False) -> "str | None":
    """The matched sink name, or None. Depth-0 sites match the FULL
    lexical sets (alias-resolved) — the entry's own async context makes
    even ambiguous sinks suspect, mirroring the lexical rule; deeper
    sites match only the curated transitive set."""
    allowed = lexical_set if depth0 else transitive_set
    if site.resolved is not None:
        key = (site.resolved.module.path, site.resolved.qualname)
        if key in project_sinks:
            return site.resolved.qualname
    for name in (site.external, site.lexical):
        if name is not None and name in allowed:
            return name
    if site.external is None and site.lexical in bare_set \
            and isinstance(site.node.func, ast.Name):
        return site.lexical
    term = terminal_name(site.node.func)
    if term in method_set and isinstance(site.node.func, ast.Attribute):
        return f".{term}"
    return None


def _lexically_visible(site, lexical_set, bare_set=frozenset(),
                       method_set=frozenset()) -> bool:
    """Would the per-module lexical rule already report this site? Used
    to keep depth-0 interprocedural findings (alias-resolution catches)
    from duplicating lexical ones."""
    if site.lexical in lexical_set or site.lexical in bare_set:
        return True
    term = terminal_name(site.node.func)
    return term in method_set and isinstance(site.node.func, ast.Attribute)


def _emit_chain(findings, supp, rule, reached, site, subject, message):
    """One chain-carrying finding anchored in the entry function."""
    entry = reached.entry
    anchor = reached.anchor if reached.anchor is not None else site
    line, col = anchor.line, anchor.col
    s = supp.get(entry.module.path)
    if s is not None and s.suppresses(rule, line):
        return
    chain = reached.chain
    sites = reached.chain_sites[:-1] + (
        (reached.fn.module.path, site.line),)
    if len(chain) == 1:
        chain, sites = (), ()  # depth-0: the scope IS the chain
    findings.append(Finding(
        rule=rule, path=entry.module.path, line=line, col=col,
        scope=entry.qualname, detail=subject, message=message,
        chain=chain, chain_sites=sites))


def _dedupe(findings: list[Finding]) -> list[Finding]:
    seen = set()
    out = []
    for f in findings:
        key = (f.rule, f.path, f.scope, f.detail, f.line, f.col)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


# -- upgraded rules 1/2/6 -----------------------------------------------------


def _transitive_blocking(project, supp) -> list[Finding]:
    from .rules import BLOCKING_BARE, BLOCKING_DOTTED, EVENT_LOOP_SCOPES

    def follow_await(callee) -> bool:
        # an awaited async callee in an event-loop dir is its own entry;
        # following into OTHER dirs keeps coverage for e.g. an ops/
        # helper coroutine awaited from runtime/ without double-reporting
        return callee.module.path.split("/", 1)[0] not in EVENT_LOOP_SCOPES

    findings: list[Finding] = []
    for entry in async_entries(project, EVENT_LOOP_SCOPES):
        for r in reach_from(entry, follow_await=follow_await):
            depth0 = r.fn is entry
            for site in r.fn.calls:
                subject = _sink_subject(
                    site, BLOCKING_DOTTED, BLOCKING_DOTTED,
                    bare_set=BLOCKING_BARE, depth0=depth0)
                if subject is None:
                    continue
                if depth0 and _lexically_visible(
                        site, BLOCKING_DOTTED, BLOCKING_BARE):
                    continue  # the lexical rule already reports it
                _emit_chain(
                    findings, supp, "blocking-call-in-async", r, site,
                    subject,
                    f"blocking call `{subject}` reachable on the event "
                    f"loop via `{' → '.join(r.chain)}` stalls replication "
                    f"keepalives; route the chain off-loop "
                    f"(run_in_executor) or use the async equivalent")
    return _dedupe(findings)


def _transitive_device_sync(project, supp) -> list[Finding]:
    from .rules import DEVICE_SYNC_DOTTED, DEVICE_SYNC_METHODS

    def prune(site, callee) -> bool:
        # a call that IS the sink (the autotune probe) gets reported at
        # the call; its internals would only re-describe the same cause
        return (callee.module.path, callee.qualname) \
            in DEVICE_SYNC_PROJECT_SINKS

    findings: list[Finding] = []
    for entry in async_entries(project):
        for r in reach_from(entry, prune=prune):
            depth0 = r.fn is entry
            for site in r.fn.calls:
                subject = _sink_subject(
                    site, DEVICE_SYNC_DOTTED, DEVICE_SYNC_TRANSITIVE,
                    method_set=(DEVICE_SYNC_METHODS if depth0
                                else SYNC_METHOD_SINKS),
                    project_sinks=DEVICE_SYNC_PROJECT_SINKS,
                    depth0=depth0)
                if subject is None:
                    continue
                if depth0 and _lexically_visible(
                        site, DEVICE_SYNC_DOTTED,
                        method_set=DEVICE_SYNC_METHODS):
                    continue
                if r.dispatch and subject in ("jax.device_put",):
                    continue  # committed upload riding the pipeline
                _emit_chain(
                    findings, supp, "device-sync-in-async", r, site,
                    subject,
                    f"device sync point `{subject}` reachable from async "
                    f"code via `{' → '.join(r.chain)}` blocks the event "
                    f"loop on the host<->device link; dispatch and hand "
                    f"back a pending handle, or run the chain in an "
                    f"executor")
    return _dedupe(findings)


def _transitive_hot_transfer(project, supp) -> list[Finding]:
    from .rules import (DISPATCH_UPLOAD_DOTTED, HOT_TRANSFER_DOTTED,
                        HOT_TRANSFER_METHODS)

    def prune(site, callee) -> bool:
        return (callee.module.path, callee.qualname) \
            in DEVICE_SYNC_PROJECT_SINKS

    findings: list[Finding] = []
    for entry in hot_entries(project):
        for r in reach_from(entry, prune=prune):
            depth0 = r.fn is entry
            for site in r.fn.calls:
                subject = _sink_subject(
                    site, HOT_TRANSFER_DOTTED, HOT_TRANSFER_TRANSITIVE,
                    method_set=(HOT_TRANSFER_METHODS if depth0
                                else SYNC_METHOD_SINKS),
                    project_sinks=DEVICE_SYNC_PROJECT_SINKS,
                    depth0=depth0)
                if subject is None:
                    continue
                # the lexical rule reports depth-0 sinks only when it
                # could SEE the hot context: an aliased decorator
                # (`@hl`) defeats it, so the resolver must not defer
                lexically_hot = bool(entry.lex_decorators
                                     & {HOT_DECORATOR})
                if depth0 and lexically_hot and _lexically_visible(
                        site, HOT_TRANSFER_DOTTED,
                        method_set=HOT_TRANSFER_METHODS):
                    continue
                if r.dispatch and subject in DISPATCH_UPLOAD_DOTTED:
                    continue
                _emit_chain(
                    findings, supp, "hot-loop-host-transfer", r, site,
                    subject,
                    f"host transfer `{subject}` reachable from @hot_loop "
                    f"code via `{' → '.join(r.chain)}` serializes the hot "
                    f"path against the device link; fetch at the consumer "
                    f"(_PendingDecode.result) instead")
    return _dedupe(findings)


# -- rule: arena-lease-leak ---------------------------------------------------


def _is_lease_call(value) -> bool:
    return (isinstance(value, ast.Call)
            and terminal_name(value.func) == "lease"
            and isinstance(value.func, ast.Attribute)
            and not value.args and not value.keywords)


def _stmt_names(stmt):
    """(loads, stores, receiver_uses) of bare Names at one CFG node —
    compound statements contribute only their header (cfg.header_roots);
    nested callables are their own activation and are skipped. A Name
    that is the receiver of an attribute access (`x.release()`,
    `x.take(...)`) is a receiver use, not a value load — method calls on
    a lease keep ownership local."""
    from .cfg import header_roots

    loads, stores, receivers = [], [], []
    parents: dict[int, ast.AST] = {}
    nodes = []
    stack = list(header_roots(stmt))
    while stack:
        node = stack.pop()
        if node is not stmt and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        nodes.append(node)
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
            stack.append(child)
    for node in nodes:
        if not isinstance(node, ast.Name):
            continue
        parent = parents.get(id(node))
        if isinstance(node.ctx, ast.Store):
            stores.append(node.id)
        elif isinstance(parent, ast.Attribute) and parent.value is node:
            receivers.append((node.id, parent))
        else:
            loads.append(node.id)
    return loads, stores, receivers


def _iter_own_stmts(fn):
    """Every statement lexically in `fn`, excluding nested callables."""
    body = getattr(fn.node, "body", None)
    if not isinstance(body, list):
        return
    stack = list(body)
    while stack:
        stmt = stack.pop()
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield stmt
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                stack.append(child)
            elif hasattr(child, "body") and isinstance(
                    getattr(child, "body", None), list):
                stack.extend(s for s in child.body
                             if isinstance(s, ast.stmt))


def _releases_in(stmt) -> set:
    from .cfg import iter_header_nodes

    out = set()
    for node in iter_header_nodes(stmt):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "release" \
                and isinstance(node.func.value, ast.Name):
            out.add(node.func.value.id)
    return out


def _arena_lease_leak(project, supp) -> list[Finding]:
    findings: list[Finding] = []
    for fn in project.iter_functions():
        acquires: list[tuple[ast.stmt, str]] = []
        for stmt in _iter_own_stmts(fn):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and _is_lease_call(stmt.value):
                acquires.append((stmt, stmt.targets[0].id))
        if not acquires:
            continue
        escaped = _leak_escapes(fn, acquires)
        tracked = [(s, v) for (s, v) in acquires if v not in escaped]
        if not tracked:
            continue
        cfg = CFG(fn.node)
        acq_ids = {id(s): (s, v) for (s, v) in tracked}

        def transfer(node, state, _ids=acq_ids):
            if not isinstance(node, ast.stmt):
                return state
            out = set(state)
            released = _releases_in(node)
            if released:
                out = {a for a in out if _ids[a][1] not in released}
            _loads, stores, _recv = _stmt_names(node)
            if stores:  # reassignment of the lease var drops tracking
                out = {a for a in out if _ids[a][1] not in stores}
            if id(node) in _ids:  # gen after kill: `x = pool.lease()`
                out.add(id(node))
            return frozenset(out)

        def exc_transfer(node, state, _ids=acq_ids):
            # exception paths: a raising `x = pool.lease()` did NOT
            # acquire (no gen), but a release that ran still released —
            # without this, the release statement's own exception edge
            # would resurrect the lease and flag every finally block
            if not isinstance(node, ast.stmt):
                return state
            released = _releases_in(node)
            if released:
                return frozenset(a for a in state
                                 if _ids[a][1] not in released)
            return state

        in_states = dataflow_forward(cfg, transfer,
                                     exc_transfer=exc_transfer)
        live_exit = in_states.get(EXIT, frozenset())
        live_exc = in_states.get(EXC_EXIT, frozenset())
        for a in sorted(live_exit | live_exc,
                        key=lambda a: acq_ids[a][0].lineno):
            stmt, var = acq_ids[a]
            s = supp.get(fn.module.path)
            if s is not None and s.suppresses("arena-lease-leak",
                                              stmt.lineno):
                continue
            where = "on a normal path" if a in live_exit \
                else "when an exception escapes"
            findings.append(Finding(
                rule="arena-lease-leak", path=fn.module.path,
                line=stmt.lineno, col=stmt.col_offset + 1,
                scope=fn.qualname, detail=var,
                message=f"arena lease `{var}` can reach function exit "
                        f"{where} without release(); put the release in "
                        f"a finally/with (or hand the lease off "
                        f"explicitly) — leaked leases pin pool arenas "
                        f"forever (ARENA_POOL.outstanding)"))
    return findings


#: method terminals that STORE their argument (container inserts,
#: future/queue hand-offs): a lease passed to one of these escapes —
#: some later consumer owns the release now
_HANDOFF_TERMINALS = frozenset({
    "append", "appendleft", "add", "insert", "extend", "put",
    "put_nowait", "set_result", "send", "send_nowait", "setdefault",
})


def _leak_escapes(fn, acquires) -> set:
    """Lease variables whose ownership TRANSFERS out of the function:
    returned/yielded, stored into a container or attribute/subscript,
    aliased to another name, or passed to a storing method
    (`self._pending.append(lease)`, `queue.put_nowait(lease)`,
    `fut.set_result(lease)`). Passing the lease as any OTHER call
    argument is a BORROW (the pack stage writes into it; the caller
    still releases) — the distinction that keeps the real pipeline
    pattern `decoder._pack_stage(staged, arena=lease)` tracked while
    `handle.set_result((pending, lease))` correctly hands off."""
    escaped: set[str] = set()
    names = {v for (_s, v) in acquires}
    for stmt in _iter_own_stmts(fn):
        parents: dict[int, ast.AST] = {}
        stack = [stmt]
        while stack:
            node = stack.pop()
            if node is not stmt and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
                stack.append(child)
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Name) or node.id not in names \
                    or not isinstance(node.ctx, ast.Load):
                continue
            if any(s is stmt for (s, v) in acquires if v == node.id):
                continue  # the acquiring statement itself
            parent = parents.get(id(node))
            if isinstance(parent, ast.Attribute) and parent.value is node:
                continue  # receiver use: x.take()/x.release()
            if isinstance(parent, ast.Call):
                if terminal_name(parent.func) in _HANDOFF_TERMINALS:
                    escaped.add(node.id)  # stored for a later consumer
                continue  # otherwise borrowed: plain positional argument
            if isinstance(parent, ast.keyword):
                continue  # borrowed: keyword argument
            if isinstance(parent, ast.Compare):
                continue  # identity/None checks don't move ownership
            escaped.add(node.id)
    return escaped


# -- rule: donated-buffer-use -------------------------------------------------


def _donated_buffer_use(project, supp) -> list[Finding]:
    findings: list[Finding] = []
    for fn in project.iter_functions():
        m = fn.module
        donating = dict(m.donating)
        for stmt in _iter_own_stmts(fn):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                pos = donated_argnums(m, stmt.value, project)
                if pos is not None:
                    donating[stmt.targets[0].id] = pos
        if not donating:
            continue
        # donating call statements -> tainted buffer names
        taint_at: dict[int, tuple[ast.stmt, tuple[str, ...], int]] = {}
        for stmt in _iter_own_stmts(fn):
            for node in ast.walk(stmt):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    continue
                if not isinstance(node, ast.Call):
                    continue
                d = dotted_name(node.func)
                if d is None or d not in donating:
                    continue
                tainted = tuple(sorted(
                    a.id for i, a in enumerate(node.args)
                    if i in donating[d] and isinstance(a, ast.Name)))
                if tainted:
                    taint_at[id(stmt)] = (stmt, tainted, node.lineno)
        if not taint_at:
            continue
        cfg = CFG(fn.node)

        def transfer(node, state, _taints=taint_at):
            if not isinstance(node, ast.stmt):
                return state
            out = set(state)
            _loads, stores, _recv = _stmt_names(node)
            out -= set(stores)
            if id(node) in _taints:
                # the canonical rebind idiom `buf = step(buf)` is SAFE:
                # the name now holds the jit OUTPUT buffer, so a name
                # the donating statement itself stores is not tainted
                out |= set(_taints[id(node)][1]) - set(stores)
            return frozenset(out)

        in_states = dataflow_forward(cfg, transfer)
        reported = set()
        for stmt in sorted((s for s in cfg.statements()),
                           key=lambda s: (s.lineno, s.col_offset)):
            tainted_in = in_states.get(stmt, frozenset())
            if not tainted_in:
                continue
            loads, _stores, recvs = _stmt_names(stmt)
            uses = [n for n in loads if n in tainted_in] \
                + [n for (n, _a) in recvs if n in tainted_in]
            for name in uses:
                key = (name, stmt.lineno)
                if key in reported:
                    continue
                reported.add(key)
                s = supp.get(fn.module.path)
                if s is not None and s.suppresses("donated-buffer-use",
                                                  stmt.lineno):
                    continue
                findings.append(Finding(
                    rule="donated-buffer-use", path=fn.module.path,
                    line=stmt.lineno, col=stmt.col_offset + 1,
                    scope=fn.qualname, detail=name,
                    message=f"`{name}` was passed in a donate_argnums "
                            f"position — the device owns its buffer now; "
                            f"reading it afterwards sees poisoned memory "
                            f"on TPU (XLA reused the allocation)"))
    return findings


# -- rules: lock-held-across-await / lock-order-inversion ---------------------


class _LockTables:
    """Project-wide lock identity resolution (see docs/static-analysis.md
    for the heuristics and their limits)."""

    def __init__(self, project: Project):
        self.project = project
        self.attr_owner: dict[str, list[str]] = {}
        self.thread_attr_owner: dict[str, list[str]] = {}
        self.getter_owner: dict[str, list[str]] = {}
        for path in sorted(project.modules):
            m = project.modules[path]
            for cname in sorted(m.classes):
                cls = m.classes[cname]
                for a in cls.lock_attrs:
                    self.attr_owner.setdefault(a, []).append(
                        f"{m.path}::{cname}.{a}")
                for a in cls.thread_lock_attrs:
                    self.thread_attr_owner.setdefault(a, []).append(
                        f"{m.path}::{cname}.{a}")
                for g in cls.lock_getters:
                    self.getter_owner.setdefault(g, []).append(
                        f"{m.path}::{cname}.{g}()")

    def identify(self, fn, item) -> "tuple[str, bool] | None":
        """(lock id, is_async_lock) for a with-item context expr, else
        None when the expression is not recognizably a lock."""
        m = fn.module
        expr = item
        d = dotted_name(expr)
        if d is not None:
            head, _, rest = d.partition(".")
            if not rest:
                if d in m.module_locks:
                    return (f"{m.path}::{d}", True)
                if d in m.module_thread_locks:
                    return (f"{m.path}::{d}", False)
                return None
            attr = d.rsplit(".", 1)[-1]
            if head in ("self", "cls"):
                cls = self._own_class(fn)
                if cls is not None and "." not in rest:
                    if rest in cls.lock_attrs:
                        return (f"{cls.module.path}::{cls.name}.{rest}",
                                True)
                    if rest in cls.thread_lock_attrs:
                        return (f"{cls.module.path}::{cls.name}.{rest}",
                                False)
            owners = self.attr_owner.get(attr)
            if owners:
                return (owners[0] if len(owners) == 1
                        else f"<attr:{attr}>", True)
            owners = self.thread_attr_owner.get(attr)
            if owners:
                return (owners[0] if len(owners) == 1
                        else f"<attr:{attr}>", False)
            return None
        if isinstance(expr, ast.Call):
            term = terminal_name(expr.func)
            owners = self.getter_owner.get(term or "")
            if owners:
                return (owners[0] if len(owners) == 1
                        else f"<getter:{term}>", True)
        return None

    def _own_class(self, fn):
        scope = fn
        while scope is not None and scope.class_name is None:
            scope = scope.parent
        if scope is None:
            return None
        return fn.module.classes.get(scope.class_name)


def _self_derived_names(fn) -> set:
    """Locals transitively assigned from `self`/`cls` expressions —
    the own-resource sanction for awaits under a held lock."""
    derived = {"self", "cls"}
    for _ in range(6):  # fixpoint for assignment chains in any order
        before = len(derived)
        for stmt in _iter_own_stmts(fn):
            targets: list[str] = []
            value = None
            if isinstance(stmt, ast.Assign):
                value = stmt.value
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        targets.append(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        targets.extend(e.id for e in t.elts
                                       if isinstance(e, ast.Name))
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)) \
                    and isinstance(stmt.target, ast.Name):
                targets, value = [stmt.target.id], stmt.value
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if isinstance(item.optional_vars, ast.Name) and any(
                            isinstance(n, ast.Name) and n.id in derived
                            for n in ast.walk(item.context_expr)):
                        derived.add(item.optional_vars.id)
                continue
            if value is None or not targets:
                continue
            if any(isinstance(n, ast.Name) and n.id in derived
                   for n in ast.walk(value)):
                derived.update(targets)
        if len(derived) == before:
            break
    return derived


def _await_root(node: ast.Await) -> "str | None":
    """The receiver-chain root name of the awaited expression, unwrapping
    asyncio.wait_for/shield to their first argument and walking through
    attribute/call chains: `self._channel(schema).reset()` roots at
    `self` — the own-resource idiom with an inline receiver."""
    value = node.value
    if isinstance(value, ast.Call):
        term = terminal_name(value.func)
        if term in _AWAIT_WRAPPERS and value.args:
            value = value.args[0]
    while True:
        if isinstance(value, ast.Call):
            value = value.func
        elif isinstance(value, ast.Attribute):
            value = value.value
        else:
            break
    return value.id if isinstance(value, ast.Name) else None


def _await_subject(node: ast.Await) -> str:
    value = node.value
    target = value.func if isinstance(value, ast.Call) else value
    return dotted_name(target) or terminal_name(target) or "<await>"


def _walk_holding(fn, tables, on_acquire, on_await, on_call):
    """Walk `fn`'s body tracking the held-lock stack. Calls the hooks:
    on_acquire(lock, held_before, node), on_await(node, held),
    on_call(callsite, held). Nested defs are skipped (own activation)."""
    calls_by_node = {id(s.node): s for s in fn.calls}

    def walk(node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = list(held)
            for item in node.items:
                # context expr evaluates BEFORE the lock is held
                walk(item.context_expr, new_held)
                lock = tables.identify(fn, item.context_expr)
                if lock is not None:
                    on_acquire(lock, tuple(new_held), node)
                    new_held = new_held + [lock]
            for stmt in node.body:
                walk(stmt, new_held)
            return
        if isinstance(node, ast.Await):
            on_await(node, tuple(held))
        if isinstance(node, ast.Call):
            site = calls_by_node.get(id(node))
            if site is not None:
                on_call(site, tuple(held))
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    body = getattr(fn.node, "body", None)
    if isinstance(body, list):
        for stmt in body:
            walk(stmt, [])


def _lock_held_across_await(project, supp) -> list[Finding]:
    tables = _LockTables(project)
    findings: list[Finding] = []
    for fn in project.iter_functions():
        if fn.module.path.split("/", 1)[0] not in LOCK_RULE_SCOPES:
            continue
        derived = None
        resolved_calls = {id(s.node) for s in fn.calls
                          if s.resolved is not None}

        def on_await(node, held, fn=fn, resolved_calls=resolved_calls):
            nonlocal derived
            if not held:
                return
            if derived is None:
                derived = _self_derived_names(fn)
            sync_locks = [lk for (lk, is_async) in held if not is_async]
            async_locks = [lk for (lk, is_async) in held if is_async]
            subject = _await_subject(node)
            s = supp.get(fn.module.path)
            if sync_locks:
                if s is not None and s.suppresses(
                        "lock-held-across-await", node.lineno):
                    return
                findings.append(Finding(
                    rule="lock-held-across-await", path=fn.module.path,
                    line=node.lineno, col=node.col_offset + 1,
                    scope=fn.qualname,
                    detail=f"{_short(sync_locks[0])}:{subject}",
                    message=f"`await {subject}` while holding sync lock "
                            f"`{_short(sync_locks[0])}`: a threading "
                            f"mutex held across an await blocks every "
                            f"other loop task that touches it — release "
                            f"before awaiting"))
                return
            if not async_locks:
                return
            root = _await_root(node)
            if root is not None and root in derived:
                return  # own-resource serialization: the sanctioned idiom
            if isinstance(node.value, ast.Call) \
                    and id(node.value) in resolved_calls:
                # awaiting a PROJECT coroutine is a design choice the
                # lock-order rule polices (held locks propagate into the
                # callee there); this rule targets parking on foreign
                # awaitables — sleeps, queues, other components' I/O
                return
            if s is not None and s.suppresses(
                    "lock-held-across-await", node.lineno):
                return
            findings.append(Finding(
                rule="lock-held-across-await", path=fn.module.path,
                line=node.lineno, col=node.col_offset + 1,
                scope=fn.qualname,
                detail=f"{_short(async_locks[-1])}:{subject}",
                message=f"`await {subject}` while holding "
                        f"`{_short(async_locks[-1])}` parks every other "
                        f"waiter behind a foreign awaitable; move the "
                        f"await outside the lock, or serialize only the "
                        f"owner's own resource (docs/CONCURRENCY.md)"))

        _walk_holding(fn, tables, lambda *a: None, on_await,
                      lambda *a: None)
    return findings


def _short(lock_id: str) -> str:
    return lock_id.split("::", 1)[-1]


def _lock_order_inversion(project, supp) -> list[Finding]:
    tables = _LockTables(project)
    # pair -> (site path, line, chain tuple) of the first witness
    pairs: dict[tuple[str, str], tuple] = {}
    # (function, frozen held-set) states already expanded
    seen: set = set()
    work: list = []

    def scan(fn, incoming, chain):
        key = (id(fn), incoming)
        if key in seen or len(chain) > 8:
            return
        seen.add(key)

        def on_acquire(lock, held, node, fn=fn, chain=chain):
            lid = lock[0]
            for h in tuple(incoming) + tuple(x[0] for x in held):
                if h == lid:
                    continue
                pairs.setdefault((h, lid), (
                    fn.module.path, node.lineno, chain + (fn.qualname,)))

        def on_call(site, held, fn=fn, chain=chain):
            callee = site.resolved
            if callee is None or (callee.is_async and not site.awaited):
                return
            eff = frozenset(incoming) | {x[0] for x in held}
            if eff:
                work.append((callee, frozenset(eff),
                             chain + (fn.qualname,)))

        _walk_holding(fn, tables, on_acquire, lambda *a: None, on_call)

    for fn in project.iter_functions():
        scan(fn, frozenset(), ())
    while work:
        fn, held, chain = work.pop(0)
        scan(fn, held, chain)

    findings: list[Finding] = []
    reported = set()
    for (a, b), (path, line, chain) in sorted(pairs.items()):
        if (b, a) not in pairs or frozenset((a, b)) in reported:
            continue
        reported.add(frozenset((a, b)))
        other_path, other_line, other_chain = pairs[(b, a)]
        first, second = sorted([(a, b, path, line, chain),
                                (b, a, other_path, other_line,
                                 other_chain)])
        s = supp.get(first[2])
        if s is not None and s.suppresses("lock-order-inversion",
                                          first[3]):
            continue
        detail = " <> ".join(sorted((_short(a), _short(b))))
        findings.append(Finding(
            rule="lock-order-inversion", path=first[2], line=first[3],
            col=1, scope=" → ".join(first[4]) or "<module>",
            detail=detail,
            chain=first[4], chain_sites=((first[2], first[3]),),
            message=f"locks `{_short(first[0])}` and `{_short(first[1])}` "
                    f"are acquired in opposite orders "
                    f"(here {_short(first[0])} → {_short(first[1])}; "
                    f"at {second[2]}:{second[3]} "
                    f"{_short(second[0])} → {_short(second[1])}): two "
                    f"tasks interleaving these paths deadlock — pick one "
                    f"global order (docs/CONCURRENCY.md)"))
    return findings
