"""`python -m etl_tpu.analysis [paths]` — run etl-lint.

Exit codes: 0 clean (after baseline), 1 violations (or, with
`--check-baseline`, stale suppressions), 2 usage/parse error.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import baseline as baseline_mod
from .ir import IR_CONTRACT_NAMES, IR_NAMESPACE
from .rules import RULE_NAMES, analyze_paths, repo_package_dir


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m etl_tpu.analysis",
        description="etl-lint: async-safety & device-sync static analysis "
                    "for the etl_tpu codebase (lexical + whole-program)")
    p.add_argument("paths", nargs="*",
                   help="files or directories to scan "
                        "(default: the etl_tpu package)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline suppression file "
                        "(default: etl_tpu/analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to cover all current "
                        "findings, pruning fixed entries")
    p.add_argument("--check-baseline", action="store_true",
                   help="fail (exit 1) on stale baseline entries and on "
                        "inline `# etl-lint: ignore[...]` comments that "
                        "suppress nothing")
    p.add_argument("--no-interproc", action="store_true",
                   help="skip the whole-program pass (lexical rules only)")
    p.add_argument("--programs", action="store_true",
                   help="also run the IR tier: lower every enumerable "
                        "decode program and check the compiled-program "
                        "contracts (ir-*)")
    p.add_argument("--mesh", action="store_true",
                   help="with --programs: additionally verify the "
                        "mesh-sharded program variants in a forced "
                        "8-device subprocess")
    # internal: the forced-mesh child process entry (see ir.runner)
    p.add_argument("--programs-mesh-inner", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--callgraph", action="store_true",
                   help="dump the resolved call graph edges and exit")
    p.add_argument("--domains", action="store_true",
                   help="dump the inferred execution-domain map "
                        "(concurrency tier) and exit")
    p.add_argument("--explain", action="store_true",
                   help="print a resolvable file:line trace for each "
                        "violation's call chain")
    p.add_argument("--format", choices=("text", "json", "github"),
                   default="text", dest="fmt",
                   help="output format; `github` emits workflow-command "
                        "annotations (::error file=...) for CI")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="alias for --format=json")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule names and exit")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the summary line")
    return p


def _dump_callgraph(paths, as_json: bool) -> int:
    from .callgraph import Project
    from .rules import analyze_paths

    # reuse the scanner so rel-path canonicalization (and therefore
    # module keys) matches the analysis run exactly — parse-only, no
    # rule pass (the findings would be discarded anyway)
    units: list = []
    analyze_paths(paths, interprocedural=False, lexical=False,
                  units_out=units)
    project = Project.build([(u.path, u.source, u.tree) for u in units])
    edges = project.edges()
    if as_json:
        print(json.dumps({"edges": [list(e) for e in edges]}, indent=2))
    else:
        for src, dst in edges:
            print(f"{src} -> {dst}")
        print(f"etl-lint: {len(edges)} resolved call edges",
              file=sys.stderr)
    return 0


def _dump_domains(paths, as_json: bool) -> int:
    """`path::qualname: domain,domain` lines, sorted and stable — the
    review-diffable twin of --callgraph (two runs over an unchanged
    tree print byte-identical output; see docs/CONCURRENCY.md)."""
    from .callgraph import Project
    from .domains import infer_domains
    from .rules import analyze_paths

    units: list = []
    analyze_paths(paths, interprocedural=False, lexical=False,
                  units_out=units)
    project = Project.build([(u.path, u.source, u.tree) for u in units])
    dm = infer_domains(project)
    rows = [(f"{fn.module.path}::{fn.qualname}", domains)
            for fn, domains in dm.items()]
    if as_json:
        print(json.dumps({"domains": {name: domains
                                      for name, domains in rows}},
                         indent=2, sort_keys=True))
    else:
        for name, domains in rows:
            print(f"{name}: {','.join(domains)}")
        print(f"etl-lint: {len(rows)} functions classified",
              file=sys.stderr)
    return 0


def _annotation_path(path: str) -> str:
    """Repo-relative path for a workflow annotation. Finding paths are
    canonical (package-stripped), so package files need the `etl_tpu/`
    prefix back; files from other scan roots (fixture trees) keep their
    canonical path — anchoring to a nonexistent file helps nobody."""
    import os

    prefixed = os.path.join("etl_tpu", path)
    return prefixed if os.path.exists(prefixed) else path


def _render_github(f) -> str:
    # workflow commands reject newlines in the message; title carries
    # the rule so annotations group in the PR UI
    msg = f.message.replace("\n", " ")
    if f.chain:
        msg += f" (via {f.chain_text()})"
    return (f"::error file={_annotation_path(f.path)},line={f.line},"
            f"col={f.col},title=etl-lint {f.rule}::{msg}")


def main(argv: "list[str] | None" = None) -> int:
    args = build_parser().parse_args(argv)
    if args.as_json:
        args.fmt = "json"
    if args.list_rules:
        names = RULE_NAMES + (IR_CONTRACT_NAMES if args.programs else ())
        print("\n".join(names))
        return 0
    if args.mesh and not (args.programs or args.programs_mesh_inner):
        print("etl-lint: --mesh requires --programs", file=sys.stderr)
        return 2
    if args.programs_mesh_inner:
        from .ir import runner as ir_runner

        try:
            print(json.dumps(ir_runner.run_mesh_inner()))
        except Exception as e:  # analyzer failure, not a lint result
            print(f"etl-lint: ir analyzer error: {e}", file=sys.stderr)
            return 2
        return 0
    paths = args.paths or [str(repo_package_dir())]
    if args.callgraph:
        try:
            return _dump_callgraph(paths, args.fmt == "json")
        except (SyntaxError, OSError) as e:
            print(f"etl-lint: {e}", file=sys.stderr)
            return 2
    if args.domains:
        try:
            return _dump_domains(paths, args.fmt == "json")
        except (SyntaxError, OSError) as e:
            print(f"etl-lint: {e}", file=sys.stderr)
            return 2
    scanned: list[str] = []
    units: list = []
    try:
        findings = analyze_paths(paths, scanned=scanned,
                                 interprocedural=not args.no_interproc,
                                 units_out=units)
    except (SyntaxError, OSError) as e:
        print(f"etl-lint: {e}", file=sys.stderr)
        return 2
    except RecursionError as e:  # analyzer bug, not a lint result
        print(f"etl-lint: analyzer error: {e}", file=sys.stderr)
        return 2

    if args.programs:
        from .ir import runner as ir_runner

        try:
            ir_findings, ir_paths = ir_runner.analyze_programs(
                mesh=args.mesh)
        except ir_runner.IrAnalysisError as e:
            print(f"etl-lint: {e}", file=sys.stderr)
            return 2
        findings = sorted(findings + ir_findings,
                          key=lambda f: (f.path, f.line, f.col, f.rule))
        scanned.extend(ir_paths)

    if args.update_baseline:
        # scanned_paths bounds the rewrite: a scoped run only rewrites
        # entries for the files it actually looked at
        out = baseline_mod.save(findings, args.baseline,
                                scanned_paths=set(scanned))
        if not args.quiet:
            print(f"etl-lint: baseline updated: {out} "
                  f"({len(findings)} findings grandfathered)")
        return 0

    if args.no_baseline:
        allowed: dict[str, int] = {}
    else:
        try:
            allowed = baseline_mod.load(args.baseline)
        except (ValueError, OSError) as e:
            print(f"etl-lint: {e}", file=sys.stderr)
            return 2
    violations, stale = baseline_mod.apply(findings, allowed)
    # stale warnings only make sense for files this run actually looked
    # at — a scoped run can't know whether out-of-scope debt was fixed.
    # When the IR tier ran, the ENTIRE `programs/` namespace counts as
    # scanned (not just the enumerated paths): that pass enumerates
    # every program any tier can produce, so a baseline entry it did not
    # re-produce — including one for a layout that no longer exists, or
    # a finding that migrated between tiers — is genuinely stale.
    scanned_set = set(scanned)
    stale = {fp: n for fp, n in stale.items()
             if baseline_mod.fingerprint_path(fp) in scanned_set
             or (args.programs and baseline_mod.fingerprint_path(fp)
                 .startswith(IR_NAMESPACE))}

    if args.check_baseline:
        unused_ignores = [(u.path, line, rule) for u in units
                          for line, rule in u.suppressions.unused()]
        for fp, n in sorted(stale.items()):
            print(f"etl-lint: stale baseline entry ({n} unused): {fp}")
        for path, line, rule in sorted(unused_ignores):
            print(f"etl-lint: stale inline ignore at {path}:{line}: "
                  f"ignore[{rule}] suppresses nothing")
        dirty = bool(stale) or bool(unused_ignores)
        if not args.quiet:
            print(f"etl-lint: --check-baseline: {len(stale)} stale "
                  f"baseline entries, {len(unused_ignores)} stale "
                  f"inline ignores")
        return 1 if dirty else 0

    if args.fmt == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "violations": [f.to_dict() for f in violations],
            "stale_baseline": stale,
            "baselined": len(findings) - len(violations),
        }, indent=2))
    elif args.fmt == "github":
        for f in violations:
            print(_render_github(f))
        if not args.quiet:
            print(f"etl-lint: {len(violations)} violations "
                  f"({len(findings) - len(violations)} baselined)",
                  file=sys.stderr)
    else:
        for f in violations:
            print(f.render())
            if args.explain:
                print(f.explain())
        for fp, unused in sorted(stale.items()):
            print(f"etl-lint: stale baseline entry ({unused} unused): {fp}",
                  file=sys.stderr)
        if not args.quiet:
            print(f"etl-lint: {len(findings)} findings, "
                  f"{len(findings) - len(violations)} baselined, "
                  f"{len(violations)} violations"
                  + (f", {len(stale)} stale baseline entries" if stale
                     else ""))
    return 1 if violations else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
