"""`python -m etl_tpu.analysis [paths]` — run etl-lint.

Exit codes: 0 clean (after baseline), 1 violations, 2 usage/parse error.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import baseline as baseline_mod
from .rules import RULE_NAMES, analyze_paths, repo_package_dir


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m etl_tpu.analysis",
        description="etl-lint: async-safety & device-sync static analysis "
                    "for the etl_tpu codebase")
    p.add_argument("paths", nargs="*",
                   help="files or directories to scan "
                        "(default: the etl_tpu package)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline suppression file "
                        "(default: etl_tpu/analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to cover all current "
                        "findings, pruning fixed entries")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule names and exit")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the summary line")
    return p


def main(argv: "list[str] | None" = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        print("\n".join(RULE_NAMES))
        return 0
    paths = args.paths or [str(repo_package_dir())]
    scanned: list[str] = []
    try:
        findings = analyze_paths(paths, scanned=scanned)
    except (SyntaxError, OSError) as e:
        print(f"etl-lint: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        # scanned_paths bounds the rewrite: a scoped run only rewrites
        # entries for the files it actually looked at
        out = baseline_mod.save(findings, args.baseline,
                                scanned_paths=set(scanned))
        if not args.quiet:
            print(f"etl-lint: baseline updated: {out} "
                  f"({len(findings)} findings grandfathered)")
        return 0

    if args.no_baseline:
        allowed: dict[str, int] = {}
    else:
        try:
            allowed = baseline_mod.load(args.baseline)
        except (ValueError, OSError) as e:
            print(f"etl-lint: {e}", file=sys.stderr)
            return 2
    violations, stale = baseline_mod.apply(findings, allowed)
    # stale warnings only make sense for files this run actually looked
    # at — a scoped run can't know whether out-of-scope debt was fixed
    scanned_set = set(scanned)
    stale = {fp: n for fp, n in stale.items()
             if baseline_mod.fingerprint_path(fp) in scanned_set}

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "violations": [f.to_dict() for f in violations],
            "stale_baseline": stale,
            "baselined": len(findings) - len(violations),
        }, indent=2))
    else:
        for f in violations:
            print(f.render())
        for fp, unused in sorted(stale.items()):
            print(f"etl-lint: stale baseline entry ({unused} unused): {fp}",
                  file=sys.stderr)
        if not args.quiet:
            print(f"etl-lint: {len(findings)} findings, "
                  f"{len(findings) - len(violations)} baselined, "
                  f"{len(violations)} violations"
                  + (f", {len(stale)} stale baseline entries" if stale
                     else ""))
    return 1 if violations else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
