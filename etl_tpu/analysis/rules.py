"""The etl-lint rule set (codebase-specific async-safety & device-sync).

Six rules, each encoding an invariant the round-5 advisor or a prior
VERDICT caught by hand (see docs/static-analysis.md for the contract and
worked examples):

  1. blocking-call-in-async   — sync sleep/subprocess/sqlite/socket/file
                                I/O lexically inside `async def` bodies in
                                runtime/, postgres/, api/
  2. device-sync-in-async     — host<->device sync points (np.asarray,
                                jax.device_get, .block_until_ready, the
                                jit-compiling autotune probe) inside async
                                code unless routed through run_in_executor
  3. orphaned-task            — create_task/ensure_future whose handle is
                                discarded (GC may cancel the task mid-flight)
  4. unawaited-coroutine      — statement-level call of a locally-defined
                                `async def` without await/gather/create_task
  5. cancellation-swallow     — handlers that eat asyncio.CancelledError
                                anywhere, plus broad `except Exception` in
                                runtime/ that never re-raises
  6. hot-loop-host-transfer   — host transfers inside `@hot_loop`
                                functions; `@dispatch_stage` (the decode
                                pipeline's dispatch stage) sanctions
                                host→device uploads only
  7. unbounded-retry          — `while True` retry loops whose handlers
                                swallow exceptions and spin again with no
                                backoff (no sleep / RetryPolicy delay):
                                a failing dependency turns them into a
                                busy-loop hammering it at CPU speed
  8. unbounded-await          — bare `await q.get()` / `await ev.wait()`
                                in runtime/ and destinations/ without a
                                timeout or shutdown race: a producer that
                                dies (or an event nobody sets) wedges the
                                worker forever with no error — exactly
                                the silent-hang class the supervision
                                watchdog exists for; bound the await
                                (asyncio.wait_for / or_shutdown /
                                beat_while_waiting) or justify inline

Rules 1, 2, and 6 additionally run INTERPROCEDURALLY (interproc.py): a
blocking or host-transfer sink anywhere in the call closure of an
event-loop `async def` / `@hot_loop` function is reported with the full
call chain, and import aliases resolve (`from time import sleep`). Four
whole-program rules (arena-lease-leak, donated-buffer-use,
lock-held-across-await, lock-order-inversion) live there too — they
need the call graph and per-function CFGs, not a lexical walk.
"""

from __future__ import annotations

import ast
import os
from pathlib import Path

from .findings import _PACKAGE_SEGMENT, Finding, canonical_path
from .visitor import (LintContext, Rule, collect_async_defs, dotted_name,
                      handler_type_names, has_raise, lint_module,
                      terminal_name)

# -- rule 1 -------------------------------------------------------------------

#: directories whose async code runs on the replication event loop,
#: where one blocking call stalls keepalives for every table
EVENT_LOOP_SCOPES = ("runtime", "postgres", "api")

BLOCKING_DOTTED = frozenset({
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "os.system", "os.popen", "os.waitpid",
    "sqlite3.connect",
    "socket.create_connection", "socket.getaddrinfo",
    "socket.gethostbyname",
    "urllib.request.urlopen",
    "requests.get", "requests.post", "requests.put", "requests.request",
})
#: bare built-in calls that hit the filesystem synchronously
BLOCKING_BARE = frozenset({"open"})


class BlockingCallInAsync(Rule):
    name = "blocking-call-in-async"

    def applies_to(self, rel_path: str) -> bool:
        head = rel_path.split("/", 1)[0]
        return head in EVENT_LOOP_SCOPES

    def on_call(self, ctx: LintContext, node: ast.Call) -> None:
        if not ctx.in_async:
            return
        dotted = dotted_name(node.func)
        subject = None
        if dotted in BLOCKING_DOTTED:
            subject = dotted
        elif isinstance(node.func, ast.Name) and node.func.id in BLOCKING_BARE:
            subject = node.func.id
        if subject is None:
            return
        # NOTE deliberately no run_in_executor argument exemption:
        # correct usage passes the callable UNCALLED (no Call node here),
        # while `run_in_executor(None, time.sleep(5))` runs the blocking
        # call eagerly on the loop — exactly when the rule must fire
        ctx.report(
            self.name, node, subject,
            f"blocking call `{subject}` inside async def stalls the "
            f"replication event loop; use the async equivalent or "
            f"loop.run_in_executor")


# -- rule 2 -------------------------------------------------------------------

#: calls that synchronize with (or jit-compile for) the accelerator —
#: inside async code each one stalls keepalives for the round trip
DEVICE_SYNC_DOTTED = frozenset({
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "jax.device_get", "jax.device_put", "jax.jit",
    # the autotune probe jit-compiles + moves 2x8 MiB over the link.
    # NOTE the round-5 advisor's actual bug fired through a SYNC call
    # chain (DeviceDecoder.__init__ on the loop), which lexical analysis
    # cannot see — that path is fixed by Pipeline.start() awaiting
    # autotune.prewarm() (guarded by its own test); this rule prevents
    # the probe from being reintroduced directly into async code
    "autotune.measure", "autotune.resolve_device_min_rows",
})
DEVICE_SYNC_METHODS = frozenset({"block_until_ready"})


class DeviceSyncInAsync(Rule):
    name = "device-sync-in-async"

    def on_call(self, ctx: LintContext, node: ast.Call) -> None:
        if not ctx.in_async:
            return
        dotted = dotted_name(node.func)
        subject = None
        if dotted in DEVICE_SYNC_DOTTED:
            subject = dotted
        else:
            term = terminal_name(node.func)
            if term in DEVICE_SYNC_METHODS and isinstance(node.func,
                                                          ast.Attribute):
                subject = f".{term}"
        if subject is None:
            return
        # no run_in_executor argument exemption — see BlockingCallInAsync
        ctx.report(
            self.name, node, subject,
            f"device sync point `{subject}` inside async def blocks the "
            f"event loop on the host<->device link; dispatch and hand "
            f"back a pending handle, or route through run_in_executor")


# -- rule 3 -------------------------------------------------------------------

TASK_SPAWNERS = frozenset({"create_task", "ensure_future"})


class OrphanedTask(Rule):
    name = "orphaned-task"

    def _report(self, ctx: LintContext, call: ast.Call) -> None:
        subject = dotted_name(call.func) or terminal_name(call.func)
        ctx.report(
            self.name, call, subject,
            f"`{subject}` result discarded: the event loop holds only a "
            f"weak reference, so GC can cancel the task mid-flight — "
            f"keep the handle (and await it on shutdown)")

    def on_expr_statement(self, ctx: LintContext, node: ast.Expr) -> None:
        call = node.value
        if isinstance(call, ast.Call) \
                and terminal_name(call.func) in TASK_SPAWNERS:
            self._report(ctx, call)

    def on_call(self, ctx: LintContext, node: ast.Call) -> None:
        # `lambda: ensure_future(...)` as a callback (signal handlers,
        # add_done_callback): the lambda returns the handle but every
        # callback caller discards it — same GC hazard, different shape
        if terminal_name(node.func) not in TASK_SPAWNERS:
            return
        ancestors = ctx.ancestors()
        if ancestors and isinstance(ancestors[-1], ast.Lambda) \
                and ancestors[-1].body is node:
            self._report(ctx, node)


# -- rule 4 -------------------------------------------------------------------

class UnawaitedCoroutine(Rule):
    name = "unawaited-coroutine"

    def __init__(self) -> None:
        self._plain: set[str] = set()
        self._methods: dict[str, set[str]] = {}

    def before_module(self, ctx: LintContext, tree: ast.Module) -> None:
        self._plain, self._methods = collect_async_defs(tree)

    def on_expr_statement(self, ctx: LintContext, node: ast.Expr) -> None:
        call = node.value
        if not isinstance(call, ast.Call):
            return
        func = call.func
        subject = None
        if isinstance(func, ast.Name) and func.id in self._plain:
            subject = func.id
        elif (isinstance(func, ast.Attribute)
              and isinstance(func.value, ast.Name)
              and func.value.id in ("self", "cls")
              and func.attr in self._methods.get(
                  ctx.current_class or "", ())):
            subject = f"{func.value.id}.{func.attr}"
        if subject is None:
            return
        ctx.report(
            self.name, call, subject,
            f"`{subject}` is an async def: calling it without "
            f"await/gather/create_task builds a coroutine object and "
            f"silently drops it — the body never runs")


# -- rule 5 -------------------------------------------------------------------

class CancellationSwallow(Rule):
    name = "cancellation-swallow"

    @staticmethod
    def _is_cancel_drain(ctx: LintContext,
                         node: ast.ExceptHandler) -> bool:
        """The canonical safe idiom `t.cancel(); try: await t; except
        CancelledError: pass` — the swallow IS the point: awaiting a task
        you just cancelled raises its CancelledError into you. Recognized
        lexically (a `.cancel()` on the awaited target earlier in the
        same function, trivial handler body) so the repo's shutdown
        drains need no per-site suppression."""
        for stmt in node.body:
            if not (isinstance(stmt, (ast.Pass, ast.Continue, ast.Break))
                    or (isinstance(stmt, ast.Return)
                        and (stmt.value is None
                             or isinstance(stmt.value, ast.Constant)))):
                return False
        ancestors = ctx.ancestors()
        try_node = next((n for n in reversed(ancestors)
                         if isinstance(n, ast.Try)), None)
        if try_node is None or node not in try_node.handlers:
            return False
        targets = set()
        for stmt in try_node.body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Await):
                    d = dotted_name(n.value)
                    if d:
                        targets.add(d)
        if not targets:
            return False
        scope = next((n for n in reversed(ancestors)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))),
                     ancestors[0] if ancestors else try_node)
        for n in ast.walk(scope):
            if isinstance(n, ast.Call):
                d = dotted_name(n.func)
                if d is not None and d.endswith(".cancel") \
                        and d[:-len(".cancel")] in targets \
                        and getattr(n, "lineno", 1 << 30) <= node.lineno:
                    return True
        return False

    @staticmethod
    def _cancellation_shielded(ctx: LintContext,
                               node: ast.ExceptHandler) -> bool:
        """True when an EARLIER handler of the same `try` catches
        CancelledError and re-raises — cancellation never reaches `node`,
        so a broad catch there (panic containment) is not a swallow."""
        for anc in reversed(ctx.ancestors()):
            if isinstance(anc, ast.Try):
                for prior in anc.handlers:
                    if prior is node:
                        break
                    if "CancelledError" in handler_type_names(prior) \
                            and has_raise(prior):
                        return True
                return False
        return False

    def on_except_handler(self, ctx: LintContext,
                          node: ast.ExceptHandler) -> None:
        names = handler_type_names(node)
        if has_raise(node):
            return
        if self._is_cancel_drain(ctx, node):
            return
        if (("<bare>" in names or "BaseException" in names
                or "CancelledError" in names)
                and not self._cancellation_shielded(ctx, node)):
            caught = "except" if "<bare>" in names \
                else f"except {'|'.join(names)}"
            ctx.report(
                self.name, node, caught,
                f"`{caught}` catches asyncio.CancelledError and never "
                f"re-raises: shutdown/timeout cancellation dies here and "
                f"the worker keeps running")
            return
        broad = {"Exception", "BaseException", "<bare>"} & set(names)
        if broad and ctx.rel_path.split("/", 1)[0] == "runtime":
            caught = sorted(broad)[0]
            caught = "except" if caught == "<bare>" else f"except {caught}"
            ctx.report(
                self.name, node, caught,
                f"broad `{caught}` in runtime/ without re-raise hides "
                f"apply-loop failures; narrow it, re-raise, or baseline "
                f"with a justification")


# -- rule 6 -------------------------------------------------------------------

HOT_TRANSFER_DOTTED = frozenset({
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "jax.device_get", "jax.device_put",
})
HOT_TRANSFER_METHODS = frozenset({"block_until_ready"})

#: host→device UPLOADS: inside a @dispatch_stage function (the decode
#: pipeline's dispatch stage, ops/pipeline.py architecture) these are the
#: point — the committed placement of a packed arena rides the pipeline.
#: Fetch-side transfers (asarray / device_get / block_until_ready) stay
#: forbidden there: they belong at the consumer, the fetch stage.
DISPATCH_UPLOAD_DOTTED = frozenset({"jax.device_put"})


class HotLoopHostTransfer(Rule):
    name = "hot-loop-host-transfer"

    def on_call(self, ctx: LintContext, node: ast.Call) -> None:
        if not ctx.in_hot_loop:
            return
        dotted = dotted_name(node.func)
        subject = None
        if dotted in HOT_TRANSFER_DOTTED:
            subject = dotted
        else:
            term = terminal_name(node.func)
            if term in HOT_TRANSFER_METHODS and isinstance(node.func,
                                                           ast.Attribute):
                subject = f".{term}"
        if subject is None:
            return
        if ctx.in_dispatch_stage and subject in DISPATCH_UPLOAD_DOTTED:
            return  # upload in the dispatch stage: sanctioned
        ctx.report(
            self.name, node, subject,
            f"host transfer `{subject}` inside a @hot_loop function "
            f"serializes the hot path against the device link; fetch at "
            f"the consumer (_PendingDecode.result) instead")


# -- rule 7 -------------------------------------------------------------------

#: calls that count as backoff inside a retry loop: sleeps (direct or
#: wrapped, e.g. or_shutdown(shutdown, asyncio.sleep(d))), the unified
#: RetryPolicy's delay schedule, and the destination retry wrapper
BACKOFF_TERMINALS = frozenset({"sleep", "delay", "delay_ms", "base_delay",
                               "with_retries"})
#: `.execute(...)` counts as backoff ONLY on a retry-policy receiver
#: (`policy.execute`, `self.retry.execute`) — a bare `cursor.execute`
#: inside a while-True hammer must NOT suppress the rule
_EXECUTE_RECEIVER_HINTS = ("retry", "policy")


class UnboundedRetry(Rule):
    """`while True` loops that catch exceptions, keep looping, and never
    back off. The swallowing handler turns a dead dependency into a
    CPU-speed hammer (connect storms against a down Postgres, request
    storms against a throttling destination). Fix: a RetryPolicy delay /
    sleep in the handler or loop body, or re-raise / break out."""

    name = "unbounded-retry"

    @staticmethod
    def _is_while_true(node: ast.While) -> bool:
        return isinstance(node.test, ast.Constant) and node.test.value is True

    @classmethod
    def _region(cls, node: ast.AST, with_loop_depth: bool = False):
        """The nodes belonging to ONE while-True's retry region: nested
        callables are pruned (they run in a different activation — the
        has_raise lesson, visitor._contains_raise), and nested while-True
        loops are pruned too (each gets its own on_while analysis: an
        inner hot spin must not be absolved by an outer loop's backoff,
        and one handler must not be reported per level). With
        `with_loop_depth`, yields (node, inside_inner_loop) so a
        handler's `break` can be judged against the loop it would
        actually exit."""
        stack = [(n, False) for n in ast.iter_child_nodes(node)]
        while stack:
            n, in_loop = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.While) and cls._is_while_true(n):
                continue
            yield (n, in_loop) if with_loop_depth else n
            nested = in_loop or isinstance(n, (ast.For, ast.AsyncFor,
                                               ast.While))
            stack.extend((c, nested) for c in ast.iter_child_nodes(n))

    @staticmethod
    def _exits_loop(handler: ast.ExceptHandler,
                    try_in_inner_loop: bool) -> bool:
        """Does the handler leave the retry loop? raise/return anywhere
        (nested callables pruned, including a def as the handler's own
        statement) exit the function; `break` counts only when it would
        exit the RETRY loop — not when the try already sits inside an
        inner loop (`try_in_inner_loop`) or the break is inside a loop
        nested within the handler."""

        def scan(node: ast.AST, in_nested_loop: bool) -> bool:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, (ast.Return, ast.Raise)):
                    return True
                if isinstance(child, ast.Break) and not in_nested_loop:
                    return True
                nested = in_nested_loop or isinstance(
                    child, (ast.For, ast.AsyncFor, ast.While))
                if scan(child, nested):
                    return True
            return False

        for stmt in handler.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # a def IS the statement: its body never runs here
            if isinstance(stmt, (ast.Return, ast.Raise)):
                return True
            if isinstance(stmt, ast.Break) and not try_in_inner_loop:
                return True
            if scan(stmt, try_in_inner_loop or isinstance(
                    stmt, (ast.For, ast.AsyncFor, ast.While))):
                return True
        return False

    @classmethod
    def _has_backoff(cls, node: ast.While) -> bool:
        for n in cls._region(node):
            if not isinstance(n, ast.Call):
                continue
            term = terminal_name(n.func)
            if term in BACKOFF_TERMINALS:
                return True
            if term == "execute":
                dotted = (dotted_name(n.func) or "").lower()
                receiver = dotted.rsplit(".", 1)[0]
                if any(h in receiver for h in _EXECUTE_RECEIVER_HINTS):
                    return True
        return False

    def on_while(self, ctx: LintContext, node: ast.While) -> None:
        if not self._is_while_true(node):
            return
        swallowing = []
        for n, in_inner_loop in self._region(node, with_loop_depth=True):
            if not isinstance(n, ast.Try):
                continue
            for handler in n.handlers:
                names = set(handler_type_names(handler))
                broad = names & {"Exception", "BaseException", "<bare>",
                                 "EtlError", "OSError", "ConnectionError",
                                 "ClientError", "TimeoutError"}
                if broad and not self._exits_loop(handler, in_inner_loop):
                    swallowing.append((handler, sorted(broad)[0]))
        if not swallowing or self._has_backoff(node):
            return
        handler, caught = min(swallowing,
                              key=lambda hc: hc[0].lineno)
        caught = "except" if caught == "<bare>" else f"except {caught}"
        ctx.report(
            self.name, handler, caught,
            f"`while True` retry loop swallows `{caught}` and spins with "
            f"no backoff — a failing dependency gets hammered at CPU "
            f"speed; add a RetryPolicy delay / sleep, or re-raise")


# -- rule 8 -------------------------------------------------------------------

#: directories whose workers must never park on an unbounded await: a
#: wedged queue pop / event wait there stalls replication silently
UNBOUNDED_AWAIT_SCOPES = ("runtime", "destinations")

#: awaited zero-arg methods that park until someone else acts
_PARKING_TERMINALS = frozenset({"get", "wait"})


class UnboundedAwait(Rule):
    """Bare `await X.get()` / `await X.wait()` with no timeout and no
    shutdown race. The sanctioned shapes never produce the flagged AST:
    `await asyncio.wait_for(q.get(), t)` and `await or_shutdown(sd,
    ev.wait())` await the WRAPPER call, and `asyncio.wait(...)` takes
    arguments. Receivers whose dotted path mentions shutdown are exempt —
    the shutdown signal IS the escape hatch the rule demands."""

    name = "unbounded-await"

    def applies_to(self, rel_path: str) -> bool:
        return rel_path.split("/", 1)[0] in UNBOUNDED_AWAIT_SCOPES

    def on_call(self, ctx: LintContext, node: ast.Call) -> None:
        ancestors = ctx.ancestors()
        if not ancestors or not isinstance(ancestors[-1], ast.Await):
            return
        if node.args or node.keywords:
            return  # q.get(timeout), asyncio.wait(tasks, ...) are bounded
        term = terminal_name(node.func)
        if term not in _PARKING_TERMINALS:
            return
        if not isinstance(node.func, ast.Attribute):
            return  # bare get()/wait() name: not a parking receiver
        receiver = dotted_name(node.func.value) or ""
        if receiver in ("self", "cls"):
            return  # a method on the worker itself, not an event/queue
        if "shutdown" in receiver.lower():
            return
        subject = f"{receiver}.{term}" if receiver else term
        ctx.report(
            self.name, node, subject,
            f"bare `await {subject}()` parks this worker until someone "
            f"else acts — a dead producer wedges it forever with no "
            f"error; bound it (asyncio.wait_for) or race it against "
            f"shutdown (or_shutdown), or justify with an inline ignore")


# -- rule 13 ------------------------------------------------------------------

#: lexical row-path sinks: constructing row objects, expanding a batch
#: into per-row events, or transposing rows into/out of a ColumnarBatch.
#: Inside a @hot_loop batch-encode entry point any of these means the
#: columnar egress path has fallen back to per-row Python — the exact
#: regression the fetch-to-wire refactor (ROADMAP item 2) removed.
ROW_MATERIALIZATION_CTORS = frozenset({"TableRow", "PartialTableRow"})
ROW_MATERIALIZATION_FREE_CALLS = frozenset({"expand_batch_events"})
ROW_MATERIALIZATION_METHODS = frozenset({"to_rows", "from_rows"})
#: predicate-compile sinks: binding a publication row filter re-resolves
#: columns, re-coerces every literal, and (on first dispatch) re-traces
#: the fused device program — decoder-CONSTRUCTION work. Inside a
#: @hot_loop function it runs per batch/flush, the exact per-batch
#: recompile the fused-filter design forbids (ops/predicate.py).
PREDICATE_COMPILE_CALLS = frozenset({"compile_row_filter",
                                     "parse_row_filter",
                                     "compile_texts", "compile_values"})


class HotLoopRowMaterialization(Rule):
    """`TableRow(...)` / `.to_rows()` / `.from_rows(...)` /
    `expand_batch_events(...)` inside a `@hot_loop` function: the columnar
    egress hot path is materializing Python row objects. Intentional
    compatibility-shim uses carry an inline ignore with a justification
    (they are the row fallback, not the hot path).

    Also covers the predicate-compile path (`compile_row_filter` /
    `parse_row_filter` / the per-row evaluator compilers): publication
    row filters compile ONCE at decoder construction; a compile inside a
    @hot_loop function re-binds and re-traces per batch."""

    name = "hot-loop-row-materialization"

    def on_call(self, ctx: LintContext, node: ast.Call) -> None:
        if not ctx.in_hot_loop:
            return
        term = terminal_name(node.func)
        subject = None
        pred_compile = False
        if term in ROW_MATERIALIZATION_CTORS \
                or term in ROW_MATERIALIZATION_FREE_CALLS:
            subject = f"{term}(…)"
        elif term in PREDICATE_COMPILE_CALLS:
            subject = f"{term}(…)"
            pred_compile = True
        elif term in ROW_MATERIALIZATION_METHODS \
                and isinstance(node.func, ast.Attribute):
            subject = f".{term}(…)"
        if subject is None:
            return
        if pred_compile:
            ctx.report(
                self.name, node, subject,
                f"row-filter compilation `{subject}` inside a @hot_loop "
                f"function: predicates compile at decoder construction, "
                f"never per batch — hoist it to __init__/startup, or "
                f"justify with an inline ignore")
            return
        ctx.report(
            self.name, node, subject,
            f"row materialization `{subject}` inside a @hot_loop "
            f"batch-encode entry point: encode from the ColumnarBatch "
            f"column-at-a-time instead, or justify the compatibility "
            f"shim with an inline ignore")


# -- rule 14 ------------------------------------------------------------------

#: device traffic forbidden on the admission grant path: the fetch set
#: from rule 6 PLUS `jax.device_put` — the @dispatch_stage upload
#: sanction does NOT extend here, because an admission decision holds the
#: scheduler's condition lock (or gates every tenant's dispatch), so ANY
#: device call head-of-line-blocks all tenants, uploads included
ADMISSION_DEVICE_DOTTED = HOT_TRANSFER_DOTTED | {"jax.device_put"}
ADMISSION_DEVICE_METHODS = HOT_TRANSFER_METHODS


class AdmissionBlockingFetch(Rule):
    """Blocking device traffic inside the batch-admission scheduler's
    grant path (`@admission_path`, ops/pipeline.AdmissionScheduler): a
    `jax.device_get` / `.block_until_ready` / `np.asarray`-on-device-value
    under the scheduler lock serializes EVERY tenant's admission behind
    one tenant's device round trip — the fairness lock becomes a
    head-of-line blocker and a lagging tenant's weight can't help it.
    Lag/weight providers must read host state (LSN deltas, counters).
    Lexical, same sanctioning machinery as @dispatch_stage: the frame
    flag inherits into nested defs and lambdas (inline lag providers),
    not across call edges — keep helpers called from the grant path
    device-free or annotate them too."""

    name = "admission-blocking-fetch"

    def on_call(self, ctx: LintContext, node: ast.Call) -> None:
        if not ctx.in_admission_path:
            return
        dotted = dotted_name(node.func)
        subject = None
        if dotted in ADMISSION_DEVICE_DOTTED:
            subject = dotted
        else:
            term = terminal_name(node.func)
            if term in ADMISSION_DEVICE_METHODS \
                    and isinstance(node.func, ast.Attribute):
                subject = f".{term}"
        if subject is None:
            return
        ctx.report(
            self.name, node, subject,
            f"device call `{subject}` inside an @admission_path function "
            f"head-of-line-blocks every tenant's admission; read host "
            f"state in grant decisions and keep device traffic in the "
            f"dispatch/fetch stages")


# -- rule 15 ------------------------------------------------------------------

#: unfiltered full-table-list store reads: against a SHARED store these
#: return EVERY shard's tables, and shard-scoped code acting on the full
#: list re-copies / re-owns / purges tables a sibling pod owns
CROSS_SHARD_FULL_READS = frozenset({"get_table_states"})


class CrossShardTableAccess(Rule):
    """`X.get_table_states()` with no arguments inside a `@shard_scoped`
    function (etl_tpu/sharding): shard-scoped code must read through the
    shard view (`ShardScopedStore.owned_table_states()`), which filters
    the shared store down to the tables this shard's ShardMap slice owns.
    Lexical, same sanctioning machinery as @dispatch_stage: the frame
    flag inherits into nested defs and lambdas, not across call edges —
    keep helpers called from shard-scoped code on the filtered view or
    annotate them too. A deliberate cross-shard sweep (the coordinator's
    global view) carries an inline ignore with a justification."""

    name = "cross-shard-table-access"

    def on_call(self, ctx: LintContext, node: ast.Call) -> None:
        if not ctx.in_shard_scoped:
            return
        term = terminal_name(node.func)
        if term not in CROSS_SHARD_FULL_READS \
                or not isinstance(node.func, ast.Attribute):
            return
        if node.args or node.keywords:
            return  # a filter argument makes the read shard-aware
        ctx.report(
            self.name, node, f".{term}()",
            f"unfiltered `.{term}()` inside a @shard_scoped function "
            f"returns EVERY shard's tables on a shared store; read "
            f"through the shard view (owned_table_states()) or justify "
            f"the cross-shard sweep with an inline ignore")


# -- rule 16 ------------------------------------------------------------------

#: forbidden inside the autoscaling decision path: every blocking-I/O
#: sink rule 1 knows about, PLUS all device traffic (the admission set —
#: fetches AND uploads). The decision must be a pure function of the
#: already-sampled signal history: a blocking call ties decision latency
#: to an external service, a device call ties shard-count control to
#: accelerator health — the dependency loop an autoscaler must not have
#: (a sick device delaying the decision that would route around it).
CONTROL_LOOP_BLOCKING_DOTTED = BLOCKING_DOTTED | ADMISSION_DEVICE_DOTTED
CONTROL_LOOP_BLOCKING_BARE = BLOCKING_BARE
CONTROL_LOOP_BLOCKING_METHODS = ADMISSION_DEVICE_METHODS


class ControlLoopBlockingIo(Rule):
    """Blocking I/O or device traffic inside the autoscaling control
    loop's decision path (`@control_loop`, etl_tpu/autoscale): the
    signal→policy→decision computation must stay a pure, seeded-
    replayable function of (SignalFrame history, config) — that is what
    makes the policy property-testable and the decision trace
    deterministic per seed. Sampling (async store/registry reads) and
    actuation (coordinator/orchestrator calls) live OUTSIDE the marked
    path. Lexical, same sanctioning machinery as @dispatch_stage: the
    frame flag inherits into nested defs and lambdas (inline capacity
    estimators, sort keys), not across call edges — keep helpers called
    from the decision path free of blocking I/O or annotate them too."""

    name = "control-loop-blocking-io"

    def on_call(self, ctx: LintContext, node: ast.Call) -> None:
        if not ctx.in_control_loop:
            return
        dotted = dotted_name(node.func)
        subject = None
        if dotted in CONTROL_LOOP_BLOCKING_DOTTED:
            subject = dotted
        elif isinstance(node.func, ast.Name) \
                and node.func.id in CONTROL_LOOP_BLOCKING_BARE:
            subject = node.func.id
        else:
            term = terminal_name(node.func)
            if term in CONTROL_LOOP_BLOCKING_METHODS \
                    and isinstance(node.func, ast.Attribute):
                subject = f".{term}"
        if subject is None:
            return
        ctx.report(
            self.name, node, subject,
            f"blocking/device call `{subject}` inside a @control_loop "
            f"function: the autoscale decision path must be a pure "
            f"function of the sampled signal history — move I/O to the "
            f"collector (sampling) or the controller's actuation, or "
            f"justify with an inline ignore")


# -- rule 17 ------------------------------------------------------------------

#: the durability-wait terminal: awaiting it inline in a dispatch path
#: re-serializes the pipeline to one ack round-trip per batch
DURABILITY_WAIT_METHODS = frozenset({"wait_durable"})


class InlineDurabilityWait(Rule):
    """`await ack.wait_durable()` inside a `@flush_path` function (the
    apply loop's flush machinery, the copy partition's chunk/drain path):
    the bounded ack window (runtime/ack_window.py) OWNS durability waits
    — it chains submissions in WAL order, overlaps up to
    `BatchConfig.write_window` ack round-trips, advances durable
    progress over the contiguous acked prefix, and carries the per-entry
    timeout bounds and overlap telemetry. A bare inline wait silently
    reintroduces the one-in-flight ceiling (`batch_size / ack_rtt`) the
    window removes — route the ack through `AckWindow.dispatch` /
    `CopyAckWindow.add`, or justify a deliberate inline barrier with an
    inline ignore. Lexical, same sanctioning machinery as
    @dispatch_stage: the frame flag inherits into nested defs and
    lambdas (the flush submit closures), not across call edges."""

    name = "inline-durability-wait"

    def on_call(self, ctx: LintContext, node: ast.Call) -> None:
        if not ctx.in_flush_path:
            return
        term = terminal_name(node.func)
        if term not in DURABILITY_WAIT_METHODS \
                or not isinstance(node.func, ast.Attribute):
            return
        ctx.report(
            self.name, node, f".{term}()",
            f"bare `.{term}()` inside a @flush_path function "
            f"re-serializes the pipeline to one ack round-trip per "
            f"batch; the ack window owns durability waits — dispatch "
            f"through AckWindow/CopyAckWindow, or justify an inline "
            f"barrier with an inline ignore")


# -- rule 18 ------------------------------------------------------------------

#: destination write-path entry points: an `except Exception` that
#: re-raises unwrapped from one of these hands the worker retry layer a
#: failure with no ErrorKind — which the retry classifier treats as
#: UNKNOWN/TIMED and, worse, the poison-isolation protocol can never
#: trigger on (models.errors.POISON_KINDS needs a concrete kind)
DESTINATION_WRITE_FNS = frozenset({
    "write_events", "write_table_rows", "write_event_batches",
    "write_table_batch",
})

#: names whose appearance in a raised expression mean the failure was
#: classified: an EtlError construction, the shared classifiers, or
#: anything carrying an ErrorKind
_CLASSIFIED_RAISE_NAMES = ("EtlError", "ErrorKind", "etl_error",
                           "classify_http_error",
                           "classify_write_exception")


class UnclassifiedDestinationError(Rule):
    """Broad `except Exception` (or bare `except`) on a destination
    write path or inside a `@flush_path` function whose body RE-RAISES
    without wrapping in `EtlError`/`ErrorKind`: the unclassified
    exception reaches the worker retry layer bare, where the retry
    classifier falls back to UNKNOWN (blind timed retry) and the
    poison-isolation protocol (runtime/poison.py) can never key on it —
    a permanent rejection retries forever instead of bisecting to the
    poison row. Wrap through `destinations.util.classify_write_exception`
    / `classify_http_error` (or construct a typed EtlError), or justify
    a deliberate passthrough with an inline ignore. Handlers that never
    re-raise are rule 5's (cancellation-swallow) business, not this
    rule's. Lexical: the flush-path frame flag inherits into nested
    defs/lambdas; the write-path function-name scope covers nested defs
    too (the retried `attempt()` closures)."""

    name = "unclassified-destination-error"

    @staticmethod
    def _raise_classified(node: ast.Raise) -> bool:
        if node.exc is None:
            return False  # bare re-raise: whatever was caught, unwrapped
        for n in ast.walk(node.exc):
            label = None
            if isinstance(n, ast.Name):
                label = n.id
            elif isinstance(n, ast.Attribute):
                label = n.attr
            if label in _CLASSIFIED_RAISE_NAMES:
                return True
        return False

    @staticmethod
    def _in_scope(ctx: LintContext) -> bool:
        if ctx.in_flush_path:
            return True
        if ctx.rel_path.split("/", 1)[0] != "destinations":
            return False
        return any(part in DESTINATION_WRITE_FNS
                   for part in ctx.scope.split("."))

    def on_except_handler(self, ctx: LintContext,
                          node: ast.ExceptHandler) -> None:
        if not self._in_scope(ctx):
            return
        names = set(handler_type_names(node))
        if not ({"Exception", "<bare>"} & names):
            return
        raises = [n for stmt in node.body for n in ast.walk(stmt)
                  if isinstance(n, ast.Raise)]
        if not raises:
            return  # swallowing is cancellation-swallow's concern
        if all(self._raise_classified(r) for r in raises):
            return
        caught = "except" if "<bare>" in names else "except Exception"
        ctx.report(
            self.name, node, caught,
            f"`{caught}` on a destination write path re-raises without "
            f"wrapping in EtlError/ErrorKind: the unclassified failure "
            f"reaches the retry layer bare (blind UNKNOWN retry, and "
            f"the poison-isolation trigger can never fire) — wrap via "
            f"destinations.util.classify_write_exception / "
            f"classify_http_error, or justify with an inline ignore")


# -- rule 19 ------------------------------------------------------------------

#: logger-method terminals whose arguments are emitted to logs. `.log`
#: rides along: any `.log(...)`-shaped call carrying a secret argument
#: deserves a look regardless of the receiver.
LOG_SINK_METHODS = frozenset({
    "debug", "info", "warning", "error", "exception", "critical", "log",
})

#: metric-emission calls whose `labels=` values are exported to the
#: metrics endpoint (runtime/telemetry.py registry surface)
METRIC_LABEL_CALLS = frozenset({
    "counter_inc", "gauge_set", "histogram_observe", "labels",
})

#: attribute/variable names bound to secret-typed config fields. The
#: config loader (config/load.py) wraps these in `Secret`, whose repr()
#: redacts — but str()/f-string INTERPOLATION yields the raw value
#: (Secret subclasses str), so reaching a log sink is a leak either way.
#: Mirrors the api/orchestrator.py redaction list.
SECRET_NAMES = frozenset({
    "password", "api_key", "secret_key", "private_key_pem",
    "catalog_token", "auth_token", "access_token",
})
#: deliberately NOT including bare "_token": replication progress tokens
#: (offset_token, continuation/page tokens) are identifiers, not secrets
SECRET_NAME_SUFFIXES = ("_password", "_secret", "_api_key",
                        "_auth_token", "_access_token")
#: name prefixes that mark a DERIVED non-secret (presence flags,
#: switches): `has_password` is shape, not value
_NONSECRET_PREFIXES = ("has_", "is_", "use_", "with_", "without_",
                       "no_", "needs_", "require_", "allow_")


def _is_secret_name(name: str) -> bool:
    if name.startswith(_NONSECRET_PREFIXES):
        return False
    return name in SECRET_NAMES or name.endswith(SECRET_NAME_SUFFIXES)


def _secret_subjects(tree: ast.AST) -> "list[str]":
    """Secret-valued subexpressions anywhere under `tree`, normalized:
    `.expose()` unwrap calls, secret-named attributes (`cfg.password`),
    and bare secret-named locals. Order is source order (ast.walk)."""
    out = []
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "expose":
            out.append(".expose()")
        elif isinstance(n, ast.Attribute):
            if _is_secret_name(n.attr):
                out.append(f".{n.attr}")
        elif isinstance(n, ast.Name):
            if _is_secret_name(n.id):
                out.append(n.id)
    return out


class SecretInLog(Rule):
    """A secret-typed value (config `Secret` fields, `.expose()` unwraps,
    secret-named variables) interpolated into a logging call, an
    exception message, or a metric label value.

    `Secret.__repr__` redacts, but `Secret` subclasses `str`: %-format,
    `.format`, and f-string interpolation all emit the RAW value, and an
    `.expose()` result is a plain str with no protection at all. Log
    pipelines, exception trackers, and metric endpoints are all
    exported surfaces — log presence/shape (`"password=[set]"`), never
    the value."""

    name = "secret-in-log"

    def on_call(self, ctx: LintContext, node: ast.Call) -> None:
        term = terminal_name(node.func)
        if isinstance(node.func, ast.Attribute) \
                and term in LOG_SINK_METHODS:
            targets = list(node.args) + [kw.value for kw in node.keywords]
            sink = f"logging call `.{term}(…)`"
        elif term in METRIC_LABEL_CALLS:
            targets = [kw.value for kw in node.keywords
                       if kw.arg == "labels"]
            sink = f"metric labels of `{term}(…)`"
        elif any(isinstance(a, ast.Raise) for a in ctx.ancestors()):
            targets = list(node.args) + [kw.value for kw in node.keywords]
            sink = "exception message"
        else:
            return
        seen: set = set()
        for t in targets:
            for subject in _secret_subjects(t):
                if subject in seen:
                    continue
                seen.add(subject)
                ctx.report(
                    self.name, node, subject,
                    f"secret value `{subject}` reaches {sink}: Secret's "
                    f"repr redacts but str/f-string interpolation emits "
                    f"the raw value — log presence or shape "
                    f"(\"password=[set]\"), never the secret itself")


# -- rule 20 ------------------------------------------------------------------

#: CDC/copy write entry points that land data WITHOUT coordinates when
#: called from a transactional-commit seam; the `*_committed` variants
#: carry their range and are always fine
UNCOORDINATED_WRITE_FNS = frozenset({
    "write_events", "write_event_batches", "write_table_rows",
    "write_table_batch",
})


class UncoordinatedTransactionalWrite(Rule):
    """A `@transactional_commit` function (the exactly-once seam,
    docs/destinations.md) that performs a CDC write while NEVER
    consulting its commit-range parameter: the data lands but the WAL
    coordinate range is never recorded with it, so a crash-restart
    cannot recover the sink's high-water mark and the destination
    silently degrades to at-least-once while still ADVERTISING
    `supports_transactional_commit()` — the worst of both (the apply
    loop trusts the seam, recovery trusts the marker). Every committed
    write path must derive its dedup token / MERGE key / snapshot
    property / offset from the `commit` argument (or explicitly forward
    it to an inner `*_committed` call); a deliberate pass-through (e.g.
    offset-token sinks whose plain path already carries coordinates)
    justifies itself by touching `commit` to decide, or with an inline
    ignore. Whole-function and lexical: nested defs/lambdas (retried
    write closures) belong to the marked function's body."""

    name = "uncoordinated-transactional-write"

    @staticmethod
    def _commit_param(node) -> "str | None":
        args = node.args
        params = [a.arg for a in args.posonlyargs + args.args
                  + args.kwonlyargs]
        if "commit" in params:
            return "commit"
        # the base seam signature is (self, events, commit)
        if len(params) > 2 and params[0] in ("self", "cls"):
            return params[2]
        if len(params) > 1 and params[0] not in ("self", "cls"):
            return params[1]
        return None

    def on_function(self, ctx: LintContext, node) -> None:
        from .visitor import TRANSACTIONAL_COMMIT_DECORATORS

        if ctx.in_transactional_commit:
            return  # a nested def: the enclosing marked frame's analysis
            # already covered this body
        decorators = {terminal_name(d.func if isinstance(d, ast.Call)
                                    else d)
                      for d in node.decorator_list}
        if not (decorators & TRANSACTIONAL_COMMIT_DECORATORS):
            return
        commit = self._commit_param(node)
        consulted = commit is not None and any(
            isinstance(n, ast.Name) and n.id == commit
            and isinstance(n.ctx, ast.Load)
            for stmt in node.body for n in ast.walk(stmt))
        if consulted:
            return
        for stmt in node.body:
            for n in ast.walk(stmt):
                if not isinstance(n, ast.Call):
                    continue
                term = terminal_name(n.func)
                if term not in UNCOORDINATED_WRITE_FNS:
                    continue
                ctx.report(
                    self.name, n, f"{term}()",
                    f"`{term}()` inside a @transactional_commit function "
                    f"that never consults its commit-range parameter"
                    f"{f' `{commit}`' if commit else ''}: the data lands "
                    f"without its WAL coordinates, so recovery cannot "
                    f"rebuild the high-water mark — derive the dedup "
                    f"token / commit marker from the commit range, or "
                    f"justify a deliberate pass-through with an inline "
                    f"ignore")


# -- entry points -------------------------------------------------------------

def default_rules() -> list[Rule]:
    return [
        BlockingCallInAsync(),
        DeviceSyncInAsync(),
        OrphanedTask(),
        UnawaitedCoroutine(),
        CancellationSwallow(),
        HotLoopHostTransfer(),
        UnboundedRetry(),
        UnboundedAwait(),
        HotLoopRowMaterialization(),
        AdmissionBlockingFetch(),
        CrossShardTableAccess(),
        ControlLoopBlockingIo(),
        InlineDurabilityWait(),
        UnclassifiedDestinationError(),
        SecretInLog(),
        UncoordinatedTransactionalWrite(),
    ]


#: whole-program rules (etl_tpu/analysis/interproc.py) — they have no
#: per-module Rule class; listed here so --list-rules and suppression
#: docs stay complete
INTERPROC_RULE_NAMES = (
    "arena-lease-leak",
    "donated-buffer-use",
    "lock-held-across-await",
    "lock-order-inversion",
    # concurrency tier (etl_tpu/analysis/concurrency.py)
    "unsynchronized-shared-mutation",
    "loop-state-from-thread",
    "coordinator-store-bypass",
)

RULE_NAMES = tuple(r.name for r in default_rules()) + INTERPROC_RULE_NAMES


def analyze_source(source: str, rel_path: str,
                   rules: list[Rule] | None = None,
                   interprocedural: bool = True) -> list[Finding]:
    """Lint one module's source. `rel_path` drives path-scoped rules and
    fixture trees mirror the package layout, so `runtime/foo.py` gets the
    runtime/ rule scoping whether it is real or a test snippet. The
    whole-program pass runs over the single module (cross-module targets
    stay unresolved, by design)."""
    import ast as ast_mod

    from .interproc import ModuleUnit, analyze_interprocedural
    from .visitor import Suppressions

    tree = ast_mod.parse(source, filename=rel_path)
    supp = Suppressions(source)
    findings = lint_module(source, rel_path, rules or default_rules(),
                           tree=tree, suppressions=supp)
    if interprocedural:
        findings = findings + analyze_interprocedural(
            [ModuleUnit(canonical_path(rel_path), source, tree, supp)])
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_python_files(path: str | Path) -> "list[Path]":
    p = Path(path)
    if p.is_file():
        return [p]
    return sorted(f for f in p.rglob("*.py")
                  if "__pycache__" not in f.parts)


def analyze_paths(paths, root: "str | None" = None,
                  scanned: "list[str] | None" = None,
                  interprocedural: bool = True,
                  lexical: bool = True,
                  units_out: "list | None" = None) -> list[Finding]:
    """Lint every .py under `paths`. Rel paths are computed against each
    argument (directory args act as scan roots), then canonicalized, so
    `analyze_paths(["etl_tpu"])` and `analyze_paths(["."])` fingerprint
    identically. When `scanned` is given, the canonical path of every
    file visited is appended to it (clean files included) — baseline
    updates need the full scan scope, not just files with findings.

    All modules are parsed first, then the per-module lexical pass and
    the whole-program interprocedural pass run over the same trees —
    cross-module call chains resolve only within the scanned set, so a
    scoped run sees a smaller closure (fingerprints of what it DOES see
    are identical to the full run's). `units_out`, when given, receives
    the interproc ModuleUnits (path, source, tree, suppressions) —
    `--check-baseline` reads per-module suppression usage from them."""
    import ast as ast_mod

    from .interproc import ModuleUnit, analyze_interprocedural
    from .visitor import Suppressions

    units: list = []
    for arg in paths:
        if not Path(arg).exists():
            # a typo'd path silently scanning nothing would keep CI green
            raise OSError(f"no such path: {arg}")
        for f in iter_python_files(arg):
            resolved = f.resolve()
            # fingerprint identity must not depend on HOW the file was
            # reached: `analysis etl_tpu`, `analysis etl_tpu/api`, and
            # `analysis etl_tpu/api/db.py` all canonicalize db.py to
            # api/db.py, or path-scoped rules and baseline matching
            # silently break for scoped runs. Package files key off the
            # etl_tpu segment of the FULL path (caveat: a checkout whose
            # root dir is itself named etl_tpu would confuse this);
            # mirror trees (fixtures) key off the scan root.
            if root is not None:
                base = Path(root).resolve()
            elif _PACKAGE_SEGMENT in resolved.parts:
                base = None  # canonical_path strips to the package
            elif Path(arg).is_dir():
                base = Path(arg).resolve()
            else:
                base = Path.cwd()
            rel = resolved
            if base is not None:
                try:
                    rel = resolved.relative_to(base)
                except ValueError:
                    pass
            canon = canonical_path(rel.as_posix())
            if scanned is not None:
                scanned.append(canon)
            source = f.read_text(encoding="utf-8")
            try:
                tree = ast_mod.parse(source, filename=str(f))
            except SyntaxError as e:
                raise SyntaxError(
                    f"etl-lint: cannot parse {f}: {e}") from e
            units.append(ModuleUnit(canon, source, tree,
                                    Suppressions(source)))

    findings: list[Finding] = []
    if lexical:
        for u in units:
            findings.extend(lint_module(u.source, u.path, default_rules(),
                                        tree=u.tree,
                                        suppressions=u.suppressions))
    if interprocedural:
        findings.extend(analyze_interprocedural(units))
    if units_out is not None:
        units_out.extend(units)
    findings.sort(key=lambda x: (x.path, x.line, x.col, x.rule))
    return findings


def repo_package_dir() -> Path:
    """The installed etl_tpu package directory (the default scan target)."""
    return Path(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
