"""Concurrency tier: lockset + happens-before race detection.

Static twin of the chaos corpus, in the style of Eraser (Savage et al.
1997) and FastTrack (Flanagan & Freund 2009), adapted to lint time: the
domain inference in domains.py plays the role of thread identity, the
rule-11 lock tables play the role of the dynamic lockset, and the
happens-before edges a dynamic detector would observe (fork, join,
message receive) become STATIC sanctions the analysis recognizes:

  lock-held           — every write to the attribute holds one common
                        `threading.Lock` (Eraser's lockset invariant;
                        asyncio locks do NOT count — they serialize
                        loop tasks, not OS threads).
  init-before-spawn   — writes inside `__init__` happen before any
                        thread the object spawns can observe them
                        (fork edge).
  queue/condition     — writes under a `threading.Condition` guard are
                        handoff-mediated (the Condition's lock IS the
                        lockset member, so this falls out of lock-held
                        once Condition counts as a lock ctor).
  immutable-after-publish / contextvar-scoped — frozen dataclasses and
                        `ContextVar.set()` never appear as attribute
                        rebinds, so they are sanctioned by construction
                        (documented, not detected).
  @handoff            — an explicit ownership-transfer seam
                        (annotations.py): the function establishes its
                        own happens-before edge (publish via future/
                        queue/journal) that the lockset cannot see.

Three rules, all chain-carrying and fingerprint-stable:

  unsynchronized-shared-mutation — an attribute (or module global)
      written from ≥ 2 execution domains with no common thread lock
      across the writes. Anchored at the first unguarded write.
  loop-state-from-thread — thread-domain code calling loop-affine
      scheduling surfaces (`.call_soon`, `.create_task`,
      `asyncio.ensure_future`, …) directly; `call_soon_threadsafe` /
      `run_coroutine_threadsafe` are the sanctioned crossings.
  coordinator-store-bypass — coordinator-domain code mutating a
      multi-process-reachable StateStore surface outside a @handoff
      persist-then-actuate seam.

Precision contract (docs/static-analysis.md): writes are syntactic
`self.x` rebinds and declared-global rebinds — container mutation
(`self.d[k] = v` mutates the dict, not the attribute binding) is out of
scope, as is aliasing through locals. Domains come from resolved call
edges only, so a callable handed to an external framework needs a
`@domain` pin to participate.
"""

from __future__ import annotations

import ast

from .domains import (COORDINATOR, LOOP, THREAD_DOMAINS, DomainMap,
                      infer_domains, is_handoff)
from .findings import Finding
from .visitor import terminal_name

#: path heads the shared-mutation/loop-affinity rules police (chaos/,
#: testing/, benchmarks/ double deliberately race or are single-process
#: test scaffolding; top-level production modules listed by filename —
#: their canonical path has no directory segment)
CONCURRENCY_RULE_SCOPES = (
    "runtime", "ops", "destinations", "postgres", "store", "supervision",
    "api", "telemetry", "parallel", "dlq", "fleet", "autoscale",
    "sharding", "replicator.py", "maintenance.py",
    "maintenance_coordination.py", "retry.py",
)

#: loop-affine scheduling surfaces: calling these from a worker thread
#: corrupts the loop's internal structures (asyncio documents them as
#: not thread-safe). `call_soon_threadsafe`/`run_coroutine_threadsafe`
#: are different terminals, so the sanctioned crossings never match.
LOOP_AFFINE_METHODS = frozenset({
    "call_soon", "call_later", "call_at", "create_task", "ensure_future",
})
LOOP_AFFINE_DOTTED = frozenset({
    "asyncio.create_task", "asyncio.ensure_future",
})

#: StateStore surfaces other PROCESSES act on (store/base.py): shard
#: fences, autoscale/fleet journals and specs. Mutating one outside a
#: persist-then-actuate @handoff seam lets a crashed coordinator leave
#: actuation and journal disagreeing — the exact split-brain the
#: journal protocol exists to prevent.
MULTIPROC_STORE_MUTATORS = frozenset({
    "update_shard_assignment", "update_autoscale_journal",
    "update_fleet_spec", "update_fleet_journal",
})

CONCURRENCY_RULE_NAMES = (
    "unsynchronized-shared-mutation",
    "loop-state-from-thread",
    "coordinator-store-bypass",
)


def _in_scope(path: str) -> bool:
    return path.split("/", 1)[0] in CONCURRENCY_RULE_SCOPES


def _own_class_name(fn) -> "str | None":
    scope = fn
    while scope is not None and scope.class_name is None:
        scope = scope.parent
    return scope.class_name if scope is not None else None


def _flatten_targets(node):
    if isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            yield from _flatten_targets(el)
    elif isinstance(node, ast.Starred):
        yield from _flatten_targets(node.value)
    else:
        yield node


class _Write:
    """One attribute/global write site with its Eraser lockset."""

    __slots__ = ("fn", "node", "locks", "is_init", "domains")

    def __init__(self, fn, node, locks, is_init, domains):
        self.fn = fn
        self.node = node
        self.locks = locks  # frozenset of held THREAD-lock ids
        self.is_init = is_init
        self.domains = domains  # relevant domains reaching fn


def _walk_writes(fn, tables, on_write):
    """Walk `fn`'s own body tracking held THREAD locks; report every
    `self.x` rebind and declared-global rebind. Mirrors interproc's
    `_walk_holding` (nested defs own their activation and are skipped)
    but keys on assignment statements instead of calls/awaits."""
    globals_decl: set = set()
    body = getattr(fn.node, "body", None)
    if not isinstance(body, list):
        return

    def collect_globals(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested defs declare their own globals
        if isinstance(node, ast.Global):
            globals_decl.update(node.names)
        for child in ast.iter_child_nodes(node):
            collect_globals(child)

    for stmt in body:
        collect_globals(stmt)

    def targets_of(node):
        if isinstance(node, ast.Assign):
            return node.targets
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            return (node.target,) if node.value is not None \
                or isinstance(node, ast.AugAssign) else ()
        return ()

    def walk(node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = list(held)
            for item in node.items:
                walk(item.context_expr, new_held)
                lock = tables.identify(fn, item.context_expr)
                if lock is not None and not lock[1]:  # thread locks only
                    new_held = new_held + [lock[0]]
            for stmt in node.body:
                walk(stmt, new_held)
            return
        for tgt in targets_of(node):
            for el in _flatten_targets(tgt):
                if isinstance(el, ast.Attribute) \
                        and isinstance(el.value, ast.Name) \
                        and el.value.id == "self":
                    on_write(("self", el.attr), frozenset(held), node)
                elif isinstance(el, ast.Name) and el.id in globals_decl:
                    on_write(("global", el.id), frozenset(held), node)
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for stmt in body:
        walk(stmt, [])


def _domain_chain(dm: DomainMap, fn, sink_line=None):
    """(chain, chain_sites) from the thread-preferred witness, rendered
    exactly like interproc chains: last hop's site is the sink line in
    the reached function's own module. Depth-0 (the root IS the scope)
    collapses to empty per the chain convention."""
    w = dm.witness(fn)
    if w is None or len(w.chain) <= 1:
        return (), ()
    sites = w.chain_sites
    if sink_line is not None:
        sites = sites[:-1] + ((fn.module.path, sink_line),)
    return w.chain, sites


def _unsynchronized_shared_mutation(project, dm, tables, supp):
    relevant = THREAD_DOMAINS | {LOOP}
    findings: list[Finding] = []
    for path in sorted(project.modules):
        if not _in_scope(path):
            continue
        m = project.modules[path]
        writes: dict = {}  # (class|<module>, attr) -> [_Write]
        for qual in sorted(m.functions):
            fn = m.functions[qual]
            doms = dm.of(fn) & relevant
            if not doms or is_handoff(fn):
                continue
            cls = _own_class_name(fn)
            is_init = qual == (f"{cls}.__init__" if cls else "__init__")

            def on_write(key, locks, node, fn=fn, cls=cls,
                         is_init=is_init, doms=doms):
                kind, name = key
                owner = cls if kind == "self" else "<module>"
                if kind == "self" and cls is None:
                    return  # `self` outside a class: not shared state
                writes.setdefault((owner, name), []).append(
                    _Write(fn, node, locks, is_init, doms))

            _walk_writes(fn, tables, on_write)
        for (owner, attr) in sorted(writes):
            sites = writes[(owner, attr)]
            live = [w for w in sites if not w.is_init]
            if not live:
                continue  # init-before-spawn: all writes precede fork
            doms = frozenset().union(*(w.domains for w in live))
            if len(doms) < 2:
                continue
            lockset = frozenset.intersection(*(w.locks for w in live))
            if lockset:
                continue  # Eraser invariant holds: a common thread lock
            live.sort(key=lambda w: (w.node.lineno, w.node.col_offset))
            anchor = next((w for w in live if not w.locks), live[0])
            line = anchor.node.lineno
            s = supp.get(path)
            if s is not None and s.suppresses(
                    "unsynchronized-shared-mutation", line):
                continue
            detail = f"{owner}.{attr}"
            chain, chain_sites = _domain_chain(dm, anchor.fn, line)
            findings.append(Finding(
                rule="unsynchronized-shared-mutation", path=path,
                line=line, col=anchor.node.col_offset + 1,
                scope=anchor.fn.qualname, detail=detail,
                message=f"`{detail}` is written from domains "
                        f"{{{', '.join(sorted(doms))}}} with no common "
                        f"thread lock — hold one threading.Lock at every "
                        f"write, hand off through a queue/future, or mark "
                        f"the ownership-transfer seam @handoff",
                chain=chain, chain_sites=chain_sites))
    return findings


def _loop_state_from_thread(project, dm, supp):
    findings: list[Finding] = []
    for fn in list(project.iter_functions()):
        path = fn.module.path
        if not _in_scope(path):
            continue
        tdoms = dm.of(fn) & THREAD_DOMAINS
        if not tdoms or is_handoff(fn):
            continue
        for site in fn.calls:
            subject = None
            if site.external in LOOP_AFFINE_DOTTED:
                subject = site.external
            else:
                term = terminal_name(site.node.func)
                if term in LOOP_AFFINE_METHODS \
                        and isinstance(site.node.func, ast.Attribute):
                    subject = f".{term}"
            if subject is None:
                continue
            s = supp.get(path)
            if s is not None and s.suppresses(
                    "loop-state-from-thread", site.line):
                continue
            chain, chain_sites = _domain_chain(dm, fn, site.line)
            findings.append(Finding(
                rule="loop-state-from-thread", path=path,
                line=site.line, col=site.col + 1,
                scope=fn.qualname, detail=subject,
                message=f"`{subject}` called from thread domain"
                        f"{{{', '.join(sorted(tdoms))}}} — asyncio's "
                        f"scheduling surfaces are not thread-safe; cross "
                        f"with call_soon_threadsafe()/"
                        f"run_coroutine_threadsafe(), or resolve a "
                        f"future the loop awaits",
                chain=chain, chain_sites=chain_sites))
    return findings


def _coordinator_store_bypass(project, dm, supp):
    findings: list[Finding] = []
    for fn in list(project.iter_functions()):
        path = fn.module.path
        if COORDINATOR not in dm.of(fn) or is_handoff(fn):
            continue
        for site in fn.calls:
            term = terminal_name(site.node.func)
            if term not in MULTIPROC_STORE_MUTATORS \
                    or not isinstance(site.node.func, ast.Attribute):
                continue
            s = supp.get(path)
            if s is not None and s.suppresses(
                    "coordinator-store-bypass", site.line):
                continue
            subject = f".{term}"
            w = dm.info(fn, COORDINATOR)
            chain = w.chain if w is not None and len(w.chain) > 1 else ()
            sites = ()
            if chain:
                sites = w.chain_sites[:-1] + ((path, site.line),)
            findings.append(Finding(
                rule="coordinator-store-bypass", path=path,
                line=site.line, col=site.col + 1,
                scope=fn.qualname, detail=subject,
                message=f"`{subject}` mutates a multi-process-reachable "
                        f"StateStore surface from the coordinator domain "
                        f"outside a persist-then-actuate seam — route the "
                        f"write through the @handoff journal method so a "
                        f"crash cannot leave actuation and journal "
                        f"disagreeing",
                chain=chain, chain_sites=sites))
    return findings


def analyze_concurrency(project, supp) -> list[Finding]:
    """The concurrency tier over an already-built Project. `supp` maps
    module path → Suppressions, as in analyze_interprocedural."""
    from .interproc import _LockTables  # deferred: interproc calls us

    dm = infer_domains(project)
    tables = _LockTables(project)
    findings: list[Finding] = []
    findings += _unsynchronized_shared_mutation(project, dm, tables, supp)
    findings += _loop_state_from_thread(project, dm, supp)
    findings += _coordinator_store_bypass(project, dm, supp)
    return findings
