"""The per-program contracts the IR tier checks.

Each checker is a pure function over introspection artifacts the runner
already holds (jaxpr, lowered StableHLO text, compiled HLO text, output
avals) and returns a list of (detail, message) violation pairs — empty
means the contract holds. Keeping the checkers artifact-in/tuples-out
makes them trivially falsifiable from tests without a catalog or a CLI:
build a deliberately-bad program, hand its artifacts to the checker,
assert it fires.

Contract catalog (see docs/static-analysis.md "IR tier"):

  ir-host-callback    no pure/io/debug callback primitive anywhere in a
                      @hot_loop program's jaxpr (a host round-trip per
                      dispatch is a silent perf cliff on real TPUs)
  ir-donation         declared `donate_argnums` must be realized as
                      input/output aliasing in the lowered module on
                      accelerator backends — and must NOT be declared at
                      all on CPU, where the engine deliberately skips
                      donation (_donation_supported)
  ir-collective       mesh-sharded decode programs compile to zero
                      forward-path collectives (the PR 8 shard-local
                      invariant, machine-checked on compiled HLO)
  ir-widening         no 64-bit element types (f64/i64/u64) introduced by
                      convert_element_type or flowing out of any
                      equation, outside an explicit allowlist
  ir-output-budget    fetched-output bytes computed from the output
                      avals stay within the per-layout budget (packed
                      words + filter metadata + slack) — the
                      selectivity-scaling property as a static bound
  ir-egress-output-budget
                      egress (wire-encoding) programs fetch at most the
                      declared encoded-bytes budget: R·ΣW text bytes +
                      one int32 length per rendered field per row +
                      slack (ops/egress.py) — fetched bytes scale with
                      ENCODED OUTPUT, the tentpole property
  ir-canonical-dedup  permuted-column specs sharing a canonical layout
                      must lower to byte-identical serialized IR
"""

from __future__ import annotations

import re

import numpy as np

#: jaxpr primitives that round-trip through the host. Matched exactly
#: first, then by substring as a forward guard for new callback flavors.
CALLBACK_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
})

#: 64-bit element types the decode path must never widen to: the packed
#: output format is u32 words and every parser is specified in 32-bit
#: arithmetic; an f64/i64 creeping in doubles register pressure and
#: transfer bytes on TPU for zero precision the format can represent.
WIDE_DTYPES = frozenset({"float64", "int64", "uint64", "complex128"})

#: primitives allowed to touch 64-bit types. Deliberately tiny:
#: nothing on the current forward path needs one.
WIDENING_ALLOWLIST: frozenset = frozenset()

#: compiled-HLO opcodes that are cross-shard collectives. `\b...\b(?!-)`?
#: — HLO spells variants like `all-gather-start`, so match the stem.
_COLLECTIVE_RE = re.compile(
    r"\b(all-gather|all-reduce|collective-permute|all-to-all|"
    r"reduce-scatter|collective-broadcast)\b")

#: marker StableHLO attaches to donated inputs in the lowered module
_ALIASING_MARKER = "tf.aliasing_output"

#: accelerator backends where the engine declares donation
#: (mirrors ops.engine._donation_supported)
ACCEL_BACKENDS = ("tpu", "gpu")


def iter_eqns(jaxpr):
    """Every equation in `jaxpr` and, recursively, in any sub-jaxpr an
    equation carries in its params (pjit bodies, scan/cond branches,
    custom_jvp call jaxprs, ...)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                inner = getattr(sub, "jaxpr", sub)
                if hasattr(inner, "eqns"):
                    yield from iter_eqns(inner)


def check_host_callback(jaxpr) -> list:
    """ir-host-callback: callback primitives anywhere in the jaxpr."""
    out = []
    seen = set()
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in seen:
            continue
        if name in CALLBACK_PRIMITIVES or "callback" in name:
            seen.add(name)
            out.append((name,
                        f"hot-loop program contains host callback "
                        f"primitive `{name}`: every dispatch round-trips "
                        f"to the host"))
    return out


def check_donation(stablehlo_text: str, declared: bool,
                   backend: str) -> list:
    """ir-donation: declared donation must match realized aliasing for
    the backend. Three failure modes, each its own detail so baselines
    stay precise."""
    realized = _ALIASING_MARKER in stablehlo_text
    accel = backend in ACCEL_BACKENDS
    if declared and not accel:
        return [("declared-on-" + backend,
                 f"donation declared on {backend} where the engine "
                 f"deliberately skips it (_donation_supported): the "
                 f"lowering cannot realize the aliasing and XLA warns "
                 f"per compile")]
    if declared and accel and not realized:
        return [("declared-not-realized",
                 f"donate_argnums declared but no {_ALIASING_MARKER} in "
                 f"the lowered module on {backend}: the packed input "
                 f"buffers are NOT being reused for the output")]
    if not declared and realized:
        return [("realized-not-declared",
                 f"{_ALIASING_MARKER} present without declared donation "
                 f"on {backend}: aliasing the engine did not ask for")]
    return []


def check_collectives(compiled_hlo_text: str) -> list:
    """ir-collective: cross-shard ops in the compiled forward module."""
    out = []
    for op in sorted(set(_COLLECTIVE_RE.findall(compiled_hlo_text))):
        out.append((op,
                    f"mesh-sharded decode program compiles to `{op}`: "
                    f"the forward path must stay shard-local (rows are "
                    f"independent; any collective is a sharding-spec "
                    f"regression)"))
    return out


def _dtype_name(dt) -> str:
    try:
        return str(np.dtype(dt))
    except TypeError:
        return str(dt)


def check_widening(jaxpr, allowlist: frozenset = WIDENING_ALLOWLIST) -> list:
    """ir-widening: 64-bit element types in the jaxpr. Checked on the
    jaxpr (not the StableHLO text) because MLIR spells shape/dimension
    ATTRIBUTES as i64 — a raw text scan false-positives on every
    broadcast_in_dim."""
    out = []
    seen = set()
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in allowlist:
            continue
        if name == "convert_element_type":
            nd = _dtype_name(eqn.params.get("new_dtype"))
            if nd in WIDE_DTYPES and ("convert:" + nd) not in seen:
                seen.add("convert:" + nd)
                out.append((f"convert_element_type[{nd}]",
                            f"convert_element_type widens to {nd}: "
                            f"x64 creep on the decode path"))
                continue
        for v in eqn.outvars:
            dt = getattr(getattr(v, "aval", None), "dtype", None)
            if dt is None:
                continue
            nd = _dtype_name(dt)
            key = f"{name}:{nd}"
            if nd in WIDE_DTYPES and key not in seen:
                seen.add(key)
                out.append((f"{name}[{nd}]",
                            f"`{name}` produces a {nd} value: 64-bit "
                            f"types are outside the packed-u32 decode "
                            f"contract"))
    return out


def output_bytes(out_avals) -> int:
    """Total fetched-output bytes across the program's output avals."""
    total = 0
    for aval in out_avals:
        n = 1
        for d in aval.shape:
            n *= int(d)
        total += n * np.dtype(aval.dtype).itemsize
    return total


def output_budget_bytes(n_words: int, row_capacity: int, *,
                        filtered: bool, n_shards: int) -> int:
    """The per-program budget: the packed words themselves, plus the
    filter metadata the fused path legitimately returns (keep mask,
    per-shard survivor counts), plus the mesh's per-shard fallback
    counts, plus 64 bytes of fixed slack. Anything more — an extra
    R-sized output, a widened word array — trips the contract."""
    budget = n_words * 4 * row_capacity
    shards = max(n_shards, 1)
    if filtered:
        budget += 4 * ((row_capacity + 31) // 32)  # keep mask, 1 bit/row
        budget += 4 * shards                       # survivor counts
    if n_shards:
        budget += 4 * n_shards                     # shard_bad counts
    return budget + 64


def check_output_budget(out_avals, n_words: int, row_capacity: int, *,
                        filtered: bool, n_shards: int) -> list:
    """ir-output-budget: actual output bytes vs the layout budget."""
    actual = output_bytes(out_avals)
    budget = output_budget_bytes(n_words, row_capacity,
                                 filtered=filtered, n_shards=n_shards)
    if actual <= budget:
        return []
    per_row = actual / max(row_capacity, 1)
    return [(f"bytes={actual}>budget={budget}",
             f"program fetches {actual} output bytes "
             f"({per_row:.1f} B/row) against a {budget}-byte budget for "
             f"this layout ({n_words} packed words/row): an output "
             f"grew beyond packed words + filter metadata")]


def egress_output_budget_bytes(row_capacity: int, total_width: int,
                               n_fields: int) -> int:
    """The egress-program budget (ops/egress.py): the left-aligned text
    buffer — row_capacity × ΣW uint8 bytes where ΣW is the plan's total
    rendered field width — plus one int32 length per rendered field per
    row, plus 64 bytes of fixed slack. Anything more (a widened buffer,
    an extra R-sized output) trips the contract: encoded bytes must
    scale with the DECLARED wire widths, nothing else."""
    return row_capacity * total_width + 4 * row_capacity * n_fields + 64


def check_egress_output_budget(out_avals, row_capacity: int,
                               total_width: int, n_fields: int) -> list:
    """ir-egress-output-budget: actual output bytes vs the egress plan's
    encoded-bytes budget."""
    actual = output_bytes(out_avals)
    budget = egress_output_budget_bytes(row_capacity, total_width,
                                        n_fields)
    if actual <= budget:
        return []
    per_row = actual / max(row_capacity, 1)
    return [(f"bytes={actual}>budget={budget}",
             f"egress program fetches {actual} output bytes "
             f"({per_row:.1f} B/row) against a {budget}-byte budget "
             f"(ΣW={total_width}, {n_fields} rendered fields): an "
             f"output grew beyond the declared wire widths")]


def check_canonical_dedup(text_a: str, text_b: str) -> list:
    """ir-canonical-dedup: two spec permutations of one canonical layout
    must serialize to byte-identical IR."""
    if text_a == text_b:
        return []
    return [("permutation-lowering-differs",
             "column-permuted specs that share a canonical layout "
             "lowered to DIFFERENT serialized IR: canonicalization is "
             "not collapsing them to one cached program (cache-key "
             "aliasing / compile-count regression)")]
