"""Program enumeration for the IR tier.

A `ProgramDescriptor` is everything needed to lower ONE decode program
exactly the way production dispatch would: canonical specs, row bucket,
engine selection (XLA / pallas), nibble packing, mesh, donation policy,
and the compiled row filter for fused-filter variants. The catalog
enumerates descriptors from three sources:

  * the built-in schema catalog below — a kind-diverse set covering
    every DEVICE_KIND family, the nibble fast path, the pallas engine
    envelope, and a filtered table, so the tier has real coverage even
    on a fresh checkout with an empty program store;
  * the program store's *observed signatures* — host-program cache keys
    recorded from live dispatches, folded in so layouts actually seen in
    production are re-verified on every lint run;
  * permuted-column twins per multi-column schema, feeding the
    ir-canonical-dedup contract.

Descriptor tags (`programs/<kinds>-<hash8>`) derive from the canonical
specs via the program store's stable repr, so the finding namespace is
identical across processes, machines, and the forced-mesh subprocess.
"""

from __future__ import annotations

import dataclasses
import hashlib


@dataclasses.dataclass
class ProgramDescriptor:
    """One lowerable decode program (see module docstring)."""
    tag: str            # stable layout tag, e.g. "i32x3-1f2e3d4c"
    specs: tuple        # canonical (col_index, kind, gather_w, bit_w) specs
    row_capacity: int
    variant: str        # host|device|nibble|pallas|filtered|mesh|mesh-filtered
    nibble: bool = False
    use_pallas: bool = False
    mesh: object = None           # jax.sharding.Mesh | None
    donate: bool = False
    pred: object = None           # predicate.CompiledRowFilter | None
    hot_loop: bool = True
    source: str = "schema"        # schema | observed
    #: wire-encoder name (ops/egress.py) — set on egress-program
    #: descriptors, which lower the SECOND fused stage (words → wire
    #: text) instead of a decode program
    egress: str = None
    #: permuted-twin canonical specs for ir-canonical-dedup (None = skip)
    dedup_twin: tuple = None

    @property
    def path(self) -> str:
        return f"programs/{self.tag}"

    @property
    def scope(self) -> str:
        return f"{self.variant}-r{self.row_capacity}"

    @property
    def n_shards(self) -> int:
        return self.mesh.size if self.mesh is not None else 0


def layout_tag(specs: tuple) -> str:
    """`<kind-counts>-<hash8>`: human-greppable prefix + collision-proof
    stable hash of the canonical specs."""
    from ...ops.program_store import _stable_repr

    counts: dict = {}
    for _, kind, _, _ in specs:
        name = kind.name.lower()
        counts[name] = counts.get(name, 0) + 1
    kinds = "+".join(f"{k}x{n}" for k, n in sorted(counts.items()))
    digest = hashlib.sha256(_stable_repr(specs).encode()).hexdigest()[:8]
    return f"{kinds or 'empty'}-{digest}"


def _table(name: str, cols) -> "object":
    from ...models import (ReplicatedTableSchema, TableName, TableSchema)

    oid = 90000 + (hash(name) % 1000)
    return ReplicatedTableSchema.with_all_columns(TableSchema(
        oid, TableName("public", name), tuple(cols)))


def default_schemas() -> list:
    """(name, schema) pairs the tier always covers. Chosen for span, not
    volume: every DEVICE_KIND family appears, one schema is nibble-
    eligible (all-int, even widths), one fits the pallas envelope
    (ΣW ≤ MAX_TOTAL_WIDTH), one exceeds it, and one mixes dense with
    host-object columns the way real tables do."""
    from ...models import ColumnSchema, Oid

    pgbench = _table("pgbench_accounts", (
        ColumnSchema("aid", Oid.INT4, nullable=False, primary_key_ordinal=1),
        ColumnSchema("bid", Oid.INT4),
        ColumnSchema("abalance", Oid.INT4),
        ColumnSchema("filler", Oid.BPCHAR, modifier=88)))
    # every remaining DEVICE_KIND family + object spill (numeric/text)
    kinds_wide = _table("lint_kinds_wide", (
        ColumnSchema("id", Oid.INT8, nullable=False, primary_key_ordinal=1),
        ColumnSchema("flag", Oid.BOOL),
        ColumnSchema("small", Oid.INT2),
        ColumnSchema("ratio", Oid.FLOAT4),
        ColumnSchema("total", Oid.FLOAT8),
        ColumnSchema("born", Oid.DATE),
        ColumnSchema("at_time", Oid.TIME),
        ColumnSchema("created", Oid.TIMESTAMP),
        ColumnSchema("updated", Oid.TIMESTAMPTZ),
        ColumnSchema("amount", Oid.NUMERIC),
        ColumnSchema("note", Oid.TEXT)))
    # nibble-eligible: int/date kinds only — exercises the halved-upload
    # program variant
    nibble = _table("lint_nibble", (
        ColumnSchema("a", Oid.INT4, nullable=False, primary_key_ordinal=1),
        ColumnSchema("b", Oid.INT8),
        ColumnSchema("d", Oid.DATE)))
    return [("pgbench_accounts", pgbench),
            ("lint_kinds_wide", kinds_wide),
            ("lint_nibble", nibble)]


def filtered_schema():
    """(name, schema, compiled-filter-producing decoder schema): pgbench
    with the bench suite's `abalance < 0` publication row filter —
    device-supported, referenced column dense."""
    from ...ops.predicate import parse_row_filter

    name, schema = default_schemas()[0]
    return ("pgbench_filtered",
            schema.with_row_predicate(parse_row_filter("abalance < 0")))


def _decoder(schema):
    from ...ops.engine import DeviceDecoder

    return DeviceDecoder(schema, mesh=None, telemetry=False,
                         device_min_rows=1 << 30,
                         nonblocking_compile=True)


def _device_specs(dec):
    """The device-path width signature for an all-NULL batch at minimum
    gather widths — the deterministic signature the tier verifies (real
    batches bucket up from here; the program structure is identical)."""
    from ...ops.staging import synthetic_staged_batch

    staged = synthetic_staged_batch(len(dec.schema.replicated_columns), 64)
    widths = dec._widths(staged)
    return dec._specs(staged, widths), widths


def build_catalog(*, mesh=None, row_buckets=None,
                  include_observed: bool = True) -> list:
    """All descriptors for one run, deterministically ordered.

    `mesh=None` enumerates the single-device set (host + device + nibble
    + pallas + filtered variants per schema). A mesh enumerates ONLY the
    mesh-sharded variants — the forced-8-shard subprocess runs with just
    those, and the parent runs the single-device set, so no program is
    checked twice."""
    from ...ops.egress import ENCODER_JSON, ENCODER_TSV, plan_for_specs
    from ...ops.engine import _donation_supported
    from ...ops.pallas_kernel import pallas_supported
    from ...ops.program_store import canonical_plan, load_observed

    buckets = tuple(row_buckets) if row_buckets else (4096,)
    donate_dev = _donation_supported()
    out: list[ProgramDescriptor] = []
    seen: set = set()

    def add(desc: ProgramDescriptor):
        key = (desc.specs, desc.row_capacity, desc.variant, desc.nibble,
               desc.use_pallas, desc.n_shards,
               desc.pred.fingerprint() if desc.pred is not None else None,
               desc.egress)
        if key in seen:
            return
        seen.add(key)
        out.append(desc)

    for name, schema in default_schemas() + [filtered_schema()]:
        dec = _decoder(schema)
        host_specs = dec._host_specs()
        if not host_specs:
            continue
        pred = dec._row_filter
        if pred is not None and not pred.device_supported:
            pred = None
        dev_specs, widths = _device_specs(dec)
        host_plan = canonical_plan(host_specs)
        dev_plan = canonical_plan(dev_specs)
        # permuted twin: reversed column order must canonicalize to the
        # same layout; the runner lowers both and byte-compares
        twin = canonical_plan(tuple(reversed(host_specs))).specs \
            if len(host_specs) > 1 else None
        # egress programs: the wire-encoding second stage, enumerated
        # per (layout, encoder) exactly as the program store keys them —
        # only for layouts with at least one renderable field
        egress_encoders = [e for e in (ENCODER_TSV, ENCODER_JSON)
                           if pred is None
                           and plan_for_specs(dev_plan.specs, e)
                           is not None]
        for bucket in buckets:
            if mesh is not None:
                if bucket % mesh.size:
                    continue
                add(ProgramDescriptor(
                    tag=layout_tag(dev_plan.specs), specs=dev_plan.specs,
                    row_capacity=bucket,
                    variant="mesh-filtered" if pred is not None else "mesh",
                    mesh=mesh, donate=donate_dev, pred=pred))
                for enc in egress_encoders:
                    add(ProgramDescriptor(
                        tag=layout_tag(dev_plan.specs),
                        specs=dev_plan.specs, row_capacity=bucket,
                        variant=f"mesh-egress-{enc}", mesh=mesh,
                        egress=enc))
                continue
            add(ProgramDescriptor(
                tag=layout_tag(host_plan.specs), specs=host_plan.specs,
                row_capacity=bucket,
                variant="filtered-host" if pred is not None else "host",
                pred=pred, dedup_twin=twin))
            add(ProgramDescriptor(
                tag=layout_tag(dev_plan.specs), specs=dev_plan.specs,
                row_capacity=bucket,
                variant="filtered" if pred is not None else "device",
                donate=donate_dev, pred=pred))
            if pred is None and dec._can_nibble(widths):
                add(ProgramDescriptor(
                    tag=layout_tag(dev_plan.specs), specs=dev_plan.specs,
                    row_capacity=bucket, variant="nibble", nibble=True,
                    donate=donate_dev))
            if pred is None and pallas_supported(dev_plan.specs):
                add(ProgramDescriptor(
                    tag=layout_tag(dev_plan.specs), specs=dev_plan.specs,
                    row_capacity=bucket, variant="pallas",
                    use_pallas=True, donate=donate_dev))
            for enc in egress_encoders:
                add(ProgramDescriptor(
                    tag=layout_tag(dev_plan.specs), specs=dev_plan.specs,
                    row_capacity=bucket, variant=f"egress-{enc}",
                    egress=enc))

    if mesh is None and include_observed:
        # observed host-program signatures: key shape is
        # (row_capacity, canonical_specs, False, None, False, pred_fp,
        #  True) — see engine._host_fn_key. Only unfiltered keys are
        # reconstructable from the fingerprint alone (a pred_fp cannot
        # be turned back into a CompiledRowFilter without its schema).
        for key in load_observed():
            if len(key) != 7 or not key[-1] or key[5] is not None:
                continue
            row_capacity, specs = key[0], key[1]
            if not (isinstance(specs, tuple) and specs
                    and all(isinstance(s, tuple) and len(s) == 4
                            for s in specs)):
                continue
            add(ProgramDescriptor(
                tag=layout_tag(specs), specs=specs,
                row_capacity=row_capacity, variant="host",
                source="observed"))

    out.sort(key=lambda d: (d.path, d.scope, d.source))
    return out
