"""etl-lint IR tier: contract verification of compiled decode programs.

The AST tier (..rules) guards source; this tier guards the *lowered
programs themselves*. It enumerates every decode program the system can
compile — canonical layouts from the program store + schema catalog,
both engines (XLA and pallas), filtered and unfiltered, single-device
and forced 8-shard mesh — lowers each through the exact
`ops.engine._build_device_fn` constructor production dispatch uses, and
checks per-program contracts on the jaxpr / StableHLO / compiled HLO.

Findings flow through the same `findings.Finding` model, fingerprints,
baseline, and `--format=github` machinery as AST findings. IR findings
live under the reserved `programs/<layout-tag>` path namespace (which
`findings.canonical_path` passes through untouched) with the program
variant as the scope, so fingerprints are stable across runs and
machines.

This module stays import-light (no jax): the CLI imports it
unconditionally for the contract names and namespace; the heavy runner
loads only behind `--programs`.
"""

from __future__ import annotations

#: reserved path namespace for IR-tier findings ("programs/<tag>");
#: never collides with a real file path, so baseline entries for the two
#: tiers cannot alias
IR_NAMESPACE = "programs/"

#: the contract catalog, in check order. These are finding `rule` names,
#: deliberately NOT part of rules.RULE_NAMES: the AST fixture-coverage
#: tests pin that tuple to source-level rules, and IR contracts are
#: exercised against lowered programs, not fixture files.
IR_CONTRACT_NAMES = (
    "ir-host-callback",
    "ir-donation",
    "ir-collective",
    "ir-widening",
    "ir-output-budget",
    "ir-egress-output-budget",
    "ir-canonical-dedup",
)


def analyze_programs(*, mesh: bool = False, row_buckets=None):
    """Run the IR tier; returns (findings, program_paths). Lazy import —
    pulls in jax and the decode engine."""
    from . import runner

    return runner.analyze_programs(mesh=mesh, row_buckets=row_buckets)
