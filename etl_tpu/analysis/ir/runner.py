"""IR-tier driver: lower every cataloged program, run the contracts.

Ordering is load-bearing: descriptors are enumerated sorted, findings
are emitted per-descriptor in contract-catalog order and then sorted by
the same (path, line, col, rule) key the AST tier uses, and the mesh
subprocess serializes findings as JSON dicts the parent reconstructs —
two runs over the same layout set are byte-identical (fingerprints,
chains, ordering), which the determinism tests pin.

The forced-mesh pass runs in a SUBPROCESS because an already-initialized
jax backend cannot grow devices: the parent may hold a single-device CPU
backend, so `--mesh` spawns `python -m etl_tpu.analysis
--programs-mesh-inner` with XLA_FLAGS forcing an 8-way host platform,
and that child enumerates ONLY the mesh-sharded variants.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

from ..findings import Finding
from . import contracts
from .catalog import ProgramDescriptor, build_catalog

_REPO_ROOT = Path(__file__).resolve().parents[3]

#: forced device count for the mesh subprocess — matches the bench
#: suite's 8-shard mesh check, i.e. one pod-slice's worth of shards
MESH_FORCED_DEVICES = 8

_MESH_SUBPROCESS_TIMEOUT_S = 600


class IrAnalysisError(RuntimeError):
    """Analyzer failure (not a lint finding): exit-code-2 territory."""


def _lower(desc: ProgramDescriptor, cache: dict):
    """(jitted, avals, lowered, stablehlo_text) for one descriptor, via
    the engine's own constructor. Cached on the full jit signature: the
    host and device variants of one layout collapse to one lowering on
    CPU (identical constructor args), which is exactly the production
    sharing the canonical-program design promises."""
    from ...ops.egress import lower_egress_program
    from ...ops.engine import lower_program

    key = (desc.specs, desc.row_capacity, desc.nibble, desc.use_pallas,
           desc.n_shards, desc.donate,
           desc.pred.fingerprint() if desc.pred is not None else None,
           desc.egress)
    hit = cache.get(key)
    if hit is None:
        if desc.egress is not None:
            fn, avals, lowered = lower_egress_program(
                desc.specs, desc.egress, desc.row_capacity,
                mesh=desc.mesh)
        else:
            fn, avals, lowered = lower_program(
                desc.specs, desc.row_capacity, nibble=desc.nibble,
                use_pallas=desc.use_pallas, mesh=desc.mesh,
                donate=desc.donate, pred=desc.pred)
        hit = (fn, avals, lowered, lowered.as_text())
        cache[key] = hit
    return hit


def _twin_text(desc: ProgramDescriptor, cache: dict) -> str:
    twin = ProgramDescriptor(
        tag=desc.tag, specs=desc.dedup_twin,
        row_capacity=desc.row_capacity, variant=desc.variant,
        nibble=desc.nibble, use_pallas=desc.use_pallas, mesh=desc.mesh,
        donate=desc.donate, pred=desc.pred)
    return _lower(twin, cache)[3]


def analyze_descriptor(desc: ProgramDescriptor, cache: dict,
                       backend: "str | None" = None) -> list:
    """All contract findings for one program descriptor."""
    import jax

    from ...ops.bitpack import layout_for_specs

    fn, avals, lowered, text = _lower(desc, cache)
    backend = backend or jax.default_backend()
    findings: list[Finding] = []

    def emit(rule: str, pairs) -> None:
        for detail, message in pairs:
            findings.append(Finding(rule=rule, path=desc.path, line=1,
                                    col=0, scope=desc.scope,
                                    detail=detail, message=message))

    if desc.hot_loop:
        jaxpr = fn.trace(*avals).jaxpr
        emit("ir-host-callback", contracts.check_host_callback(jaxpr))
        emit("ir-widening", contracts.check_widening(jaxpr))
    emit("ir-donation",
         contracts.check_donation(text, desc.donate, backend))
    out_avals = jax.tree_util.tree_leaves(lowered.out_info)
    if desc.egress is not None:
        from ...ops.egress import plan_for_specs

        plan = plan_for_specs(desc.specs, desc.egress)
        emit("ir-egress-output-budget",
             contracts.check_egress_output_budget(
                 out_avals, desc.row_capacity, plan.total_width,
                 len(plan.slots)))
    else:
        n_words = layout_for_specs(desc.specs).n_words
        emit("ir-output-budget",
             contracts.check_output_budget(out_avals, n_words,
                                           desc.row_capacity,
                                           filtered=desc.pred is not None,
                                           n_shards=desc.n_shards))
    if desc.n_shards:
        # collectives only materialize in the COMPILED module — the
        # lowered StableHLO still carries sharding annotations, not ops
        emit("ir-collective",
             contracts.check_collectives(lowered.compile().as_text()))
    if desc.dedup_twin is not None:
        emit("ir-canonical-dedup",
             contracts.check_canonical_dedup(text, _twin_text(desc, cache)))
    return findings


def _finding_sort_key(f: Finding):
    # same composite the AST tier's analyze_paths sorts on, extended
    # with (scope, detail) — IR findings share line/col
    return (f.path, f.line, f.col, f.rule, f.scope, f.detail)


def analyze_local(*, mesh=None, row_buckets=None) -> tuple:
    """Run the tier in-process over the catalog for `mesh` (None =
    single-device variants). Returns (findings, program_paths) — paths
    cover every ENUMERATED program, clean or not, so `--check-baseline`
    can treat the whole namespace as scanned."""
    try:
        descriptors = build_catalog(mesh=mesh, row_buckets=row_buckets)
    except Exception as e:
        raise IrAnalysisError(f"program enumeration failed: {e}") from e
    cache: dict = {}
    findings: list[Finding] = []
    paths: list[str] = []
    for desc in descriptors:
        paths.append(desc.path)
        try:
            findings.extend(analyze_descriptor(desc, cache))
        except Exception as e:
            raise IrAnalysisError(
                f"lowering {desc.path} [{desc.scope}] failed: {e}") from e
    findings.sort(key=_finding_sort_key)
    return findings, sorted(set(paths))


def run_mesh_inner() -> dict:
    """The `--programs-mesh-inner` payload: enumerate ONLY the mesh
    variants on this (forced-multi-device) backend and return the JSON
    document the parent merges."""
    from ...parallel.mesh import decode_mesh

    mesh = decode_mesh()
    if mesh is None or mesh.size < 2:
        raise IrAnalysisError(
            "mesh inner pass started without a multi-device backend "
            "(XLA_FLAGS --xla_force_host_platform_device_count missing?)")
    findings, paths = analyze_local(mesh=mesh)
    return {"findings": [f.to_dict() for f in findings],
            "paths": paths, "n_shards": mesh.size}


def _finding_from_dict(d: dict) -> Finding:
    return Finding(rule=d["rule"], path=d["path"], line=d["line"],
                   col=d["col"], scope=d["scope"], detail=d["detail"],
                   message=d["message"], chain=tuple(d.get("chain", ())),
                   chain_sites=tuple(tuple(s) for s
                                     in d.get("chain_sites", ())))


def run_mesh_subprocess() -> tuple:
    """Spawn the forced-8-shard child and reconstruct its findings."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_"
                            f"device_count={MESH_FORCED_DEVICES}").strip()
    proc = subprocess.run(
        [sys.executable, "-m", "etl_tpu.analysis", "--programs-mesh-inner"],
        capture_output=True, text=True, env=env, cwd=str(_REPO_ROOT),
        timeout=_MESH_SUBPROCESS_TIMEOUT_S)
    if proc.returncode != 0:
        raise IrAnalysisError(
            f"mesh subprocess failed (exit {proc.returncode}): "
            f"{proc.stderr.strip()[-2000:]}")
    try:
        doc = json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError) as e:
        raise IrAnalysisError(
            f"mesh subprocess emitted no JSON document: {e}; "
            f"stdout tail: {proc.stdout[-500:]!r}") from e
    return ([_finding_from_dict(d) for d in doc.get("findings", ())],
            list(doc.get("paths", ())))


def analyze_programs(*, mesh: bool = False, row_buckets=None) -> tuple:
    """The CLI entry: single-device pass in-process, plus the forced
    mesh subprocess when `mesh`. Returns (findings, program_paths),
    both deterministically sorted."""
    findings, paths = analyze_local(row_buckets=row_buckets)
    if mesh:
        mesh_findings, mesh_paths = run_mesh_subprocess()
        findings = findings + mesh_findings
        paths = paths + mesh_paths
    findings.sort(key=_finding_sort_key)
    return findings, sorted(set(paths))
