"""Per-function control-flow graph + forward dataflow.

Statement-granularity CFG over one function body: nodes are the
function's `ast.stmt` objects plus three synthetic markers (ENTRY, EXIT
for normal returns/fall-through, EXC_EXIT for exceptions escaping the
function). Edges cover branches (`if`/`else`), loops (`while`/`for`,
`break`/`continue`, `else` clauses), `try`/`except`/`else`/`finally`,
`with`, `return`, and `raise`.

Exception edges are deliberately coarse — any statement that *contains a
call, await, subscript, or attribute access* may raise, and it may raise
*before or after* its own effect took hold, so a may-analysis gets an
exception edge from the statement itself (state as of the statement's
ENTRY, not its exit). The edge lands on the innermost enclosing
handler/finally, else on EXC_EXIT. That is exactly the precision the
resource rules need: "`lease = pool.lease()` then work that can raise
with no `finally` release" produces a path acquire → EXC_EXIT that
avoids the release, while `finally: lease.release()` routes every
exception edge through the release first.

`dataflow_forward` runs a classic union-join worklist over the graph;
rules supply a transfer function over frozensets. Used by
`arena-lease-leak` (live-lease facts) and `donated-buffer-use` (tainted
buffer names).
"""

from __future__ import annotations

import ast

ENTRY = "<entry>"
EXIT = "<exit>"
EXC_EXIT = "<exc-exit>"

#: node kinds whose evaluation can raise — the coarse may-raise test
_RAISING = (ast.Call, ast.Await, ast.Subscript, ast.Attribute,
            ast.BinOp, ast.Raise, ast.Assert)


def header_roots(stmt: ast.stmt) -> list:
    """The sub-expressions that execute AT a statement's own CFG node.
    For a simple statement that is the whole statement; for a compound
    statement only its header (condition / iterable / with-items) — the
    body statements are separate CFG nodes and must not contribute
    their effects (releases, raises) to the header's transfer."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.target, stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, ast.Try):
        return []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return list(stmt.decorator_list)
    return [stmt]


def iter_header_nodes(stmt: ast.stmt):
    for root in header_roots(stmt):
        yield from ast.walk(root)


def may_raise(stmt: ast.stmt) -> bool:
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    return any(isinstance(node, _RAISING)
               for node in iter_header_nodes(stmt))


class CFG:
    """succs/preds over `ast.stmt` nodes + the synthetic markers.

    Two edge kinds: NORMAL edges propagate a statement's post-state
    (its effect happened), EXC edges (`exc_succs`) propagate its
    PRE-state — an exception may fire before the statement's own effect,
    so `x = pool.lease()` raising must not claim the lease was taken,
    and `x.release()` raising must not claim it was released."""

    def __init__(self, fn_node):
        self.fn = fn_node
        self.succs: dict[object, set] = {ENTRY: set(), EXIT: set(),
                                         EXC_EXIT: set()}
        self.exc_succs: dict[object, set] = {}
        self._loop_stack: list[tuple[set, set]] = []  # (breaks, continues)
        self._exc_targets: list[object] = [EXC_EXIT]
        self._finally_stack: list[ast.stmt] = []  # innermost last
        body = fn_node.body if isinstance(fn_node.body, list) \
            else [ast.Expr(fn_node.body)]  # lambda
        frontier = self._block(body, {ENTRY})
        for n in frontier:
            self._edge(n, EXIT)
        self.preds: dict[object, set] = {k: set() for k in self.succs}
        for src, dsts in list(self.succs.items()) \
                + list(self.exc_succs.items()):
            for d in dsts:
                self.preds.setdefault(d, set()).add(src)

    # -- construction --------------------------------------------------------

    def _edge(self, src, dst) -> None:
        self.succs.setdefault(src, set()).add(dst)
        self.succs.setdefault(dst, set())

    def _exc_edge(self, src, dst) -> None:
        self.exc_succs.setdefault(src, set()).add(dst)
        self.succs.setdefault(dst, set())

    def _enter(self, stmt: ast.stmt, preds: set) -> None:
        for p in preds:
            self._edge(p, stmt)
        # a Try node evaluates nothing itself — giving it an exception
        # edge would fabricate a path that bypasses its own finally
        if may_raise(stmt) and not isinstance(stmt, ast.Try):
            self._exc_edge(stmt, self._exc_targets[-1])

    def _block(self, stmts: list, preds: set) -> set:
        """Wire `stmts` sequentially; returns the fall-through frontier."""
        frontier = set(preds)
        for stmt in stmts:
            if not frontier:
                break  # unreachable tail (after return/raise/break)
            self._enter(stmt, frontier)
            frontier = self._stmt(stmt)
        return frontier

    def _stmt(self, stmt: ast.stmt) -> set:
        if isinstance(stmt, (ast.Return, ast.Raise)):
            if isinstance(stmt, ast.Return):
                # a return inside try/finally runs the finally first
                if self._finally_stack:
                    self._edge(stmt, self._finally_stack[-1].finalbody[0])
                else:
                    self._edge(stmt, EXIT)
            else:
                self._edge(stmt, self._exc_targets[-1])
            return set()
        if isinstance(stmt, ast.Break):
            if self._loop_stack:
                self._loop_stack[-1][0].add(stmt)
            return set()
        if isinstance(stmt, ast.Continue):
            if self._loop_stack:
                self._loop_stack[-1][1].add(stmt)
            return set()
        if isinstance(stmt, ast.If):
            then = self._block(stmt.body, {stmt})
            if stmt.orelse:
                other = self._block(stmt.orelse, {stmt})
            else:
                other = {stmt}
            return then | other
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            breaks: set = set()
            continues: set = set()
            self._loop_stack.append((breaks, continues))
            body_exit = self._block(stmt.body, {stmt})
            self._loop_stack.pop()
            for n in body_exit | continues:
                self._edge(n, stmt)  # back edge
            # loop exit: condition false (or iterator exhausted) / break;
            # while True only exits via break
            exits = set(breaks)
            infinite = isinstance(stmt, ast.While) \
                and isinstance(stmt.test, ast.Constant) \
                and stmt.test.value is True
            if not infinite:
                exits.add(stmt)
            if stmt.orelse and not infinite:
                exits = self._block(stmt.orelse, {stmt}) | set(breaks)
            return exits
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._block(stmt.body, {stmt})
        if isinstance(stmt, ast.Try):
            return self._try(stmt)
        return {stmt}

    def _try(self, stmt: ast.Try) -> set:
        has_finally = bool(stmt.finalbody)
        # while inside the try body, exceptions flow to the handler
        # dispatch marker (handlers are tried in order but any may
        # match) or, with no handlers, straight to the finally
        exc_target: object
        if stmt.handlers:
            exc_target = ("handlers", stmt)
            self.succs.setdefault(exc_target, set())
        elif has_finally:
            exc_target = stmt.finalbody[0]
        else:
            exc_target = self._exc_targets[-1]
        self._exc_targets.append(exc_target)
        if has_finally:
            self._finally_stack.append(stmt)
        body_exit = self._block(stmt.body, {stmt})
        self._exc_targets.pop()

        after: set = set()
        if stmt.handlers:
            # handler bodies run with exceptions escaping to finally/outer
            inner_target = stmt.finalbody[0] if has_finally \
                else self._exc_targets[-1]
            for h in stmt.handlers:
                self._exc_targets.append(inner_target)
                h_exit = self._block(h.body, {exc_target})
                self._exc_targets.pop()
                after |= h_exit
            # an exception matching NO handler propagates — unless some
            # handler catches everything (bare / BaseException), in
            # which case no exception escapes the dispatch unhandled
            def _catches_all(h: ast.ExceptHandler) -> bool:
                if h.type is None:
                    return True
                types = h.type.elts if isinstance(h.type, ast.Tuple) \
                    else [h.type]
                return any(
                    isinstance(t, (ast.Name, ast.Attribute))
                    and (t.id if isinstance(t, ast.Name) else t.attr)
                    == "BaseException" for t in types)

            if not any(_catches_all(h) for h in stmt.handlers):
                self._edge(exc_target, stmt.finalbody[0] if has_finally
                           else self._exc_targets[-1])
        if stmt.orelse:
            body_exit = self._block(stmt.orelse, body_exit)
        after |= body_exit
        if has_finally:
            self._finally_stack.pop()
            fin_exit = self._block(stmt.finalbody, after if after
                                   else {stmt})
            # finally also re-raises (and completes returns): its exit
            # flows onward, toward EXIT (return-through-finally — via
            # any OUTER finally still pending, which must not be
            # bypassed), and to the outer exception target —
            # conservative all-ways edges
            exit_target = self._finally_stack[-1].finalbody[0] \
                if self._finally_stack else EXIT
            for n in fin_exit:
                self._edge(n, self._exc_targets[-1])
                self._edge(n, exit_target)
            return fin_exit
        return after

    # -- queries -------------------------------------------------------------

    def nodes(self):
        return self.succs.keys()

    def statements(self):
        return [n for n in self.succs
                if isinstance(n, ast.stmt)]


def dataflow_forward(cfg: CFG, transfer, entry_state=frozenset(),
                     exc_transfer=None):
    """Union-join forward may-analysis. `transfer(node, state) -> state`
    over frozensets; returns {node: IN-state}. Normal successors receive
    the post-state; exception successors receive `exc_transfer(node,
    state)` when given, else the PRE-state (see CFG) — a rule whose
    kills hold even when the statement raises (releasing a lease) passes
    an exc_transfer that applies kills but not gens.
    Deterministic: worklist in insertion order with stable re-queues."""
    in_states: dict = {n: frozenset() for n in cfg.succs}
    in_states[ENTRY] = entry_state
    # every node is processed at least once (a successor whose merged
    # state is unchanged still needs its own transfer run), then
    # re-queued only when its IN-state grows — monotone, terminates
    work = sorted(cfg.succs, key=_node_order)
    work.remove(ENTRY)
    work.insert(0, ENTRY)
    queued = set(work)
    while work:
        node = work.pop(0)
        queued.discard(node)
        state_in = in_states[node]
        out = transfer(node, state_in)
        exc_state = exc_transfer(node, state_in) if exc_transfer \
            else state_in
        targets = [(s, out) for s in sorted(cfg.succs.get(node, ()),
                                            key=_node_order)] \
            + [(s, exc_state) for s in sorted(cfg.exc_succs.get(node, ()),
                                              key=_node_order)]
        for succ, state in targets:
            merged = in_states[succ] | state
            if merged != in_states[succ]:
                in_states[succ] = merged
                if succ not in queued:
                    work.append(succ)
                    queued.add(succ)
    return in_states


def _node_order(node) -> tuple:
    if isinstance(node, ast.stmt):
        return (0, node.lineno, node.col_offset)
    if isinstance(node, tuple):  # handler dispatch marker
        return (1, node[1].lineno, 0)
    return (2, 0, 0, str(node))
