"""Runtime-visible markers the static analyzer keys on.

`@hot_loop` declares a function to be on the per-row/per-dispatch hot
path where a host<->device transfer (np.asarray on a device value,
jax.device_get, .block_until_ready) would serialize the pipeline against
the accelerator link. The decorator itself is zero-cost — it tags the
function and returns it unchanged — but etl-lint's
`hot-loop-host-transfer` rule scans every function carrying the marker
and fails tier-1 on any transfer call inside it.

Contract for decorated functions:
  - no host transfers: dispatch device work, hand back futures/pending
    handles; fetch happens at the consumer (`_PendingDecode.result()`).
  - intentional transfers (there are none today) must carry an inline
    `# etl-lint: ignore[hot-loop-host-transfer]` with a justification.
"""

from __future__ import annotations

from typing import Callable, TypeVar

_F = TypeVar("_F", bound=Callable)

#: attribute set on decorated functions (runtime-introspectable; the
#: analyzer matches the decorator *name* lexically, so aliasing the
#: import defeats the lint — don't)
HOT_LOOP_ATTR = "__etl_hot_loop__"


def hot_loop(fn: _F) -> _F:
    """Mark `fn` as hot-path: etl-lint forbids host transfers inside."""
    setattr(fn, HOT_LOOP_ATTR, True)
    return fn


#: attribute set by @dispatch_stage (runtime-introspectable, same lexical
#: matching caveat as HOT_LOOP_ATTR)
DISPATCH_STAGE_ATTR = "__etl_dispatch_stage__"

#: attribute set by @admission_path (runtime-introspectable, same lexical
#: matching caveat as HOT_LOOP_ATTR)
ADMISSION_PATH_ATTR = "__etl_admission_path__"


def admission_path(fn: _F) -> _F:
    """Mark `fn` as part of the batch-admission scheduler's grant path
    (ops/pipeline.AdmissionScheduler): code that runs UNDER the
    scheduler's condition lock or between a tenant's acquire and the
    dispatch it gates. etl-lint's `admission-blocking-fetch` rule forbids
    blocking device fetches here (`jax.device_get`, `.block_until_ready`,
    `np.asarray` on device values, and `jax.device_put` uploads too — no
    device traffic of any kind belongs in an admission decision): a fetch
    inside the grant path would serialize EVERY tenant's admission behind
    one tenant's device round trip, turning the fairness lock into a
    head-of-line blocker. Weight/lag providers must read host state
    (LSN deltas, counters), never device values."""
    setattr(fn, ADMISSION_PATH_ATTR, True)
    return fn


#: attribute set by @shard_scoped (runtime-introspectable, same lexical
#: matching caveat as HOT_LOOP_ATTR)
SHARD_SCOPED_ATTR = "__etl_shard_scoped__"


def shard_scoped(fn: _F) -> _F:
    """Mark `fn` as operating inside ONE shard's slice of a sharded
    publication (etl_tpu/sharding): code that reads replication state on
    behalf of a single shard replicator. etl-lint's
    `cross-shard-table-access` rule forbids unfiltered full-table-list
    store reads here (`get_table_states()` with no arguments): against a
    SHARED store that call returns every shard's tables, and acting on
    the full list silently re-copies, re-owns, or purges tables a
    sibling pod owns — the exact corruption the shard fence exists to
    stop. Read through the shard view instead
    (`ShardScopedStore.owned_table_states()`), or justify a deliberate
    cross-shard read (the coordinator's global sweeps) with an inline
    ignore."""
    setattr(fn, SHARD_SCOPED_ATTR, True)
    return fn


#: attribute set by @control_loop (runtime-introspectable, same lexical
#: matching caveat as HOT_LOOP_ATTR)
CONTROL_LOOP_ATTR = "__etl_control_loop__"


def control_loop(fn: _F) -> _F:
    """Mark `fn` as part of the autoscaling control loop's DECISION path
    (etl_tpu/autoscale): the pure signal→policy→decision computation a
    controller tick runs between sampling and actuation. etl-lint's
    `control-loop-blocking-io` rule forbids blocking I/O (time.sleep,
    open, subprocess, sockets, requests) AND all device traffic
    (jax.device_get / device_put / .block_until_ready / np.asarray on
    device values) here: the policy must stay a pure, property-testable
    function of (SignalFrame history, config) — a blocking call makes
    decision latency depend on an external service, and a device fetch
    couples shard-count control to accelerator health, which is exactly
    the dependency loop an autoscaler must never have (a sick device
    delaying the decision that would route around it). Store writes and
    orchestrator calls belong in the (async, unmarked) actuation path."""
    setattr(fn, CONTROL_LOOP_ATTR, True)
    return fn


#: attribute set by @flush_path (runtime-introspectable, same lexical
#: matching caveat as HOT_LOOP_ATTR)
FLUSH_PATH_ATTR = "__etl_flush_path__"


def flush_path(fn: _F) -> _F:
    """Mark `fn` as a destination flush/dispatch path (the apply loop's
    flush machinery, the copy partition's chunk/drain path): code that
    dispatches destination writes through the bounded ack window
    (runtime/ack_window.py). etl-lint's `inline-durability-wait` rule
    forbids a bare `await ack.wait_durable()` here — the WINDOW owns
    durability waits (contiguous-prefix advance, per-entry timeout
    bounds, overlap telemetry); an inline wait silently re-serializes
    the pipeline to one ack round-trip per batch, the exact ceiling the
    write window removes. Route acks through
    `AckWindow.dispatch`/`CopyAckWindow.add`, or justify a deliberate
    inline barrier with an inline ignore."""
    setattr(fn, FLUSH_PATH_ATTR, True)
    return fn


#: attribute set by @transactional_commit (runtime-introspectable, same
#: lexical matching caveat as HOT_LOOP_ATTR)
TRANSACTIONAL_COMMIT_ATTR = "__etl_transactional_commit__"


def transactional_commit(fn: _F) -> _F:
    """Mark `fn` as a transactional-commit write path (docs/destinations.md
    exactly-once contract): a destination entry point that must record the
    acked WAL coordinate range ATOMICALLY alongside the data it ships —
    BigQuery `_CHANGE_SEQUENCE_NUMBER` keys, ClickHouse insert-dedup
    tokens, Iceberg/lake snapshot properties, Snowpipe offset tokens.
    etl-lint's `uncoordinated-transactional-write` rule flags any
    destination write call inside a marked frame that ships data WITHOUT
    its `CommitRange` — an uncoordinated write silently downgrades the
    sink to at-least-once (a restart cannot see what that write covered,
    so it re-streams and duplicates), which is exactly the hole the
    transactional protocol closes. Ship through the `*_committed` seam or
    pass the range explicitly; justify a deliberate at-least-once escape
    with an inline ignore."""
    setattr(fn, TRANSACTIONAL_COMMIT_ATTR, True)
    return fn


#: attribute set by @domain (runtime-introspectable, same lexical
#: matching caveat as HOT_LOOP_ATTR). Holds the pinned domain name.
DOMAIN_ATTR = "__etl_domain__"

#: the execution domains the concurrency tier understands. Matches
#: analysis/domains.py — kept here so the decorator can validate eagerly
#: (a typo'd pin would otherwise silently create a new domain).
KNOWN_DOMAINS = frozenset({"loop", "worker", "executor", "sweep",
                           "coordinator"})


def domain(name: str) -> "Callable[[_F], _F]":
    """Pin `fn` to one execution domain for the concurrency tier
    (analysis/domains.py): `loop` (asyncio event loop), `worker`
    (dedicated thread), `executor` (run_in_executor / to_thread
    offload), `sweep` (supervision sweep thread), `coordinator`
    (out-of-process control loop acting through the shared StateStore).

    Inference normally derives domains by propagating from spawn sites
    and async entry points; a pin OVERRIDES inference for the decorated
    function — incoming propagation is ignored, the pinned domain still
    propagates outward through its callees. Use it where inference
    cannot see the spawn (a callback registered with an external
    library, a coordinator tick entry invoked by a process manager) or
    where a deliberate single-domain contract should be enforced even
    if a new caller appears from another domain."""
    if name not in KNOWN_DOMAINS:
        raise ValueError(
            f"unknown execution domain {name!r}; expected one of "
            f"{sorted(KNOWN_DOMAINS)}")

    def mark(fn: _F) -> _F:
        setattr(fn, DOMAIN_ATTR, name)
        return fn

    return mark


#: attribute set by @handoff (runtime-introspectable, same lexical
#: matching caveat as HOT_LOOP_ATTR)
HANDOFF_ATTR = "__etl_handoff__"


def handoff(fn: _F) -> _F:
    """Mark `fn` as a deliberate cross-domain OWNERSHIP-TRANSFER seam:
    code that mutates shared state from one domain on behalf of another
    under a happens-before edge the lockset analysis cannot see —
    a StagingArena lease handed to the pipeline worker before the
    submitting task ever looks at it again, an AckWindow entry payload
    published before the dispatch that makes it reachable, a
    DecodePipeline result future resolved by the worker and consumed by
    the loop, a coordinator's persist-then-actuate journal write.

    The concurrency rules (`unsynchronized-shared-mutation`,
    `loop-state-from-thread`, `coordinator-store-bypass`) sanction
    accesses inside a marked frame. The marker is a CONTRACT, not an
    escape hatch: the decorated function must establish the transfer
    edge itself (publish via a queue/future/journal, or touch state
    only before the other domain can reach it). Document the edge in
    the docstring of every function you mark — docs/CONCURRENCY.md
    has the discipline."""
    setattr(fn, HANDOFF_ATTR, True)
    return fn


def dispatch_stage(fn: _F) -> _F:
    """Mark `fn` as the decode pipeline's DISPATCH stage (ops/pipeline.py
    architecture): a hot-loop function whose job is to start device work,
    where host→device *uploads* (`jax.device_put` committing a packed
    arena to the host-CPU backend) are the point and ride the pipeline
    rather than stalling it. etl-lint's `hot-loop-host-transfer` rule
    permits uploads here but still forbids device→host *fetches*
    (np.asarray / jax.device_get / .block_until_ready) — those belong at
    the consumer (`_PendingDecode.result()`, the fetch stage)."""
    setattr(fn, DISPATCH_STAGE_ATTR, True)
    return fn
