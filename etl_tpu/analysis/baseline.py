"""Baseline suppression file: grandfathered findings by fingerprint.

Format (JSON, committed at etl_tpu/analysis/baseline.json):

    {
      "version": 1,
      "entries": {
        "<rule>|<path>|<scope>|<detail>": {"count": N, "reason": "..."}
      }
    }

Matching is by fingerprint + count, never by line number, so unrelated
edits don't invalidate the baseline. If a file accrues MORE occurrences
of a grandfathered fingerprint than the baseline allows, the newest
occurrences (highest line numbers) are reported — new debt never hides
behind old debt.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path

from .findings import Finding

VERSION = 1

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def load(path: "str | Path | None" = None) -> dict[str, int]:
    """fingerprint -> allowed count; empty when the file is absent."""
    p = Path(path) if path is not None else DEFAULT_BASELINE
    if not p.exists():
        return {}
    data = json.loads(p.read_text(encoding="utf-8"))
    if data.get("version") != VERSION:
        raise ValueError(
            f"baseline {p}: unsupported version {data.get('version')!r}")
    out: dict[str, int] = {}
    for fp, entry in data.get("entries", {}).items():
        out[fp] = int(entry["count"]) if isinstance(entry, dict) \
            else int(entry)
    return out


def fingerprint_path(fp: str) -> str:
    """The canonical-path component of a fingerprint. Safe to split on
    the first two '|'s: rule and path never contain one (details may —
    e.g. `except A|B` tuples)."""
    return fp.split("|", 2)[1]


def save(findings: list[Finding], path: "str | Path | None" = None,
         reasons: "dict[str, str] | None" = None,
         scanned_paths: "set[str] | None" = None) -> Path:
    """Write a baseline covering every current finding (the
    `--update-baseline` path). Existing reasons are preserved for
    fingerprints that survive. `scanned_paths` bounds the rewrite: old
    entries for files OUTSIDE the scanned set are kept verbatim, so a
    scoped run (`... etl_tpu/runtime --update-baseline`) can't silently
    destroy the grandfathered debt (and hand-written reasons) of the
    rest of the tree. Omit it only for a full-tree scan."""
    p = Path(path) if path is not None else DEFAULT_BASELINE
    old_reasons: dict[str, str] = {}
    entries: dict[str, dict] = {}
    if p.exists():
        try:
            old = json.loads(p.read_text(encoding="utf-8"))
            for fp, entry in old.get("entries", {}).items():
                if isinstance(entry, dict) and entry.get("reason"):
                    old_reasons[fp] = entry["reason"]
                if scanned_paths is not None \
                        and fingerprint_path(fp) not in scanned_paths:
                    entries[fp] = entry if isinstance(entry, dict) \
                        else {"count": int(entry)}
        except (ValueError, KeyError):
            pass
    counts = Counter(f.fingerprint for f in findings)
    for fp in sorted(counts):
        entry = {"count": counts[fp]}
        reason = (reasons or {}).get(fp) or old_reasons.get(fp)
        if reason:
            entry["reason"] = reason
        entries[fp] = entry
    entries = {fp: entries[fp] for fp in sorted(entries)}
    p.write_text(json.dumps({"version": VERSION, "entries": entries},
                            indent=2, sort_keys=True) + "\n",
                 encoding="utf-8")
    return p


def apply(findings: list[Finding],
          baseline: dict[str, int]) -> tuple[list[Finding], dict[str, int]]:
    """(violations, stale) — violations are findings beyond the baselined
    count per fingerprint (newest occurrences reported); stale maps
    baselined fingerprints that no longer occur (or occur fewer times)
    to their unused allowance, so fixed debt can be pruned."""
    by_fp: dict[str, list[Finding]] = {}
    for f in findings:
        by_fp.setdefault(f.fingerprint, []).append(f)
    violations: list[Finding] = []
    for fp, group in by_fp.items():
        allowed = baseline.get(fp, 0)
        if len(group) > allowed:
            group.sort(key=lambda f: (f.line, f.col))
            violations.extend(group[allowed:])
    stale: dict[str, int] = {}
    for fp, allowed in baseline.items():
        used = len(by_fp.get(fp, ()))
        if used < allowed:
            stale[fp] = allowed - used
    violations.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return violations, stale
