"""Context propagation along call-graph edges.

The lexical visitor knows a function's OWN context (inside `async def`,
under `@hot_loop`). This module extends those contexts transitively: a
function reachable from an event-loop `async def` through plain sync
calls runs ON the event loop; a helper called from a `@hot_loop`
function runs IN the hot loop. Each reached function carries the chain
that proves it, entry first, so findings render `a → b → c: time.sleep`
and `--explain` can print one resolvable file:line per hop.

Edge semantics (the part that keeps this sound for asyncio):

  - a plain call edge into a SYNC project function propagates every
    context — the callee executes inline, in the caller's frame;
  - a call into an ASYNC function is followed only when the call site is
    awaited AND the callee is not its own entry for the querying rule
    (`follow_await`): un-awaited, the call just builds a coroutine
    object (rule 4 territory); awaited into another entry, the callee
    reports its own closure and re-reporting it from every upstream
    `async def` would multiply one sink into a finding per caller;
  - function REFERENCES are never edges, so the sanctioned off-loop
    idioms — `run_in_executor(None, fn)`, `asyncio.to_thread(fn)`,
    handing a lambda to an executor — break propagation exactly where
    execution actually leaves the loop/hot path;
  - `prune(site, callee)` lets a rule stop at a call that is ITSELF a
    sink (e.g. `autotune.resolve_device_min_rows`): the finding names
    the sink call; the sink's own internals would only produce noisier
    duplicates of the same root cause.

Traversal is BFS per entry, so the recorded chain is a shortest witness
and deterministic (call sites are visited in (line, col) order); cycles
terminate via the per-entry visited set.
"""

from __future__ import annotations

from .callgraph import CallSite, FunctionInfo, Project


class Reached:
    """One function reached from one entry, with its witness chain."""

    __slots__ = ("fn", "chain", "chain_sites", "entry", "dispatch",
                 "anchor")

    def __init__(self, fn: FunctionInfo, chain: tuple, chain_sites: tuple,
                 entry: FunctionInfo, dispatch: bool,
                 anchor: "CallSite | None"):
        self.fn = fn
        self.chain = chain  # qualnames, entry first, `fn` last
        self.chain_sites = chain_sites  # (path, line) per hop's call site
        self.entry = entry
        self.dispatch = dispatch  # dispatch-stage sanction along chain
        #: the call site in the ENTRY function that starts this chain —
        #: where the finding anchors (and where an inline ignore goes);
        #: None for the entry itself
        self.anchor = anchor


def reach_from(entry: FunctionInfo, *, max_depth: int = 12,
               follow_await=None, prune=None) -> "list[Reached]":
    """All project functions reachable from `entry` (including the entry
    itself at depth 0), shortest chains first.

    `follow_await(callee) -> bool` gates edges into async callees (the
    site must be awaited regardless); default: never follow — every
    `async def` is its own entry for the async-context rules, so
    following would only duplicate findings upstream. `prune(site,
    callee) -> bool` stops traversal into a callee (the sink itself)."""
    out = [Reached(entry, (entry.qualname,),
                   ((entry.module.path, entry.line),), entry,
                   entry.is_dispatch, None)]
    seen = {id(entry)}
    queue = [(entry, out[0], 0)]
    while queue:
        fn, reached, depth = queue.pop(0)
        if depth >= max_depth:
            continue
        for site in fn.calls:
            callee = site.resolved
            if callee is None or id(callee) in seen:
                continue
            if callee.is_async:
                if not site.awaited:
                    continue  # builds a coroutine; does not run here
                if follow_await is None or not follow_await(callee):
                    continue
            if prune is not None and prune(site, callee):
                continue
            seen.add(id(callee))
            sites = reached.chain_sites[:-1] \
                + ((fn.module.path, site.line),) \
                + ((callee.module.path, callee.line),)
            nxt = Reached(
                callee, reached.chain + (callee.qualname,), sites, entry,
                reached.dispatch or callee.is_dispatch,
                reached.anchor if reached.anchor is not None else site)
            out.append(nxt)
            queue.append((callee, nxt, depth + 1))
    return out


def async_entries(project: Project, scopes: "tuple[str, ...] | None" = None):
    """Every `async def` (optionally restricted to modules whose first
    path segment is in `scopes`) — the event-loop entry set."""
    for fn in project.iter_functions():
        if not fn.is_async:
            continue
        if scopes is not None \
                and fn.module.path.split("/", 1)[0] not in scopes:
            continue
        yield fn


def hot_entries(project: Project):
    """Every function marked `@hot_loop` (alias-resolved)."""
    for fn in project.iter_functions():
        if fn.is_hot:
            yield fn
