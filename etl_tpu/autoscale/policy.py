"""The scaling policy: a pure, property-testable decision function.

DS2-style rate model (Kalavri et al., OSDI'18 — compute the target
parallelism from OBSERVED rates, don't trial-and-error) over the
SignalFrame history:

  capacity   — per-shard drain capacity in bytes/s, estimated from the
               durable-LSN advance between consecutive frames (the
               median over shards of the best observed per-shard rate
               inside the window; floored at `capacity_floor_bytes_per_s`
               so a cold start can never divide by zero);
  raw target — ceil(aggregate_backlog / (capacity × drain_slo_s)): the
               shard count that drains the current backlog inside the
               SLO at the observed rate;
  decision   — the raw target wrapped in the safety envelope below.

Safety envelope (Dhalion's lesson, VLDB'17 — a self-regulating policy
needs damping more than it needs cleverness):

  hysteresis bands — scale-up is considered only while the aggregate
      backlog sits ABOVE `up_backlog_bytes`; scale-down only BELOW
      `down_backlog_bytes`. The gap between the bands is the dead zone
      where noisy signals cannot flap the topology. When the up band is
      breached the minimum response is +1 even if the rate model says
      the current K should cope — sustained backlog above the band IS
      the evidence the model's capacity estimate is optimistic.
  sustained votes — `up_ticks` (resp. `down_ticks`) CONSECUTIVE frames
      must agree before a direction is decided; a single spiky frame
      decides nothing.
  cooldown — after any applied decision, `cooldown_ticks` evaluations
      must pass before the next decision; a rebalance's own transient
      lag (the at-least-once re-apply window) must never trigger the
      next rebalance.
  max-step — K changes by exactly ±1 per decision; the two-phase
      rebalance is proven for single steps, and repeated small steps
      with cooldowns converge without overshooting.
  vetoes — any unhealthy shard holds (never rebalance a sick fleet:
      quiesce would block on the sick shard's fence anyway); memory
      pressure vetoes scale-DOWN (the survivors' headroom isn't real).

Everything here is `@control_loop`: no wall clock, no I/O, no device
traffic — a function of (history, current_k, last_decision_tick,
config) only, enforced by etl-lint rule 16 and property-tested in
tests/test_autoscale.py (monotone response, no-flap around band edges,
cooldown enforcement).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from ..analysis.annotations import control_loop
from ..models.errors import ErrorKind, EtlError

ACTION_UP = "scale_up"
ACTION_DOWN = "scale_down"
ACTION_HOLD = "hold"


@dataclass(frozen=True)
class AutoscalePolicyConfig:
    min_shards: int = 1
    max_shards: int = 8
    #: the drain SLO: how long a fully-stalled backlog may take to drain
    #: at observed capacity before more shards are warranted
    drain_slo_s: float = 60.0
    #: hysteresis bands over the AGGREGATE backlog (bytes); up > down
    up_backlog_bytes: int = 64 * 1024 * 1024
    down_backlog_bytes: int = 8 * 1024 * 1024
    #: consecutive agreeing evaluations before a direction is decided
    up_ticks: int = 2
    down_ticks: int = 3
    #: evaluations that must pass after an applied decision
    cooldown_ticks: int = 5
    #: capacity-estimate floor (bytes/s): guards cold starts and idle
    #: windows where no durable progress was observed
    capacity_floor_bytes_per_s: float = 64 * 1024.0
    #: frames considered when estimating capacity
    window_frames: int = 8

    def validate(self) -> None:
        if self.min_shards < 1:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           f"min_shards must be >= 1, got {self.min_shards}")
        if self.max_shards < self.min_shards:
            raise EtlError(
                ErrorKind.CONFIG_INVALID,
                f"max_shards {self.max_shards} < min_shards "
                f"{self.min_shards}")
        if self.down_backlog_bytes >= self.up_backlog_bytes:
            raise EtlError(
                ErrorKind.CONFIG_INVALID,
                f"hysteresis bands inverted: down {self.down_backlog_bytes}"
                f" >= up {self.up_backlog_bytes} (the gap is the dead "
                f"zone that stops flapping)")
        if self.drain_slo_s <= 0:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           "drain_slo_s must be > 0")
        if min(self.up_ticks, self.down_ticks) < 1:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           "up_ticks/down_ticks must be >= 1")
        if self.cooldown_ticks < 0:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           "cooldown_ticks must be >= 0")
        if self.capacity_floor_bytes_per_s <= 0:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           "capacity_floor_bytes_per_s must be > 0")
        if self.window_frames < 2:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           "window_frames must be >= 2 (rates are deltas)")

    def to_json(self) -> dict:
        return {
            "min_shards": self.min_shards,
            "max_shards": self.max_shards,
            "drain_slo_s": self.drain_slo_s,
            "up_backlog_bytes": self.up_backlog_bytes,
            "down_backlog_bytes": self.down_backlog_bytes,
            "up_ticks": self.up_ticks,
            "down_ticks": self.down_ticks,
            "cooldown_ticks": self.cooldown_ticks,
            "capacity_floor_bytes_per_s": self.capacity_floor_bytes_per_s,
            "window_frames": self.window_frames,
        }

    @classmethod
    def from_json(cls, doc: dict) -> "AutoscalePolicyConfig":
        cfg = cls(**{k: doc[k] for k in cls().to_json() if k in doc})
        cfg.validate()
        return cfg


@dataclass(frozen=True)
class Decision:
    """One evaluation's outcome. `target_k` is the APPLIED target (the
    ±1-clamped next K when action is up/down, current K on hold);
    `raw_target_k` is the unclamped rate-model output, kept for
    observability — a raw target far above target_k means the system is
    under-provisioned and will keep stepping after each cooldown."""

    tick: int
    action: str
    current_k: int
    target_k: int
    raw_target_k: int
    backlog_bytes: int
    capacity_bytes_per_s: float
    reason: str

    def describe(self) -> dict:
        return {
            "tick": self.tick,
            "action": self.action,
            "current_k": self.current_k,
            "target_k": self.target_k,
            "raw_target_k": self.raw_target_k,
            "backlog_bytes": self.backlog_bytes,
            "capacity_bytes_per_s": round(self.capacity_bytes_per_s, 1),
            "reason": self.reason,
        }


class AutoscalePolicy:
    """Stateless evaluator; every public entry point is a pure function
    of its arguments plus the frozen config."""

    def __init__(self, config: AutoscalePolicyConfig | None = None):
        self.config = config or AutoscalePolicyConfig()
        self.config.validate()

    # -- rate model ----------------------------------------------------------

    @control_loop
    def estimate_capacity(self, history) -> float:
        """Per-shard drain capacity (bytes/s): for each shard, the best
        durable-LSN advance rate observed between consecutive frames in
        the window (best, not mean — idle ticks say nothing about what a
        shard CAN do); the median over shards; floored. Monotone in the
        evidence: more observed drain never lowers the estimate below
        the floor."""
        cfg = self.config
        window = list(history)[-cfg.window_frames:]
        if len(window) < 2:
            return cfg.capacity_floor_bytes_per_s
        best: dict[int, float] = {}
        for prev, cur in zip(window, window[1:]):
            dt = cur.at_s - prev.at_s
            if dt <= 0:
                continue
            prev_durable = {s.shard: s.durable_lsn for s in prev.shards}
            for s in cur.shards:
                before = prev_durable.get(s.shard)
                if before is None:
                    continue
                rate = max(0.0, (s.durable_lsn - before) / dt)
                if rate > best.get(s.shard, 0.0):
                    best[s.shard] = rate
        if not best:
            return cfg.capacity_floor_bytes_per_s
        rates = sorted(best.values())
        median = rates[len(rates) // 2]
        return max(median, cfg.capacity_floor_bytes_per_s)

    @control_loop
    def raw_target(self, backlog_bytes: int, capacity: float) -> int:
        """ceil(backlog / (capacity × drain_SLO)) — the DS2 shape. Zero
        backlog needs zero shards as far as the rate model is concerned;
        clamping to the deployment envelope happens in evaluate()."""
        if backlog_bytes <= 0:
            return 0
        return math.ceil(backlog_bytes
                         / (capacity * self.config.drain_slo_s))

    # -- decision ------------------------------------------------------------

    @control_loop
    def _votes(self, history, current_k: int, capacity: float,
               want_up: bool) -> int:
        """How many CONSECUTIVE newest frames vote for the direction.
        A frame votes up when its backlog breaches the up band; down
        when its backlog is under the down band AND the rate model at
        the (already-estimated) capacity wants fewer shards."""
        cfg = self.config
        votes = 0
        for frame in reversed(list(history)):
            backlog = frame.aggregate_backlog_bytes
            if want_up:
                agrees = backlog >= cfg.up_backlog_bytes
            else:
                agrees = (backlog <= cfg.down_backlog_bytes
                          and self.raw_target(backlog, capacity)
                          < current_k)
            if not agrees:
                break
            votes += 1
        return votes

    @control_loop
    def evaluate(self, history, current_k: int,
                 last_decision_tick: "int | None" = None) -> Decision:
        """One evaluation. `history` is the frame list (newest last,
        non-empty); `current_k` the authoritative shard count;
        `last_decision_tick` the tick of the last APPLIED decision (None
        = never scaled). Pure: same inputs, same Decision."""
        cfg = self.config
        frames = list(history)
        if not frames:
            raise EtlError(ErrorKind.INVALID_STATE_TRANSITION,
                           "evaluate() needs at least one signal frame")
        latest = frames[-1]
        backlog = latest.aggregate_backlog_bytes
        capacity = self.estimate_capacity(frames)
        raw = self.raw_target(backlog, capacity)

        def hold(reason: str) -> Decision:
            return Decision(tick=latest.tick, action=ACTION_HOLD,
                            current_k=current_k, target_k=current_k,
                            raw_target_k=raw, backlog_bytes=backlog,
                            capacity_bytes_per_s=capacity, reason=reason)

        if not latest.all_healthy:
            return hold("unhealthy shard: rebalancing a sick fleet would "
                        "block on its fence")
        in_cooldown = (last_decision_tick is not None
                       and latest.tick - last_decision_tick
                       < cfg.cooldown_ticks)

        # scale-up: sustained backlog above the band; minimum response
        # +1 even when the rate model is optimistic (see module doc)
        if backlog >= cfg.up_backlog_bytes and current_k < cfg.max_shards:
            if self._votes(frames, current_k, capacity, True) \
                    >= cfg.up_ticks:
                if in_cooldown:
                    return hold(
                        f"cooldown: {latest.tick - last_decision_tick}"
                        f"/{cfg.cooldown_ticks} ticks since last decision")
                target = current_k + 1  # max-step: the rebalance is
                # proven for single steps; a raw target further out
                # keeps stepping after each cooldown
                return Decision(
                    tick=latest.tick, action=ACTION_UP,
                    current_k=current_k, target_k=target,
                    raw_target_k=raw, backlog_bytes=backlog,
                    capacity_bytes_per_s=capacity,
                    reason=f"backlog {backlog}B over up band "
                           f"{cfg.up_backlog_bytes}B for "
                           f">={cfg.up_ticks} ticks (raw target {raw})")
            return hold("backlog over up band, votes not yet sustained")

        # scale-down: sustained quiet under the band, rate model agrees
        if backlog <= cfg.down_backlog_bytes \
                and current_k > cfg.min_shards \
                and raw < current_k:
            if latest.any_memory_pressure:
                return hold("memory pressure vetoes scale-down")
            if self._votes(frames, current_k, capacity, False) \
                    >= cfg.down_ticks:
                if in_cooldown:
                    return hold(
                        f"cooldown: {latest.tick - last_decision_tick}"
                        f"/{cfg.cooldown_ticks} ticks since last decision")
                return Decision(
                    tick=latest.tick, action=ACTION_DOWN,
                    current_k=current_k, target_k=current_k - 1,
                    raw_target_k=raw, backlog_bytes=backlog,
                    capacity_bytes_per_s=capacity,
                    reason=f"backlog {backlog}B under down band "
                           f"{cfg.down_backlog_bytes}B for "
                           f">={cfg.down_ticks} ticks (raw target {raw})")
            return hold("backlog under down band, votes not yet sustained")

        return hold("backlog inside the hysteresis dead zone"
                    if cfg.down_backlog_bytes < backlog
                    < cfg.up_backlog_bytes
                    else "no eligible transition")


@control_loop
def simulate(frames, policy: AutoscalePolicy,
             start_k: int) -> "list[Decision]":
    """Dry-run a frame sequence through the policy with the applied-K
    loop closed in memory: every non-hold decision updates the simulated
    topology and starts the cooldown, exactly as a controller applying
    each decision instantly would. Pure — the replay CLI's trace, the
    bench reaction-time gate, and the no-flap property tests all run
    through here, so they exercise the same loop semantics."""
    decisions: list[Decision] = []
    current_k = start_k
    last_tick: "int | None" = None
    history: list = []
    for frame in frames:
        history.append(frame)
        decision = policy.evaluate(history, current_k, last_tick)
        decisions.append(decision)
        if decision.action != ACTION_HOLD:
            current_k = decision.target_k
            last_tick = decision.tick
    return decisions
