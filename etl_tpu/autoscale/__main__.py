"""CLI: `python -m etl_tpu.autoscale --replay signals.json`.

Dry-runs a signal timeline through the scaling policy and prints the
decision trace — one JSON object per evaluation tick (sorted keys) plus
a trailing summary line — with the applied-K loop closed in memory
(every non-hold decision updates the simulated topology and starts the
cooldown). Deterministic: the same (timeline, policy knobs) input
prints the identical trace, and `--synthetic --seed N` replays the
seeded surge→drain story bit-identically — the same replay contract as
`python -m etl_tpu.chaos`. Exit 0 always (a dry run has no invariants
to violate); malformed input exits 2.
"""

from __future__ import annotations

import argparse
import json
import sys

from .policy import ACTION_HOLD, AutoscalePolicy, AutoscalePolicyConfig, \
    simulate
from .signals import SignalTimeline, seeded_surge_timeline


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m etl_tpu.autoscale",
        description="replay a signal timeline through the scaling "
                    "policy and print the deterministic decision trace")
    src = parser.add_mutually_exclusive_group(required=True)
    src.add_argument("--replay", metavar="SIGNALS_JSON",
                     help="recorded timeline file (SignalTimeline JSON: "
                          "{frames: [{tick, at_s, shards: [...]}]})")
    src.add_argument("--synthetic", action="store_true",
                     help="generate the seeded surge→drain timeline "
                          "instead of reading a file (the bench "
                          "reaction-time gate's input)")
    parser.add_argument("--seed", type=int, default=7,
                        help="synthetic-timeline seed (default 7)")
    parser.add_argument("--start-k", type=int, default=None,
                        help="initial shard count (default: the first "
                             "frame's shard count)")
    parser.add_argument("--holds", action="store_true",
                        help="print HOLD evaluations too (default: only "
                             "scale decisions + the summary)")
    # policy knobs (docs/autoscale.md): defaults match
    # AutoscalePolicyConfig
    _d = AutoscalePolicyConfig()
    parser.add_argument("--drain-slo-s", type=float, default=_d.drain_slo_s)
    parser.add_argument("--up-backlog-bytes", type=int,
                        default=_d.up_backlog_bytes)
    parser.add_argument("--down-backlog-bytes", type=int,
                        default=_d.down_backlog_bytes)
    parser.add_argument("--up-ticks", type=int, default=_d.up_ticks)
    parser.add_argument("--down-ticks", type=int, default=_d.down_ticks)
    parser.add_argument("--cooldown-ticks", type=int,
                        default=_d.cooldown_ticks)
    parser.add_argument("--min-shards", type=int, default=_d.min_shards)
    parser.add_argument("--max-shards", type=int, default=_d.max_shards)
    args = parser.parse_args(argv)

    if args.synthetic:
        timeline = seeded_surge_timeline(args.seed)
    else:
        try:
            with open(args.replay) as f:
                timeline = SignalTimeline.from_json(json.load(f))
        except (OSError, ValueError, KeyError) as e:
            print(f"cannot load {args.replay}: {e}", file=sys.stderr)
            return 2
    if not timeline.frames:
        print("timeline has no frames", file=sys.stderr)
        return 2

    config = AutoscalePolicyConfig(
        min_shards=args.min_shards, max_shards=args.max_shards,
        drain_slo_s=args.drain_slo_s,
        up_backlog_bytes=args.up_backlog_bytes,
        down_backlog_bytes=args.down_backlog_bytes,
        up_ticks=args.up_ticks, down_ticks=args.down_ticks,
        cooldown_ticks=args.cooldown_ticks)
    config.validate()
    policy = AutoscalePolicy(config)
    start_k = args.start_k if args.start_k is not None \
        else max(1, timeline.frames[0].shard_count)

    decisions = simulate(timeline.frames, policy, start_k)
    final_k = start_k
    actions = []
    for d in decisions:
        if d.action != ACTION_HOLD:
            final_k = d.target_k
            actions.append({"tick": d.tick, "action": d.action,
                            "k": f"{d.current_k}->{d.target_k}"})
        if args.holds or d.action != ACTION_HOLD:
            print(json.dumps(d.describe(), sort_keys=True))
    print(json.dumps({
        "summary": True,
        "source": "synthetic" if args.synthetic else args.replay,
        "seed": args.seed if args.synthetic else None,
        "frames": len(timeline.frames),
        "start_k": start_k,
        "final_k": final_k,
        "decisions": actions,
        "policy": config.to_json(),
    }, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
