"""Closed-loop, SLO-driven elasticity (docs/autoscale.md).

Signals (signals.py) — per-shard lag/drain/pressure sampled into
seeded-replayable SignalFrame timelines; Policy (policy.py) — a pure
DS2-style rate model wrapped in hysteresis bands, cooldown windows,
max-step K→K±1 and flap damping; Controller (controller.py) — drives
`ShardCoordinator` two-phase rebalances and orchestrator rolls behind a
crash-resumable decision journal persisted through the StateStore
surface, and feeds per-tenant SLO weights into the shared
AdmissionScheduler.

`python -m etl_tpu.autoscale --replay signals.json` replays a recorded
timeline through the policy and prints the deterministic decision
trace; `--synthetic --seed N` does the same over the seeded surge→drain
story the bench reaction-time gate uses.
"""

from .controller import (AutoscaleController, AutoscaleJournal,
                         DecisionRecord, STATUS_ABORTED, STATUS_APPLIED,
                         STATUS_PENDING)
from .policy import (ACTION_DOWN, ACTION_HOLD, ACTION_UP, AutoscalePolicy,
                     AutoscalePolicyConfig, Decision)
from .signals import (RegistrySignalSource, ShardSignals, SignalFrame,
                      SignalTimeline, StoreSignalSource,
                      seeded_surge_timeline)

__all__ = [
    "ACTION_DOWN",
    "ACTION_HOLD",
    "ACTION_UP",
    "AutoscaleController",
    "AutoscaleJournal",
    "AutoscalePolicy",
    "AutoscalePolicyConfig",
    "Decision",
    "DecisionRecord",
    "RegistrySignalSource",
    "STATUS_ABORTED",
    "STATUS_APPLIED",
    "STATUS_PENDING",
    "ShardSignals",
    "SignalFrame",
    "SignalTimeline",
    "StoreSignalSource",
    "seeded_surge_timeline",
]
