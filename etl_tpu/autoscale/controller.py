"""The actuation half: decisions → two-phase rebalances → pod rolls.

`AutoscaleController.tick()` is one turn of the closed loop:

    sample (collector) → evaluate (policy, pure) → actuate:
      1. refuse overlap — a pending journal entry or an in-flight
         rebalance record means a decision is already being applied;
         this tick HOLDS (the two-phase protocol is single-flight by
         construction and the controller must never race itself);
      2. persist the decision to the journal (StateStore surface)
         BEFORE touching the topology — a controller crash after this
         point leaves a pending entry a successor can resume or abort;
      3. drive `ShardCoordinator.add_shard()/remove_shard()` (the PR 9
         two-phase fence: zero-loss/bounded-dup by construction);
      4. roll the fleet: `orchestrator.scale_pipeline()` (StatefulSet
         fan-out or LocalOrchestrator subprocesses) and/or the
         `scale_listener` hook (in-process fleets: chaos, tests);
      5. mark the journal entry applied.

Crash recovery (`resume()`): a pending journal entry is re-driven
through the SAME coordinator action — the coordinator's persisted
`rebalancing` record resumes with the original fence, so re-running is
idempotent; a pending entry whose target the assignment already shows
steady (crash between flip and journal mark) is marked applied with no
topology action at all — re-running a persisted decision is a no-op.
`resume(abort=True)` instead rolls the in-flight rebalance back via
`ShardCoordinator.abort_rebalance()` (slot deleted, epoch unchanged)
and marks the entry aborted.

The controller also feeds per-tenant SLO weights into the shared
`AdmissionScheduler` (ops/pipeline.py) — the PR 8 leftover: lag decides
who is behind, the SLO weight decides whose backlog costs more per
second, and the autoscale config is where operators own both knobs.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field, replace

from ..analysis.annotations import domain, handoff
from ..models.errors import ErrorKind, EtlError
from ..telemetry.metrics import (ETL_AUTOSCALE_BACKLOG_BYTES,
                                 ETL_AUTOSCALE_CAPACITY_BYTES_PER_S,
                                 ETL_AUTOSCALE_DECISION_IN_FLIGHT,
                                 ETL_AUTOSCALE_DECISIONS_TOTAL,
                                 ETL_AUTOSCALE_HOLDS_TOTAL,
                                 ETL_AUTOSCALE_RESUMES_TOTAL,
                                 ETL_AUTOSCALE_TARGET_SHARDS, registry)
from .policy import (ACTION_DOWN, ACTION_HOLD, ACTION_UP, AutoscalePolicy,
                     Decision)
from .signals import SignalTimeline

logger = logging.getLogger("etl_tpu.autoscale")

STATUS_PENDING = "pending"
STATUS_APPLIED = "applied"
STATUS_ABORTED = "aborted"


@dataclass(frozen=True)
class DecisionRecord:
    """One journaled decision. `decision_id` is monotonic per pipeline;
    `epoch_before` pins which topology the decision was made against so
    a resume can tell 'crash before flip' from 'crash after flip'."""

    decision_id: int
    tick: int
    action: str  # scale_up | scale_down
    from_k: int
    to_k: int
    epoch_before: int
    status: str = STATUS_PENDING

    def to_json(self) -> dict:
        return {
            "decision_id": self.decision_id,
            "tick": self.tick,
            "action": self.action,
            "from_k": self.from_k,
            "to_k": self.to_k,
            "epoch_before": self.epoch_before,
            "status": self.status,
        }

    @classmethod
    def from_json(cls, doc: dict) -> "DecisionRecord":
        return cls(
            decision_id=int(doc["decision_id"]),
            tick=int(doc["tick"]),
            action=str(doc["action"]),
            from_k=int(doc["from_k"]),
            to_k=int(doc["to_k"]),
            epoch_before=int(doc["epoch_before"]),
            status=str(doc.get("status", STATUS_PENDING)),
        )


@dataclass
class AutoscaleJournal:
    """The persisted decision history (bounded) + the id counter. One
    small JSON doc rewritten whole per transition — the StateStore
    surface (store/base.py) keeps ids monotonic across controllers."""

    next_id: int = 1
    entries: list = field(default_factory=list)
    max_entries: int = 64

    def pending(self) -> "DecisionRecord | None":
        for rec in reversed(self.entries):
            if rec.status == STATUS_PENDING:
                return rec
        return None

    def open_decision(self, decision: Decision,
                      epoch_before: int) -> DecisionRecord:
        rec = DecisionRecord(
            decision_id=self.next_id, tick=decision.tick,
            action=decision.action, from_k=decision.current_k,
            to_k=decision.target_k, epoch_before=epoch_before)
        self.next_id += 1
        self.entries.append(rec)
        if len(self.entries) > self.max_entries:
            del self.entries[:len(self.entries) - self.max_entries]
        return rec

    def settle(self, decision_id: int, status: str) -> None:
        self.entries = [
            replace(r, status=status) if r.decision_id == decision_id
            else r for r in self.entries]

    def last_applied_tick(self) -> "int | None":
        for rec in reversed(self.entries):
            if rec.status == STATUS_APPLIED:
                return rec.tick
        return None

    def to_json(self) -> dict:
        return {"next_id": self.next_id,
                "max_entries": self.max_entries,
                "entries": [r.to_json() for r in self.entries]}

    @classmethod
    def from_json(cls, doc: "dict | None") -> "AutoscaleJournal":
        if doc is None:
            return cls()
        j = cls(next_id=int(doc.get("next_id", 1)),
                max_entries=int(doc.get("max_entries", 64)))
        j.entries = [DecisionRecord.from_json(r)
                     for r in doc.get("entries", [])]
        return j


class AutoscaleController:
    """One pipeline's scale controller. Pod-external like the
    coordinator it drives: writes through the RAW store (never a shard
    view) and must run as a singleton per pipeline — the journal's
    single-flight check assumes one writer."""

    def __init__(self, *, store, pipeline_id: int, collector,
                 coordinator, policy: "AutoscalePolicy | None" = None,
                 orchestrator=None, spec=None, scale_listener=None,
                 slo_weights: "dict[str, float] | None" = None):
        self.store = store
        self.pipeline_id = pipeline_id
        self.collector = collector  # async sample(at_s) -> SignalFrame
        self.coordinator = coordinator  # sharding.ShardCoordinator
        self.policy = policy or AutoscalePolicy()
        # orchestrator + spec: the production roll path
        # (Orchestrator.scale_pipeline). scale_listener: async
        # (from_k, to_k, RebalanceResult) — in-process fleets (chaos,
        # tests) roll their Pipelines here. Either, both, or neither.
        self.orchestrator = orchestrator
        self.spec = spec
        self.scale_listener = scale_listener
        self._slo_weights = dict(slo_weights or {})
        self._slo_applied = False
        self.timeline = SignalTimeline(
            max_frames=max(256, self.policy.config.window_frames))
        self.decisions: list[Decision] = []  # this process's trace
        # cooldown anchor after a restart: the journal's ticks belong to
        # the process that wrote them (see _last_decision_tick)
        self._restart_anchor: "int | None" = None

    # -- SLO weight feed (the PR 8 admission leftover) -----------------------

    def apply_slo_weights(self, scheduler=None) -> None:
        """Push the configured per-tenant SLO weights into the shared
        admission scheduler. Idempotent; called once at controller start
        (and again whenever the operator updates the mapping)."""
        if not self._slo_weights:
            return
        if scheduler is None:
            from ..ops.pipeline import global_admission

            scheduler = global_admission()
        for tenant, weight in sorted(self._slo_weights.items()):
            scheduler.set_slo_weight(tenant, weight)
        self._slo_applied = True
        logger.info("applied SLO admission weights: %s",
                    sorted(self._slo_weights.items()))

    # -- journal persistence -------------------------------------------------

    async def _load_journal(self) -> AutoscaleJournal:
        return AutoscaleJournal.from_json(
            await self.store.get_autoscale_journal())

    @handoff  # persist-then-actuate seam: the journal write IS the
    # happens-before edge a restarted controller resumes from
    async def _save_journal(self, journal: AutoscaleJournal) -> None:
        await self.store.update_autoscale_journal(journal.to_json())

    # -- the loop body -------------------------------------------------------

    @domain("coordinator")
    async def tick(self, at_s: float) -> Decision:
        """One closed-loop turn. Returns the decision (HOLD decisions
        carry the reason — cooldown, dead zone, overlap refusal)."""
        frame = await self.collector.sample(at_s)
        self.timeline.record(frame)
        assignment = await self.coordinator.current(
            bootstrap_shard_count=max(1, frame.shard_count))
        journal = await self._load_journal()

        def publish(decision: Decision) -> Decision:
            registry.gauge_set(ETL_AUTOSCALE_TARGET_SHARDS,
                               decision.target_k)
            registry.gauge_set(ETL_AUTOSCALE_BACKLOG_BYTES,
                               decision.backlog_bytes)
            registry.gauge_set(ETL_AUTOSCALE_CAPACITY_BYTES_PER_S,
                               decision.capacity_bytes_per_s)
            if decision.action == ACTION_HOLD:
                registry.counter_inc(
                    ETL_AUTOSCALE_HOLDS_TOTAL,
                    labels={"reason": decision.reason.split(":")[0]
                            .split(",")[0][:40]})
            self.decisions.append(decision)
            return decision

        # single-flight: an in-flight rebalance (ours or an operator's)
        # or a pending journal entry refuses this tick's decision
        if assignment.rebalancing or journal.pending() is not None:
            registry.gauge_set(ETL_AUTOSCALE_DECISION_IN_FLIGHT, 1)
            decision = self.policy.evaluate(
                self.timeline.frames, assignment.shard_count,
                self._last_decision_tick(journal, frame.tick))
            if decision.action != ACTION_HOLD:
                decision = replace(
                    decision, action=ACTION_HOLD,
                    target_k=assignment.shard_count,
                    reason="in_flight: a decision/rebalance is already "
                           "being applied (resume() or abort first)")
            return publish(decision)
        registry.gauge_set(ETL_AUTOSCALE_DECISION_IN_FLIGHT, 0)

        decision = self.policy.evaluate(
            self.timeline.frames, assignment.shard_count,
            self._last_decision_tick(journal, frame.tick))
        if decision.action == ACTION_HOLD:
            return publish(decision)

        # persist-then-actuate: the crash window between these two is
        # exactly what resume() covers
        rec = journal.open_decision(decision, assignment.epoch)
        await self._save_journal(journal)
        registry.gauge_set(ETL_AUTOSCALE_DECISION_IN_FLIGHT, 1)
        try:
            result = await self._actuate(rec)
        except BaseException:
            # leave the entry pending: a successor resumes or aborts it
            registry.gauge_set(ETL_AUTOSCALE_DECISION_IN_FLIGHT, 0)
            raise
        journal = await self._load_journal()
        journal.settle(rec.decision_id, STATUS_APPLIED)
        await self._save_journal(journal)
        registry.gauge_set(ETL_AUTOSCALE_DECISION_IN_FLIGHT, 0)
        registry.counter_inc(
            ETL_AUTOSCALE_DECISIONS_TOTAL,
            labels={"direction": "up" if decision.action == ACTION_UP
                    else "down"})
        logger.info("autoscale %s: K=%d->%d (epoch %d->%d): %s",
                    decision.action, rec.from_k, rec.to_k,
                    result.old_epoch, result.new_epoch, decision.reason)
        return publish(decision)

    def _last_decision_tick(self, journal: AutoscaleJournal,
                            current_tick: int) -> "int | None":
        """The cooldown anchor for this evaluation. Journal ticks live
        in the PROCESS that wrote them: a restarted controller's
        collector counts from 0 again, so a persisted tick larger than
        the current frame's would read as a huge negative age and hold
        every decision until the fresh counter overtook the dead
        process's (hours). Across a restart boundary the conservative
        and correct stance is 'the cooldown starts now': clamp the
        anchor to the current tick once, remember it in-process, and
        from then on this process's own applied decisions (which share
        the live tick domain) take over."""
        last = journal.last_applied_tick()
        if last is None:
            return self._restart_anchor
        if last > current_tick:
            # foreign tick domain (pre-crash process): anchor the
            # cooldown at this process's first observation of it
            if self._restart_anchor is None:
                self._restart_anchor = current_tick
            return self._restart_anchor
        return last

    async def _actuate(self, rec: DecisionRecord):
        """Drive the two-phase rebalance, then roll the fleet."""
        if rec.action == ACTION_UP:
            result = await self.coordinator.add_shard()
        elif rec.action == ACTION_DOWN:
            result = await self.coordinator.remove_shard()
        else:  # pragma: no cover - open_decision never journals holds
            raise EtlError(ErrorKind.INVALID_STATE_TRANSITION,
                           f"journaled decision with action {rec.action!r}")
        if result.new_shard_count != rec.to_k:
            raise EtlError(
                ErrorKind.INVALID_STATE_TRANSITION,
                f"decision {rec.decision_id} targeted K={rec.to_k} but "
                f"the rebalance landed K={result.new_shard_count}")
        await self._roll_fleet(rec, result)
        return result

    async def _roll_fleet(self, rec: DecisionRecord, result) -> None:
        if self.orchestrator is not None and self.spec is not None:
            await self.orchestrator.scale_pipeline(self.spec, rec.to_k)
        if self.scale_listener is not None:
            await self.scale_listener(rec.from_k, rec.to_k, result)

    # -- crash recovery ------------------------------------------------------

    @domain("coordinator")
    async def resume(self, abort: bool = False) -> "DecisionRecord | None":
        """Recover from a controller crash. Returns the settled record,
        or None when nothing was pending. Idempotent: re-running against
        an already-settled journal does nothing, and resuming a decision
        whose flip already happened only marks the journal."""
        journal = await self._load_journal()
        rec = journal.pending()
        if rec is None:
            return None
        assignment = await self.coordinator.current()
        registry.counter_inc(ETL_AUTOSCALE_RESUMES_TOTAL,
                             labels={"mode": "abort" if abort else "resume"})
        flip_done = (not assignment.rebalancing
                     and assignment.shard_count == rec.to_k
                     and assignment.epoch > rec.epoch_before)
        if flip_done:
            # crash AFTER the flip, before the journal mark: the
            # topology is already there — re-running is a no-op beyond
            # settling the journal (and rolling the fleet, which is
            # itself an idempotent re-apply). This path wins even under
            # abort=True: an epoch flip is not abortable (pods are
            # already fenced onto the new topology); 'aborting' here
            # would strand a flipped assignment with an un-rolled fleet
            # — the moved tables would have no owning pod.
            if abort:
                logger.warning(
                    "autoscale decision %d (K=%d->%d): abort requested "
                    "but the epoch flip already happened — settling as "
                    "applied and rolling the fleet instead",
                    rec.decision_id, rec.from_k, rec.to_k)
            await self._roll_fleet(rec, _SettledResult(rec, assignment))
            journal.settle(rec.decision_id, STATUS_APPLIED)
            await self._save_journal(journal)
            return replace(rec, status=STATUS_APPLIED)
        if abort:
            if assignment.rebalancing:
                await self.coordinator.abort_rebalance()
            journal.settle(rec.decision_id, STATUS_ABORTED)
            await self._save_journal(journal)
            logger.info("autoscale decision %d (K=%d->%d) aborted",
                        rec.decision_id, rec.from_k, rec.to_k)
            return replace(rec, status=STATUS_ABORTED)
        # crash BEFORE or DURING the rebalance: re-drive the same
        # coordinator action — its persisted record resumes with the
        # original fence (or starts fresh if the crash preceded 1b)
        await self._actuate(rec)
        journal = await self._load_journal()
        journal.settle(rec.decision_id, STATUS_APPLIED)
        await self._save_journal(journal)
        logger.info("autoscale decision %d (K=%d->%d) resumed to applied",
                    rec.decision_id, rec.from_k, rec.to_k)
        return replace(rec, status=STATUS_APPLIED)

    # -- optional interval loop ----------------------------------------------

    async def run(self, interval_s: float = 5.0, shutdown=None) -> None:
        """Simple periodic driver for sidecar deployments: resume any
        crash-interrupted decision first, apply SLO weights, then tick
        forever (or until `shutdown` — a ShutdownSignal-alike with
        `.triggered` — fires). Chaos and bench drive tick() directly."""
        import time

        await self.resume()
        self.apply_slo_weights()
        while shutdown is None or not shutdown.triggered:
            await self.tick(time.monotonic())
            await asyncio.sleep(interval_s)


class _SettledResult:
    """RebalanceResult-shaped view of an already-flipped assignment (the
    resume-after-flip path has no live result to hand the listener)."""

    def __init__(self, rec: DecisionRecord, assignment):
        self.old_epoch = rec.epoch_before
        self.new_epoch = assignment.epoch
        self.old_shard_count = rec.from_k
        self.new_shard_count = assignment.shard_count
        self.fence_lsn = assignment.fence_lsn
        self.moved = {}
        self.duration_s = 0.0
