"""Autoscale signal plane: per-shard lag/rate/pressure frames.

The control loop never reads raw telemetry mid-decision. A collector
samples everything the policy needs into an immutable `SignalFrame` —
per-shard replication lag (received−durable bytes, the
`postgres/lag.py` SlotLagMetrics shape), durable-progress LSNs (the
drain-rate evidence), delivered event counts, memory-pressure and
health state — and the policy is then a pure function of the frame
HISTORY (policy.py). That split is what makes the whole loop
deterministic: a recorded (or seeded-synthetic) timeline replays the
identical decision trace through `python -m etl_tpu.autoscale --replay`,
and the chaos scenarios assert on exact decision sequences per seed.

Two collectors ship:

  RegistrySignalSource — reads the in-process telemetry registry
      (`etl_slot_lag_bytes{shard}` + `etl_shard_delivered_events{shard}`,
      published by the apply loop on its status-update cadence, and the
      memory-backpressure gauge). The single-process vantage: bench
      runs, tests, and a sidecar controller sharing the pod.
  StoreSignalSource — the COORDINATOR's vantage: per-shard lag computed
      as (source WAL position − per-shard apply-slot durable progress)
      against the shared StateStore, plus per-shard health probes. This
      is what the pod-external controller runs against K replicator
      pods it cannot share a process with.

Frames and timelines serialize to JSON (`--replay` files, chaos
manifests). `seeded_surge_timeline` generates the canonical synthetic
surge→drain story deterministically per seed — the replay CLI default,
the bench reaction-time gate, and the hysteresis property tests all
draw from it.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from ..models.errors import ErrorKind, EtlError


@dataclass(frozen=True)
class ShardSignals:
    """One shard's sampled state inside a frame. `lag_bytes` is
    received−durable WAL bytes (SlotLagMetrics.confirmed_flush_lag
    shape); `durable_lsn` is the raw progress LSN so the policy can
    derive drain rates from consecutive frames without the collector
    smuggling a clock into the data."""

    shard: int
    lag_bytes: int
    durable_lsn: int = 0
    delivered_events: int = 0
    memory_pressure: bool = False
    healthy: bool = True

    def to_json(self) -> dict:
        return {
            "shard": self.shard,
            "lag_bytes": self.lag_bytes,
            "durable_lsn": self.durable_lsn,
            "delivered_events": self.delivered_events,
            "memory_pressure": self.memory_pressure,
            "healthy": self.healthy,
        }

    @classmethod
    def from_json(cls, doc: dict) -> "ShardSignals":
        return cls(
            shard=int(doc["shard"]),
            lag_bytes=int(doc.get("lag_bytes", 0)),
            durable_lsn=int(doc.get("durable_lsn", 0)),
            delivered_events=int(doc.get("delivered_events", 0)),
            memory_pressure=bool(doc.get("memory_pressure", False)),
            healthy=bool(doc.get("healthy", True)),
        )


@dataclass(frozen=True)
class SignalFrame:
    """One evaluation tick's complete input. `at_s` is the sample time
    in SECONDS on whatever clock the collector used — the policy only
    ever takes deltas, so synthetic timelines use the tick index and
    live collectors use a monotonic clock; neither leaks into the
    decision beyond rate denominators."""

    tick: int
    at_s: float
    shards: tuple = ()

    @property
    def shard_count(self) -> int:
        return len(self.shards)

    @property
    def aggregate_backlog_bytes(self) -> int:
        return sum(s.lag_bytes for s in self.shards)

    @property
    def any_memory_pressure(self) -> bool:
        return any(s.memory_pressure for s in self.shards)

    @property
    def all_healthy(self) -> bool:
        return all(s.healthy for s in self.shards)

    def to_json(self) -> dict:
        return {"tick": self.tick, "at_s": self.at_s,
                "shards": [s.to_json() for s in self.shards]}

    @classmethod
    def from_json(cls, doc: dict) -> "SignalFrame":
        return cls(tick=int(doc["tick"]), at_s=float(doc["at_s"]),
                   shards=tuple(ShardSignals.from_json(s)
                                for s in doc.get("shards", [])))


@dataclass
class SignalTimeline:
    """Bounded frame history (newest last). The policy receives the
    whole list; the bound exists so a long-lived controller's memory
    stays flat, not to hide data from the policy — `max_frames` is
    always ≥ the policy's evaluation window."""

    max_frames: int = 256
    frames: list = field(default_factory=list)

    def record(self, frame: SignalFrame) -> None:
        if self.frames and frame.tick <= self.frames[-1].tick:
            raise EtlError(
                ErrorKind.INVALID_STATE_TRANSITION,
                f"signal frame tick regression: "
                f"{self.frames[-1].tick} -> {frame.tick}")
        self.frames.append(frame)
        if len(self.frames) > self.max_frames:
            del self.frames[:len(self.frames) - self.max_frames]

    def to_json(self) -> dict:
        return {"max_frames": self.max_frames,
                "frames": [f.to_json() for f in self.frames]}

    @classmethod
    def from_json(cls, doc: dict) -> "SignalTimeline":
        tl = cls(max_frames=int(doc.get("max_frames", 256)))
        for f in doc.get("frames", []):
            tl.record(SignalFrame.from_json(f))
        return tl


class RegistrySignalSource:
    """Samples the in-process telemetry registry: the per-shard lag and
    delivered-events gauges the apply loop publishes on its status
    cadence (`runtime/apply_loop.py`), plus the process-wide memory
    backpressure gauge. Shards that have never published read as lag 0 /
    healthy — a frame is always total over the CURRENT shard count.

    `shard_count` may be an int (a fixed topology) or a zero-arg
    callable returning the live K (pass the controller's
    assignment-reader on an autoscaled fleet): a pinned count would keep
    sampling retired shards' never-cleared gauges after a scale-down —
    inflating backlog forever — and miss new shards after a scale-up."""

    def __init__(self, shard_count):
        if not callable(shard_count) and int(shard_count) < 1:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           f"shard_count must be >= 1, got {shard_count}")
        self._count_reader = shard_count if callable(shard_count) \
            else (lambda: shard_count)
        self._tick = 0

    @property
    def shard_count(self) -> int:
        return max(1, int(self._count_reader()))

    async def sample(self, at_s: float) -> SignalFrame:
        from ..telemetry.metrics import (ETL_MEMORY_BACKPRESSURE_ACTIVE,
                                         ETL_SHARD_DELIVERED_EVENTS,
                                         ETL_SLOT_LAG_BYTES, registry)

        pressure = bool(registry.get_gauge(
            ETL_MEMORY_BACKPRESSURE_ACTIVE) or 0)
        shards = []
        for shard in range(self.shard_count):
            labels = {"shard": str(shard)}
            lag = registry.get_gauge(ETL_SLOT_LAG_BYTES, labels) or 0
            delivered = registry.get_gauge(ETL_SHARD_DELIVERED_EVENTS,
                                           labels) or 0
            shards.append(ShardSignals(
                shard=shard, lag_bytes=int(lag),
                delivered_events=int(delivered),
                memory_pressure=pressure))
        tick = self._tick
        self._tick += 1
        return SignalFrame(tick=tick, at_s=at_s, shards=tuple(shards))


class StoreSignalSource:
    """The pod-external (coordinator-vantage) collector: lag per shard =
    source WAL position − that shard's apply-slot durable progress, read
    from the SHARED store — the exact quantity the two-phase rebalance
    quiesce waits on, so the policy scales on the same evidence the
    actuation will later fence against. `health` is an optional async
    per-shard probe (e.g. the pod's /health endpoint); absent probes
    read healthy, because an autoscaler that refuses to act whenever a
    health endpoint is unreachable would freeze exactly when it is
    needed most — the policy still HOLDS on explicit unhealthy."""

    def __init__(self, store, pipeline_id: int, source_factory,
                 shard_count_reader, health=None, pressure=None):
        self.store = store
        self.pipeline_id = pipeline_id
        self.source_factory = source_factory
        # () -> int: the CURRENT topology K (the authoritative
        # assignment's shard_count — the controller passes a closure
        # over its last-read assignment so collector and policy agree)
        self.shard_count_reader = shard_count_reader
        self._health = health  # async (shard) -> bool | None
        self._pressure = pressure  # (shard) -> bool | None
        self._tick = 0

    async def sample(self, at_s: float) -> SignalFrame:
        from ..postgres.slots import apply_slot_name

        source = self.source_factory()
        await source.connect()
        try:
            wal_end = int(await source.get_current_wal_lsn())
        finally:
            await source.close()
        shards = []
        for shard in range(max(1, int(self.shard_count_reader()))):
            durable = await self.store.get_durable_progress(
                apply_slot_name(self.pipeline_id, shard))
            durable_i = int(durable) if durable is not None else 0
            healthy = True
            if self._health is not None:
                probed = await self._health(shard)
                healthy = True if probed is None else bool(probed)
            pressure = bool(self._pressure(shard)) \
                if self._pressure is not None else False
            shards.append(ShardSignals(
                shard=shard,
                lag_bytes=max(0, wal_end - durable_i),
                durable_lsn=durable_i,
                memory_pressure=pressure,
                healthy=healthy))
        tick = self._tick
        self._tick += 1
        return SignalFrame(tick=tick, at_s=at_s, shards=tuple(shards))


def seeded_surge_timeline(seed: int = 7, *, shards: int = 2,
                          ticks: int = 40, surge_at: int = 10,
                          surge_ticks: int = 6,
                          baseline_lag: int = 2_048,
                          surge_lag: int = 512 * 1024,
                          drain_per_tick: int = 128 * 1024,
                          noise: int = 512,
                          interval_s: float = 1.0) -> SignalTimeline:
    """The canonical synthetic story, bit-identical per seed: quiet
    baseline (small noisy lag), a backlog surge at `surge_at` held for
    `surge_ticks`, then a linear drain back to baseline. Durable LSNs
    advance at a steady per-tick rate so the policy's capacity estimate
    is well-defined. Used by the replay CLI's --synthetic mode, the
    bench reaction-time gate (`bench.py --autoscale`), and the
    hysteresis property tests (noise around a band edge must not flap).
    """
    rng = random.Random(seed)
    tl = SignalTimeline(max_frames=max(ticks, 256))
    durable = [0] * shards
    lag = [baseline_lag] * shards
    for tick in range(ticks):
        if tick == surge_at:
            for s in range(shards):
                lag[s] += surge_lag
        elif tick > surge_at + surge_ticks:
            for s in range(shards):
                lag[s] = max(baseline_lag, lag[s] - drain_per_tick)
        frame_shards = []
        for s in range(shards):
            durable[s] += drain_per_tick
            jitter = rng.randrange(-noise, noise + 1)
            frame_shards.append(ShardSignals(
                shard=s, lag_bytes=max(0, lag[s] + jitter),
                durable_lsn=durable[s],
                delivered_events=durable[s] // 64))
        tl.record(SignalFrame(tick=tick, at_s=tick * interval_s,
                              shards=tuple(frame_shards)))
    return tl
