"""etl_tpu — TPU-native Postgres logical-replication ETL framework.

A ground-up re-design of the capability surface of supabase/etl
(/root/reference, Rust) for the TPU stack: the control plane and the
Postgres protocol plane run on host (asyncio + a C hot path for framing),
while the WAL-decode / CDC row-transform hot loop — pgoutput tuple decode,
COPY text decode, type coercion, publication filtering, row→columnar
transpose — runs on TPU via JAX/Pallas as fixed-shape, column-parallel
programs over ragged byte batches.

Layer map (mirrors reference SURVEY.md §1):
  models/        data model: LSN, schema+masks, cells, events, errors
  config/        typed config + YAML/env loader        (ref: etl-config)
  postgres/      wire protocol, replication client, CPU codecs
                                                       (ref: crates/etl/src/postgres)
  ops/           TPU decode engine: staging + jitted/Pallas decode kernels
  parallel/      device mesh + shard_map data/column-parallel decode
  runtime/       pipeline, apply loop, table-sync workers, backpressure
                                                       (ref: crates/etl/src/{replication,runtime})
  store/         state/schema stores (memory, postgres) (ref: crates/etl/src/store)
  destinations/  Destination implementations            (ref: crates/etl-destinations)
  telemetry/     metrics + tracing                      (ref: crates/etl-telemetry)
"""

__version__ = "0.1.0"
