"""The supervisor: a periodic sweep over every component's heartbeat,
driving detections, escalations, and the health state machine.

Detection (per component, per sweep):

  hang   — heartbeat age exceeded the hang deadline: the task/thread
           stopped beating entirely (wedged await, blocked thread);
  stall  — heartbeat fresh, `busy=True`, progress token frozen past the
           stall deadline: alive but stuck (a flush that never acks, an
           apply loop whose durable LSN stopped advancing).

Escalation:

  restart — restartable components get their `on_restart` callback
            invoked (rate-limited by `restart_backoff_s`); the owning
            worker converts that into EtlError(STALL_DETECTED), which
            classifies TIMED, so recovery rides the existing RetryPolicy
            backoff and re-streams from durable progress;
  degrade — `device_degrade_threshold` detections on decode components
            force the batch engine to the host oracle for
            `device_degrade_cooldown_s` (ops/engine.force_host_oracle):
            a flaky device link costs throughput, not availability;
  breaker — destination breakers are polled; a non-closed breaker holds
            a degraded reason (the breaker itself is tripped inline by
            SupervisedDestination on write failures).

Every detection/escalation emits a SupervisionEvent to listeners (the
chaos runner budgets re-delivery from restart events) and a labeled
metric counter.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Callable

from ..config.pipeline import SupervisionConfig
from .breaker import BreakerState, CircuitBreaker
from .health import HealthStateMachine
from .heartbeat import ComponentPolicy, Heartbeat, HeartbeatRegistry

logger = logging.getLogger("etl_tpu.supervision")

#: component-name prefix that marks decode pipelines (device-side work):
#: their detections count toward the host-oracle degrade escalation
DECODE_PREFIX = "decode:"


@dataclass(frozen=True)
class SupervisionEvent:
    kind: str  # "stall" | "hang" | "restart" | "breaker" | "degrade"
    component: str
    detail: str
    at: float = field(default_factory=time.monotonic)


class Supervisor:
    """One per pipeline. `start()` spawns the sweep task on the running
    loop; components register through `self.registry` (or the `register`
    convenience that fills deadline defaults from config)."""

    def __init__(self, config: SupervisionConfig | None = None):
        self.config = config or SupervisionConfig()
        self.registry = HeartbeatRegistry()
        self.health = HealthStateMachine()
        self.breakers: dict[str, CircuitBreaker] = {}
        self.events: list[SupervisionEvent] = []
        self._listeners: list[Callable[[SupervisionEvent], None]] = []
        self._task: asyncio.Task | None = None
        self._last_restart: dict[str, float] = {}
        self._device_detections = 0
        self.started = False

    # -- wiring --------------------------------------------------------------

    def register(self, name: str, *, stall_deadline_s: float | None = None,
                 hang_deadline_s: float | None = None,
                 restartable: bool = False,
                 hang_requires_busy: bool | None = None,
                 on_restart: Callable[[], None] | None = None) -> Heartbeat:
        if hang_requires_busy is None:
            # work-driven by default for decode pipelines + destination:
            # they beat only when work flows
            hang_requires_busy = name.startswith(DECODE_PREFIX) \
                or name == "destination"
        policy = ComponentPolicy(
            stall_deadline_s=stall_deadline_s,
            hang_deadline_s=hang_deadline_s,
            restartable=restartable,
            hang_requires_busy=hang_requires_busy)
        return self.registry.register(name, policy, on_restart=on_restart)

    def breaker(self, name: str) -> CircuitBreaker:
        """Get-or-create the named destination breaker (thresholds from
        config); its transitions feed health + events."""
        b = self.breakers.get(name)
        if b is None:
            b = CircuitBreaker(
                name,
                failure_threshold=self.config.breaker_failure_threshold,
                cooldown_s=self.config.breaker_cooldown_s,
                on_transition=lambda old, new, _n=name:
                    self._on_breaker_transition(_n, old, new))
            self.breakers[name] = b
        return b

    def add_listener(self, cb: Callable[[SupervisionEvent], None]) -> None:
        self._listeners.append(cb)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._task is None:
            self.started = True
            self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        interval = self.config.check_interval_s
        while True:
            try:
                self.sweep_once()
            except Exception:  # the watchdog must outlive its own bugs; CancelledError is BaseException, passes through
                logger.exception("supervision sweep failed")
            await asyncio.sleep(interval)

    # -- the sweep -----------------------------------------------------------

    def sweep_once(self) -> list[SupervisionEvent]:
        """One detection pass; returns the events it emitted (tests and
        the sweep task both call this)."""
        from ..telemetry.metrics import (ETL_HEARTBEAT_MAX_AGE_SECONDS,
                                         registry)

        cfg = self.config
        now = time.monotonic()
        emitted: list[SupervisionEvent] = []
        max_age = 0.0
        components = self.registry.components()
        for hb in components:
            age = hb.age(now)
            max_age = max(max_age, age)
            hang_deadline = hb.policy.hang_deadline_s \
                if hb.policy.hang_deadline_s is not None \
                else cfg.hang_deadline_s
            stall_deadline = hb.policy.stall_deadline_s \
                if hb.policy.stall_deadline_s is not None \
                else cfg.stall_deadline_s
            if age > hang_deadline \
                    and (hb.busy or not hb.policy.hang_requires_busy):
                emitted += self._detected(
                    "hang", hb,
                    f"heartbeat stale {age:.2f}s > {hang_deadline:.2f}s")
            elif hb.busy and hb.progress_age(now) > stall_deadline:
                emitted += self._detected(
                    "stall", hb,
                    f"busy with progress frozen "
                    f"{hb.progress_age(now):.2f}s > {stall_deadline:.2f}s "
                    f"at {hb.progress!r}")
            else:
                self.health.clear_reason(f"component:{hb.name}")
        # a component that unregistered (worker exit, pipeline close)
        # takes its anomaly with it — otherwise a restarted worker's old
        # reason pins the machine degraded forever
        active = {f"component:{hb.name}" for hb in components}
        for key in self.health.reasons:
            if key.startswith("component:") and key not in active:
                self.health.clear_reason(key)
        for name, b in self.breakers.items():
            if b.state is BreakerState.CLOSED:
                self.health.clear_reason(f"breaker:{name}")
            else:
                self.health.set_reason(
                    f"breaker:{name}", f"breaker {b.state.value} after "
                    f"{b.consecutive_failures} consecutive failures")
        registry.gauge_set(ETL_HEARTBEAT_MAX_AGE_SECONDS, max_age)
        # the device-degrade reason lifts itself once the cooldown lapses
        if "device-degraded" in self.health.reasons:
            from ..ops import engine

            if not engine.host_oracle_forced():
                self.health.clear_reason("device-degraded")
        return emitted

    def _detected(self, kind: str, hb: Heartbeat,
                  detail: str) -> list[SupervisionEvent]:
        from ..ops import engine
        from ..telemetry.metrics import (ETL_SUPERVISION_EVENTS_TOTAL,
                                         ETL_SUPERVISION_RESTARTS_TOTAL,
                                         registry)

        out = [self._emit(SupervisionEvent(kind, hb.name, detail))]
        registry.counter_inc(ETL_SUPERVISION_EVENTS_TOTAL,
                             labels={"kind": kind, "component": hb.name})
        self.health.set_reason(f"component:{hb.name}",
                               f"{kind}: {detail}")
        logger.warning("supervision %s on %s: %s", kind, hb.name, detail)
        if hb.name.startswith(DECODE_PREFIX):
            self._device_detections += 1
            if self._device_detections >= self.config.device_degrade_threshold:
                self._device_detections = 0
                cooldown = self.config.device_degrade_cooldown_s
                engine.force_host_oracle(cooldown)
                self.health.set_reason(
                    "device-degraded",
                    f"batch engine degraded to host oracle for "
                    f"{cooldown:.0f}s after repeated device-side stalls")
                out.append(self._emit(SupervisionEvent(
                    "degrade", hb.name,
                    f"host-oracle degrade for {cooldown:.0f}s")))
        if hb.policy.restartable and hb.on_restart is not None:
            now = time.monotonic()
            last = self._last_restart.get(hb.name, -1e9)
            if now - last >= self.config.restart_backoff_s:
                self._last_restart[hb.name] = now
                hb.reset_clocks()
                registry.counter_inc(ETL_SUPERVISION_RESTARTS_TOTAL,
                                     labels={"component": hb.name})
                out.append(self._emit(SupervisionEvent(
                    "restart", hb.name, f"cancel-and-restart after {kind}")))
                hb.on_restart()
        return out

    def _emit(self, ev: SupervisionEvent) -> SupervisionEvent:
        self.events.append(ev)
        del self.events[:-256]
        for cb in list(self._listeners):
            cb(ev)
        return ev

    def _on_breaker_transition(self, name: str, old: BreakerState,
                               new: BreakerState) -> None:
        self._emit(SupervisionEvent(
            "breaker", name, f"{old.value} -> {new.value}"))
        if new is BreakerState.CLOSED:
            self.health.clear_reason(f"breaker:{name}")
        else:
            self.health.set_reason(f"breaker:{name}",
                                   f"breaker {new.value}")

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "started": self.started,
            "health": self.health.snapshot(),
            "components": self.registry.snapshot(),
            "breakers": {n: b.snapshot() for n, b in self.breakers.items()},
            "recent_events": [
                {"kind": e.kind, "component": e.component,
                 "detail": e.detail} for e in self.events[-16:]],
        }
