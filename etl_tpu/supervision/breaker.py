"""Per-destination circuit breaker: closed → open → half-open → closed.

A dead or drowning sink must shed load into backpressure instead of
queuing unbounded work: after `failure_threshold` CONSECUTIVE write
failures the breaker opens and every call fails fast with
EtlError(DESTINATION_UNAVAILABLE) — no payload reaches the sink, the
apply worker's RetryPolicy backoff becomes the pacing, and WAL intake
pauses with it (the walsender buffers upstream). After `cooldown_s` one
trial call is admitted (half-open); its success closes the breaker, its
failure re-opens it for another cooldown.

DESTINATION_UNAVAILABLE is worker-retryable (re-stream after backoff)
but never writer-retryable in place — an in-place retry against an open
breaker would just spin the fast-fail.
"""

from __future__ import annotations

import enum
import time

from ..models.errors import ErrorKind, EtlError


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


def breaker_is_open(destination) -> bool:
    """True when `destination` (usually a SupervisedDestination) carries
    a circuit breaker in the OPEN (shedding) state. Plain destinations
    have no breaker. THE shared probe for dispatch gating
    (runtime/apply_loop.py) and poison-isolation abort
    (runtime/poison.py) — one definition of "the sink is being shed"."""
    breaker = getattr(destination, "breaker", None)
    if breaker is None:
        return False
    return getattr(breaker, "state", None) is BreakerState.OPEN


#: gauge encoding for ETL_DESTINATION_BREAKER_STATE
_STATE_VALUE = {BreakerState.CLOSED: 0, BreakerState.HALF_OPEN: 1,
                BreakerState.OPEN: 2}


class CircuitBreaker:
    def __init__(self, name: str = "destination", *,
                 failure_threshold: int = 5, cooldown_s: float = 15.0,
                 on_transition=None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.name = name
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.state = BreakerState.CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0
        self.opens_total = 0
        self._trial_in_flight = False
        self._on_transition = on_transition  # (old, new) -> None

    # -- gate ----------------------------------------------------------------

    def before_call(self) -> None:
        """Admission control; raises when the call must be shed."""
        if self.state is BreakerState.CLOSED:
            return
        now = time.monotonic()
        if self.state is BreakerState.OPEN:
            if now - self.opened_at < self.cooldown_s:
                raise EtlError(
                    ErrorKind.DESTINATION_UNAVAILABLE,
                    f"circuit breaker {self.name!r} open "
                    f"({self.consecutive_failures} consecutive failures; "
                    f"retry in "
                    f"{self.cooldown_s - (now - self.opened_at):.1f}s)")
            self._transition(BreakerState.HALF_OPEN)
        # half-open: admit exactly one trial at a time
        if self._trial_in_flight:
            raise EtlError(
                ErrorKind.DESTINATION_UNAVAILABLE,
                f"circuit breaker {self.name!r} half-open with a trial "
                f"call already in flight")
        self._trial_in_flight = True

    def record_success(self) -> None:
        self.consecutive_failures = 0
        self._trial_in_flight = False
        if self.state is not BreakerState.CLOSED:
            self._transition(BreakerState.CLOSED)

    def abort_call(self) -> None:
        """The admitted call ended without a verdict (cancelled mid-
        flight by a worker restart, or its ack was abandoned): release
        the half-open trial slot so the NEXT call can trial — without
        this a cancelled trial wedges the breaker open forever."""
        self._trial_in_flight = False

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state is BreakerState.HALF_OPEN:
            self._trial_in_flight = False
            self._open()
        elif self.state is BreakerState.CLOSED \
                and self.consecutive_failures >= self.failure_threshold:
            self._open()

    def _open(self) -> None:
        self.opened_at = time.monotonic()
        self.opens_total += 1
        from ..telemetry.metrics import (
            ETL_DESTINATION_BREAKER_OPENS_TOTAL, registry)

        registry.counter_inc(ETL_DESTINATION_BREAKER_OPENS_TOTAL,
                             labels={"breaker": self.name})
        self._transition(BreakerState.OPEN)

    def _transition(self, new: BreakerState) -> None:
        old, self.state = self.state, new
        from ..telemetry.metrics import (ETL_DESTINATION_BREAKER_STATE,
                                         registry)

        registry.gauge_set(ETL_DESTINATION_BREAKER_STATE, _STATE_VALUE[new],
                           {"breaker": self.name})
        if self._on_transition is not None and old is not new:
            self._on_transition(old, new)

    def snapshot(self) -> dict:
        return {
            "state": self.state.value,
            "consecutive_failures": self.consecutive_failures,
            "opens_total": self.opens_total,
            "cooldown_s": self.cooldown_s,
        }
