"""Heartbeats: cheap liveness + progress publication for every
long-running component.

A component publishes `beat(progress=token, busy=flag)` from wherever it
makes progress — the apply loop's select wakeups, a table-sync worker's
copy chunks, the decode pipeline's worker thread, the memory monitor's
sample tick. A beat is three attribute writes and one comparison; no
locks on the publish path (CPython attribute stores are atomic, and the
supervisor tolerates torn *pairs* because it re-reads every sweep).

The progress token is opaque to the supervisor: any value whose CHANGE
means forward progress (an LSN pair, a byte count, a completed-batch
counter). The supervisor's two detections read off this contract:

  hang   — `age() > hang_deadline`: the component stopped beating at
           all; the task/thread is wedged somewhere that never returns.
  stall  — beats keep arriving with `busy=True` but the progress token
           has not changed for `stall_deadline`: the component is alive
           but its work is stuck (a flush that never acks, an LSN that
           stops advancing).

`busy=False` beats park the stall clock: an idle component (no WAL, no
work in flight) legitimately makes no progress. Components about to
enter a long legitimate wait should either keep beating (see
`beat_while_waiting`) or have a hang deadline sized for the wait.
"""

from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, TypeVar

T = TypeVar("T")


@dataclass(frozen=True)
class ComponentPolicy:
    """Per-component deadlines; None inherits the supervisor default."""

    stall_deadline_s: float | None = None
    hang_deadline_s: float | None = None
    # True for components the supervisor may cancel-and-restart; False
    # for observe-only components (memory monitor, decode pipelines —
    # their recovery rides their owning worker's restart)
    restartable: bool = False
    # work-driven components (decode pipelines, destination wrappers)
    # beat only when work flows, so a stale heartbeat is a hang ONLY if
    # the last beat claimed work in flight; timer-driven components
    # (apply loop select wakeups, monitor sample ticks) hang on
    # staleness alone
    hang_requires_busy: bool = False


class Heartbeat:
    """One component's liveness slot. Publish-side is wait-free."""

    __slots__ = ("name", "policy", "registry", "last_beat", "progress",
                 "progress_at", "busy", "beats", "on_restart")

    def __init__(self, name: str, policy: ComponentPolicy,
                 registry: "HeartbeatRegistry | None" = None,
                 on_restart: Callable[[], None] | None = None):
        self.name = name
        self.policy = policy
        self.registry = registry
        self.on_restart = on_restart
        now = time.monotonic()
        self.last_beat = now
        self.progress: object = None
        self.progress_at = now
        self.busy = False
        self.beats = 0

    def beat(self, progress: object = None, busy: bool = False) -> None:
        """Publish liveness. Called from event-loop tasks AND worker
        threads — must stay allocation-free and lock-free."""
        now = time.monotonic()
        self.last_beat = now
        self.beats += 1
        self.busy = busy
        if progress is not None and progress != self.progress:
            self.progress = progress
            self.progress_at = now

    def reset_clocks(self) -> None:
        """Give a just-restarted component fresh deadlines so the sweep
        that triggered the restart doesn't immediately re-trip on it."""
        now = time.monotonic()
        self.last_beat = now
        self.progress_at = now
        self.busy = False

    def age(self, now: float | None = None) -> float:
        return (now if now is not None else time.monotonic()) - self.last_beat

    def progress_age(self, now: float | None = None) -> float:
        return (now if now is not None else time.monotonic()) \
            - self.progress_at

    def close(self) -> None:
        if self.registry is not None:
            self.registry.unregister(self.name)

    def snapshot(self) -> dict:
        now = time.monotonic()
        return {
            "age_s": round(self.age(now), 3),
            "progress_age_s": round(self.progress_age(now), 3),
            "busy": self.busy,
            "beats": self.beats,
            "progress": repr(self.progress),
            "restartable": self.policy.restartable,
        }


class HeartbeatRegistry:
    """All live components of one pipeline. Registration is rare and
    locked; the supervisor snapshots the component list per sweep."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._components: dict[str, Heartbeat] = {}

    def register(self, name: str,
                 policy: ComponentPolicy | None = None,
                 on_restart: Callable[[], None] | None = None) -> Heartbeat:
        """Create (or replace — a restarted worker re-registers) the
        component's heartbeat slot."""
        hb = Heartbeat(name, policy or ComponentPolicy(), registry=self,
                       on_restart=on_restart)
        with self._lock:
            self._components[name] = hb
        return hb

    def unregister(self, name: str) -> None:
        with self._lock:
            self._components.pop(name, None)

    def get(self, name: str) -> Heartbeat | None:
        with self._lock:
            return self._components.get(name)

    def components(self) -> list[Heartbeat]:
        with self._lock:
            return list(self._components.values())

    def snapshot(self) -> dict[str, dict]:
        return {hb.name: hb.snapshot() for hb in self.components()}


async def beat_while_waiting(hb: Heartbeat | None, aw: Awaitable[T],
                             interval_s: float = 0.5) -> T:
    """Await `aw` while keeping `hb` fresh — for legitimate long parks
    (the apply loop waiting out a table-sync handoff, a sync worker
    parked on its catchup target) that must not read as hangs. The beat
    carries busy=False, so the stall clock stays parked too."""
    if hb is None:
        return await aw
    task = asyncio.ensure_future(aw)
    try:
        while True:
            done, _ = await asyncio.wait({task}, timeout=interval_s)
            hb.beat(busy=False)
            if task in done:
                return task.result()
    finally:
        # drain without eating the caller's own cancellation
        # (runtime/shutdown.drain_cancelled rationale)
        from ..runtime.shutdown import drain_cancelled

        await drain_cancelled(task)
