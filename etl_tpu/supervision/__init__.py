"""Supervision tree: liveness watchdogs, health state machine, circuit
breakers, and escalation policies (docs/supervision.md).

Every long-running component publishes cheap heartbeats carrying a
progress token; the Supervisor detects stalls (fresh heartbeat, frozen
progress) and hangs (stale heartbeat), drives the pipeline-wide
healthy → degraded → faulted state machine, and escalates: cancel-and-
restart through the existing RetryPolicy backoff, degrade the TPU batch
engine to the host oracle, trip per-destination circuit breakers that
shed load into backpressure.
"""

from .breaker import BreakerState, CircuitBreaker
from .destination import BoundedAck, SupervisedDestination
from .health import HealthState, HealthStateMachine
from .heartbeat import (ComponentPolicy, Heartbeat, HeartbeatRegistry,
                        beat_while_waiting)
from .supervisor import DECODE_PREFIX, SupervisionEvent, Supervisor

__all__ = [
    "BoundedAck",
    "BreakerState",
    "CircuitBreaker",
    "ComponentPolicy",
    "DECODE_PREFIX",
    "HealthState",
    "HealthStateMachine",
    "Heartbeat",
    "HeartbeatRegistry",
    "SupervisedDestination",
    "SupervisionEvent",
    "Supervisor",
    "beat_while_waiting",
]
