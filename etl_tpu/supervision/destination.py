"""SupervisedDestination: timeout bounds + circuit breaker + heartbeat
around any Destination.

Every `startup`/`write_*`/`truncate`/`drop` call — and the durability
wait of every returned ack — is bounded by the configured per-call
timeout, so a destination that never returns surfaces as a classified
`EtlError(TIMEOUT)` instead of an eternal await. Failures feed the
per-destination circuit breaker; an open breaker sheds subsequent calls
with DESTINATION_UNAVAILABLE before any payload is built, turning a dead
sink into worker-backoff backpressure instead of an unbounded queue.

Chaos stall surface: `destination.write` stalls fire here (before the
bounded region's clock starts for the breaker, inside it for the
timeout), `destination.flush` stalls fire inside the bounded
`wait_durable`.
"""

from __future__ import annotations

import asyncio
from typing import Sequence

from ..chaos import failpoints
from ..destinations.base import Destination, WriteAck
from ..models.errors import ErrorKind, EtlError, is_poison_error as _is_poison
from .breaker import CircuitBreaker
from .heartbeat import Heartbeat


class BoundedAck(WriteAck):
    """WriteAck whose wait_durable is bounded by the op timeout and
    reported to the breaker: a flush that never resolves is a sink
    failure like any other."""

    __slots__ = ("_inner", "_timeout", "_breaker", "_hb")

    def __init__(self, inner: WriteAck, timeout_s: float,
                 breaker: CircuitBreaker | None,
                 hb: Heartbeat | None):
        self._inner = inner
        self._timeout = timeout_s
        self._breaker = breaker
        self._hb = hb

    @property
    def is_durable(self) -> bool:
        return self._inner.is_durable

    async def wait_durable(self) -> None:
        try:
            if self._timeout > 0:
                await asyncio.wait_for(self._inner.wait_durable(),
                                       self._timeout)
            else:
                await self._inner.wait_durable()
        except asyncio.TimeoutError:
            self._record(ok=False)
            from ..telemetry.metrics import (
                ETL_DESTINATION_OP_TIMEOUTS_TOTAL, registry)

            registry.counter_inc(ETL_DESTINATION_OP_TIMEOUTS_TOTAL,
                                 labels={"op": "flush"})
            raise EtlError(
                ErrorKind.TIMEOUT,
                f"destination flush exceeded {self._timeout:.1f}s "
                f"(wait_durable never resolved)")
        except asyncio.CancelledError:
            # abandoned flush (worker restart): no verdict — release a
            # half-open trial slot instead of stranding it
            if self._breaker is not None:
                self._breaker.abort_call()
            raise
        except Exception as e:
            self._record(ok=False, available=_is_poison(e))
            raise
        else:
            self._record(ok=True)

    def _record(self, ok: bool, available: bool = False) -> None:
        if self._hb is not None:
            self._hb.beat(progress=("flush", ok), busy=False)
        if self._breaker is None:
            return
        if ok or available:
            # `available`: the sink REFUSED the payload (poison kind) —
            # a definitive 4xx-class response proves the destination is
            # up, so the availability breaker must not count it; the
            # isolation layer (runtime/poison.py) owns that failure
            # class, and tripping the breaker on it would turn one
            # poison row into shedding for every table
            self._breaker.record_success()
        else:
            self._breaker.record_failure()


class SupervisedDestination(Destination):
    """Wraps the configured destination for the pipeline's workers.

    `inner` stays reachable for tests and the maintenance agent; the
    wrapper is intentionally stateless beyond the breaker + heartbeat so
    a restarted pipeline can re-wrap the same inner destination."""

    def __init__(self, inner: Destination, *, timeout_s: float = 60.0,
                 breaker: CircuitBreaker | None = None,
                 heartbeat: Heartbeat | None = None):
        self.inner = inner
        # egress/billing labels must name the REAL sink, not the wrapper
        # (record_egress call sites read this attribute when present)
        self.telemetry_name = getattr(inner, "telemetry_name",
                                      type(inner).__name__)
        self.timeout_s = timeout_s
        self.breaker = breaker
        self.heartbeat = heartbeat
        self._ops = 0

    @staticmethod
    async def _stallable(coro):
        """Chaos: a wedged destination call is a silent hang — injected
        INSIDE the bounded region so the per-op timeout (satellite of the
        watchdog) is what recovers it, not the raise path."""
        try:
            await failpoints.stall_point(failpoints.DESTINATION_WRITE)
        except BaseException:
            coro.close()  # cancelled mid-stall: never awaited otherwise
            raise
        return await coro

    async def _bounded(self, op: str, coro, *, gated: bool = True):
        """Run one destination call: breaker gate → stall site → bounded
        await → breaker/heartbeat accounting."""
        if gated and self.breaker is not None:
            self.breaker.before_call()
        if self.heartbeat is not None:
            self._ops += 1
            self.heartbeat.beat(progress=("op", self._ops), busy=True)
        try:
            if self.timeout_s > 0:
                result = await asyncio.wait_for(self._stallable(coro),
                                                self.timeout_s)
            else:
                result = await self._stallable(coro)
        except asyncio.TimeoutError:
            if gated and self.breaker is not None:
                self.breaker.record_failure()
            if self.heartbeat is not None:
                self.heartbeat.beat(progress=("timeout", self._ops),
                                    busy=False)
            from ..telemetry.metrics import (
                ETL_DESTINATION_OP_TIMEOUTS_TOTAL, registry)

            registry.counter_inc(ETL_DESTINATION_OP_TIMEOUTS_TOTAL,
                                 labels={"op": op})
            raise EtlError(
                ErrorKind.TIMEOUT,
                f"destination {op} exceeded {self.timeout_s:.1f}s")
        except asyncio.CancelledError:
            # no verdict on the sink: a cancelled half-open trial must
            # release its slot or the breaker wedges open forever
            if gated and self.breaker is not None:
                self.breaker.abort_call()
            raise
        except Exception as e:
            # EtlError and any unexpected failure alike count against
            # the sink (an exception with no classification is still a
            # failed call, and must not strand a half-open trial) —
            # EXCEPT poison-kind rejections: a definitive payload
            # refusal proves the sink is up and answering, and counting
            # it would let one poison row (or its bisection probes) trip
            # availability shedding for every table. The isolation layer
            # owns that failure class (runtime/poison.py).
            if gated and self.breaker is not None:
                if _is_poison(e):
                    self.breaker.record_success()
                else:
                    self.breaker.record_failure()
            if self.heartbeat is not None:
                self.heartbeat.beat(progress=("error", self._ops),
                                    busy=False)
            raise
        if self.heartbeat is not None:
            self.heartbeat.beat(progress=("done", self._ops), busy=False)
        if isinstance(result, WriteAck):
            if result.is_durable and gated and self.breaker is not None:
                # durable-on-return acks settle the breaker now; accepted
                # acks settle it when the bounded wait_durable resolves
                self.breaker.record_success()
            return BoundedAck(result, self.timeout_s,
                              self.breaker if gated else None,
                              self.heartbeat)
        if gated and self.breaker is not None:
            self.breaker.record_success()
        return result

    # -- Destination ---------------------------------------------------------

    async def startup(self) -> None:
        # startup is NOT breaker-gated: a restarted pipeline must be able
        # to probe a recovering sink without the old open breaker shedding
        # its first call
        await self._bounded("startup", self.inner.startup(), gated=False)

    async def write_table_rows(self, schema, batch) -> WriteAck:
        return await self._bounded(
            "write_table_rows", self.inner.write_table_rows(schema, batch))

    async def write_events(self, events: Sequence) -> WriteAck:
        return await self._bounded(
            "write_events", self.inner.write_events(events))

    # columnar seam: bounded + breaker-gated like the row entry points
    # (same op labels — the timeout metric and breaker verdicts must not
    # fork per encoding); the INNER destination decides whether it
    # implements the batch write natively or falls back to rows
    async def write_table_batch(self, schema, batch) -> WriteAck:
        return await self._bounded(
            "write_table_rows", self.inner.write_table_batch(schema, batch))

    async def write_event_batches(self, events: Sequence) -> WriteAck:
        return await self._bounded(
            "write_events", self.inner.write_event_batches(events))

    # transactional seam (docs/destinations.md exactly-once contract):
    # committed writes are bounded + breaker-gated under the SAME
    # "write_events" op label as the at-least-once CDC path — the timeout
    # metric and breaker verdicts must not fork per delivery guarantee.
    # The recovery query is NOT breaker-gated: it runs at restart, where
    # an open breaker from the crashed attempt must not shed the one
    # call that would trim the re-stream window (Pipeline.start must
    # never wedge on it; the caller owns retry + degradation).
    def supports_transactional_commit(self) -> bool:
        return self.inner.supports_transactional_commit()

    async def write_event_batches_committed(self, events: Sequence,
                                            commit) -> WriteAck:
        return await self._bounded(
            "write_events",
            self.inner.write_event_batches_committed(events, commit))

    async def recover_high_water(self):
        return await self._bounded(
            "recover_high_water", self.inner.recover_high_water(),
            gated=False)

    async def drop_table(self, table_id, schema=None) -> None:
        await self._bounded("drop_table",
                            self.inner.drop_table(table_id, schema))

    async def truncate_table(self, table_id) -> None:
        await self._bounded("truncate_table",
                            self.inner.truncate_table(table_id))

    async def shutdown(self) -> None:
        # shutdown is never gated or bounded-failed into the breaker —
        # teardown must always reach the inner destination
        if self.timeout_s > 0:
            await asyncio.wait_for(self.inner.shutdown(), self.timeout_s)
        else:
            await self.inner.shutdown()
