"""Pipeline-wide health state machine: healthy → degraded → faulted.

The machine is reason-driven rather than edge-driven: anomaly sources
(the supervisor's stall/hang detections, non-closed circuit breakers,
the forced host-oracle degrade, memory backpressure) `set_reason` while
the condition holds and `clear_reason` when it lifts; the state is
recomputed as

    faulted    — a fatal was recorded (`fault()`): the apply worker
                 exhausted its retries or died with a permanent error.
                 Sticky until `reset()` (a restarted pipeline starts a
                 fresh machine).
    degraded   — at least one active anomaly reason.
    healthy    — no reasons.

`/health` serves this state (503 on faulted); `/health/detail` adds the
live reasons and the transition history. Listeners observe every
transition — the chaos runner uses one to assert a scenario's
healthy → degraded → healthy arc.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Callable


class HealthState(enum.Enum):
    HEALTHY = "healthy"
    DEGRADED = "degraded"
    FAULTED = "faulted"


#: gauge encoding for ETL_PIPELINE_HEALTH_STATE
_STATE_VALUE = {HealthState.HEALTHY: 0, HealthState.DEGRADED: 1,
                HealthState.FAULTED: 2}

_HISTORY_CAP = 64


class HealthStateMachine:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.state = HealthState.HEALTHY
        self.since = time.monotonic()
        self._reasons: dict[str, str] = {}
        self._fatal: str | None = None
        self._listeners: list[Callable[[HealthState, HealthState, str], None]] = []
        self.transitions: list[tuple[str, str, float]] = []  # (state, why, t)

    # -- inputs --------------------------------------------------------------

    def set_reason(self, key: str, detail: str) -> None:
        with self._lock:
            self._reasons[key] = detail
        self._recompute(detail)

    def clear_reason(self, key: str) -> None:
        with self._lock:
            existed = self._reasons.pop(key, None) is not None
        if existed:
            self._recompute(f"cleared: {key}")

    def fault(self, detail: str) -> None:
        with self._lock:
            self._fatal = detail
        self._recompute(detail)

    def reset(self) -> None:
        with self._lock:
            self._fatal = None
            self._reasons.clear()
        self._recompute("reset")

    def add_listener(
            self, cb: Callable[[HealthState, HealthState, str], None]) -> None:
        self._listeners.append(cb)

    # -- state ---------------------------------------------------------------

    def _recompute(self, why: str) -> None:
        with self._lock:
            if self._fatal is not None:
                new = HealthState.FAULTED
            elif self._reasons:
                new = HealthState.DEGRADED
            else:
                new = HealthState.HEALTHY
            old = self.state
            if new is old:
                return
            self.state = new
            self.since = time.monotonic()
            self.transitions.append((new.value, why, self.since))
            del self.transitions[:-_HISTORY_CAP]
            listeners = list(self._listeners)
        from ..telemetry.metrics import ETL_PIPELINE_HEALTH_STATE, registry

        registry.gauge_set(ETL_PIPELINE_HEALTH_STATE, _STATE_VALUE[new])
        for cb in listeners:
            cb(old, new, why)

    @property
    def reasons(self) -> dict[str, str]:
        with self._lock:
            return dict(self._reasons)

    @property
    def fatal(self) -> str | None:
        return self._fatal

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state.value,
                "since_s_ago": round(time.monotonic() - self.since, 3),
                "reasons": dict(self._reasons),
                "fatal": self._fatal,
                "transitions": [
                    {"state": s, "why": w} for s, w, _ in self.transitions],
            }
