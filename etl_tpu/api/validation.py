"""Config validation for sources and destinations.

Reference parity: crates/etl-api/src/validation/ (trait-based validator
framework, mod.rs:1-170, validators/{source,destination,bigquery,
clickhouse,snowflake,iceberg,ducklake}.rs) behind the
`POST /v1/sources:validate` and `POST /v1/destinations:validate` routes
(routes/destinations.rs:468-516, routes/common.rs:67-79).

Two layers, matching the reference split:
  - STATIC shape checks (required fields, types) — run by the CRUD create/
    update routes as reject-before-store, no network;
  - LIVE probes (connect to the source, ping the destination service) —
    run only by the :validate routes, returning `validation_failures`
    with critical/warning severity rather than erroring, so operators
    can inspect everything wrong at once.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

import aiohttp


@dataclass(frozen=True)
class ValidationFailure:
    name: str
    reason: str
    failure_type: str = "critical"  # "critical" | "warning"

    def to_json(self) -> dict:
        return {"name": self.name, "reason": self.reason,
                "failure_type": self.failure_type}


def critical(name: str, reason: str) -> ValidationFailure:
    return ValidationFailure(name, reason, "critical")


def warning(name: str, reason: str) -> ValidationFailure:
    return ValidationFailure(name, reason, "warning")


# -- static shape (reject-before-store) --------------------------------------

_SOURCE_REQUIRED = ("host", "port", "name", "username")

_DESTINATION_REQUIRED: dict[str, tuple[str, ...]] = {
    "bigquery": ("project_id", "dataset_id", "base_url"),
    "clickhouse": ("url", "database"),
    "snowflake": ("base_url", "account", "user", "database"),
    "iceberg": ("catalog_url", "warehouse_path"),
    "lake": ("warehouse_path",),
    "memory": (),
}


def validate_source_shape(config: dict) -> list[ValidationFailure]:
    out = []
    for field in _SOURCE_REQUIRED:
        if not config.get(field):
            out.append(critical(
                f"Missing {field}",
                f"source config requires a non-empty `{field}`"))
    port = config.get("port")
    if port is not None and not (isinstance(port, int)
                                 and 0 < port < 65536):
        out.append(critical("Invalid port",
                            f"`port` must be 1-65535, got {port!r}"))
    return out


def validate_destination_shape(config: dict) -> list[ValidationFailure]:
    dtype = config.get("type")
    if dtype not in _DESTINATION_REQUIRED:
        return [critical(
            "Unknown destination type",
            f"`type` must be one of {sorted(_DESTINATION_REQUIRED)}, "
            f"got {dtype!r}")]
    out = []
    for field in _DESTINATION_REQUIRED[dtype]:
        if not config.get(field):
            out.append(critical(
                f"Missing {field}",
                f"{dtype} destination requires a non-empty `{field}`"))
    return out


# -- live probes (the :validate routes) --------------------------------------


async def validate_source(config: dict,
                          publication: str | None = None,
                          timeout_s: float = 10.0
                          ) -> list[ValidationFailure]:
    """Static shape + a real replication-capable connection: auth, server
    version support (14-18, version.rs), and — when a pipeline config
    names one — publication existence (validators/source.rs stance: best
    effort, no invasive probes)."""
    out = validate_source_shape(config)
    if out:
        return out
    from ..config.pipeline import PgConnectionConfig, TlsConfig
    from ..postgres.client import PgReplicationClient
    from ..postgres.version import POSTGRES_14, POSTGRES_18

    tls = config.get("tls") or {}
    conn_config = PgConnectionConfig(
        host=config["host"], port=int(config["port"]),
        name=config["name"], username=config["username"],
        password=config.get("password"),
        tls=TlsConfig(enabled=bool(tls.get("enabled")),
                      trusted_root_certs=tls.get("trusted_root_certs", "")))
    client = PgReplicationClient(conn_config)
    try:
        await asyncio.wait_for(client.connect(), timeout_s)
    except asyncio.TimeoutError:
        return out + [critical(
            "Source unreachable",
            f"connection to {config['host']}:{config['port']} timed out "
            f"after {timeout_s:.0f}s")]
    except Exception as e:
        return out + [critical("Source connection failed", str(e)[:300])]
    try:
        ver = client.server_version
        if ver < POSTGRES_14:
            out.append(critical(
                "Unsupported Postgres version",
                f"server reports {ver}; ETL supports Postgres 14-18"))
        elif ver >= POSTGRES_18 + 10000:
            out.append(warning(
                "Untested Postgres version",
                f"server reports {ver}, newer than the tested range"))
        if publication is not None:
            if not await client.publication_exists(publication):
                out.append(critical(
                    "Publication missing",
                    f"publication `{publication}` does not exist on the "
                    "source database"))
    except Exception as e:
        out.append(critical("Source probe failed", str(e)[:300]))
    finally:
        await client.close()
    return out


async def _http_probe(url: str, headers: dict | None = None,
                      timeout_s: float = 10.0
                      ) -> "tuple[int, str] | ValidationFailure":
    try:
        timeout = aiohttp.ClientTimeout(total=timeout_s)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            async with session.get(url, headers=headers or {}) as resp:
                return resp.status, (await resp.text())[:200]
    except asyncio.TimeoutError:
        return critical("Destination unreachable",
                        f"request to {url} timed out after {timeout_s:.0f}s")
    except aiohttp.ClientError as e:
        return critical("Destination unreachable", f"{url}: {e}")


async def validate_destination(config: dict,
                               pipeline_config: dict | None = None,
                               timeout_s: float = 10.0
                               ) -> list[ValidationFailure]:
    """Static shape + a cheap authenticated reachability probe per
    destination type (validators/{bigquery,clickhouse,...}.rs: each
    validator authenticates and touches the service before accepting the
    config)."""
    out = validate_destination_shape(config)
    if out:
        return out
    dtype = config["type"]
    if dtype == "bigquery":
        headers = {}
        if config.get("auth_token"):
            headers["Authorization"] = f"Bearer {config['auth_token']}"
        res = await _http_probe(
            f"{config['base_url']}/projects/{config['project_id']}"
            f"/datasets/{config['dataset_id']}", headers, timeout_s)
        if isinstance(res, ValidationFailure):
            out.append(res)
        elif res[0] in (401, 403):
            out.append(critical(
                "BigQuery authentication failed",
                "the service rejected the provided credentials"))
        elif res[0] == 404:
            out.append(warning(
                "BigQuery dataset missing",
                f"dataset `{config['dataset_id']}` does not exist yet; "
                "it will be created at pipeline startup"))
        elif res[0] >= 400:
            out.append(critical("BigQuery probe failed",
                                f"HTTP {res[0]}: {res[1]}"))
    elif dtype == "clickhouse":
        headers = {}
        if config.get("username"):
            headers["X-ClickHouse-User"] = config["username"]
        if config.get("password"):
            headers["X-ClickHouse-Key"] = config["password"]
        res = await _http_probe(
            f"{config['url']}/?query=SELECT%201", headers, timeout_s)
        if isinstance(res, ValidationFailure):
            out.append(res)
        elif res[0] in (401, 403):
            out.append(critical(
                "ClickHouse authentication failed",
                "the server rejected the provided credentials"))
        elif res[0] >= 400:
            out.append(critical("ClickHouse probe failed",
                                f"HTTP {res[0]}: {res[1]}"))
    elif dtype == "snowflake":
        if config.get("private_key_pem"):
            try:
                from ..destinations.snowflake import (SnowflakeConfig,
                                                      make_jwt)

                make_jwt(SnowflakeConfig(
                    base_url=config["base_url"], account=config["account"],
                    user=config["user"], database=config["database"],
                    private_key_pem=config["private_key_pem"]))
            except Exception as e:
                out.append(critical(
                    "Snowflake key invalid",
                    f"could not sign a keypair JWT: {str(e)[:200]}"))
        res = await _http_probe(f"{config['base_url']}/api/v2/statements",
                                timeout_s=timeout_s)
        if isinstance(res, ValidationFailure):
            out.append(res)
    elif dtype == "iceberg":
        res = await _http_probe(f"{config['catalog_url']}/v1/config",
                                timeout_s=timeout_s)
        if isinstance(res, ValidationFailure):
            out.append(res)
        elif res[0] >= 500:
            out.append(critical("Iceberg catalog probe failed",
                                f"HTTP {res[0]}: {res[1]}"))
    elif dtype == "lake":
        import os

        path = config["warehouse_path"]
        parent = path if os.path.isdir(path) else os.path.dirname(path) or "."
        if not os.access(parent, os.W_OK):
            out.append(critical(
                "Lake warehouse not writable",
                f"cannot write to `{path}`"))
    if pipeline_config is not None and not pipeline_config.get(
            "publication_name"):
        out.append(critical(
            "Missing publication_name",
            "pipeline_config requires `publication_name`"))
    return out
