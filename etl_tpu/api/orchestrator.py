"""Replicator orchestration: where pipelines actually run.

Reference parity: the `K8sClient` trait (crates/etl-api/src/k8s/base.rs:197)
with its HTTP implementation (k8s/http.rs, 3.2k LoC) creating per-pipeline
StatefulSets/Secrets/ConfigMaps — and, crucially, the trait seam that makes
multi-node fully testable without a cluster (SURVEY §4.7).

Implementations:
  - K8sOrchestrator: talks to the Kubernetes API over HTTP (fake server in
    tests) creating the same resource triple per pipeline;
  - LocalOrchestrator: runs replicator subprocesses on this host — the
    single-node deployment and the demo path.
"""

from __future__ import annotations

import abc
import asyncio
import json
import signal
import sys
from dataclasses import dataclass
from pathlib import Path

import aiohttp
import yaml

from ..models.errors import ErrorKind, EtlError


@dataclass(frozen=True)
class ReplicatorSpec:
    pipeline_id: int
    tenant_id: str
    config: dict  # full replicator config document (plaintext)
    image: "str | None" = None  # container image override (images CRUD)


@dataclass
class ReplicatorStatus:
    pipeline_id: int
    state: str  # "stopped" | "starting" | "running" | "failed"
    detail: str = ""


class Orchestrator(abc.ABC):
    @abc.abstractmethod
    async def start_pipeline(self, spec: ReplicatorSpec) -> None: ...

    @abc.abstractmethod
    async def stop_pipeline(self, pipeline_id: int) -> None: ...

    @abc.abstractmethod
    async def status(self, pipeline_id: int) -> ReplicatorStatus: ...

    async def restart_pipeline(self, spec: ReplicatorSpec) -> None:
        await self.stop_pipeline(spec.pipeline_id)
        await self.start_pipeline(spec)

    async def shutdown(self) -> None:
        return None


class K8sOrchestrator(Orchestrator):
    """Creates Secret + ConfigMap + StatefulSet per pipeline, mirroring the
    reference resource layout (k8s/http.rs)."""

    def __init__(self, *, api_url: str, namespace: str = "etl",
                 image: str = "etl-tpu-replicator:latest",
                 token: str = ""):
        self.api_url = api_url
        self.namespace = namespace
        self.image = image
        self.token = token
        self._session: aiohttp.ClientSession | None = None

    def _name(self, pipeline_id: int) -> str:
        return f"etl-replicator-{pipeline_id}"

    async def _api(self, method: str, path: str,
                   body: dict | None = None) -> tuple[int, dict]:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        headers = {"Authorization": f"Bearer {self.token}"} if self.token \
            else {}
        data = None
        if body is not None:
            data = json.dumps(body).encode()
            # the k8s API rejects PATCH bodies that aren't declared as a
            # patch type (415); strategic merge matches the partial
            # template documents sent here
            headers["Content-Type"] = \
                "application/strategic-merge-patch+json" \
                if method == "PATCH" else "application/json"
        async with self._session.request(
                method, f"{self.api_url}{path}", data=data,
                headers=headers) as resp:
            text = await resp.text()
            try:
                doc = json.loads(text) if text else {}
            except json.JSONDecodeError:
                doc = {"raw": text}
            return resp.status, doc

    async def start_pipeline(self, spec: ReplicatorSpec) -> None:
        ns = self.namespace
        name = self._name(spec.pipeline_id)
        config_yaml = yaml.safe_dump(spec.config)
        import time

        # fresh restarted-at template annotation on EVERY create-or-update:
        # a config/image change patches the pod template, and the changed
        # annotation makes the StatefulSet controller roll the pods even
        # when nothing else in the template moved (reference
        # k8s/http.rs:1676,1708 restart checksum)
        restarted_at = f"{time.time():.6f}"
        resources = [
            ("POST", f"/api/v1/namespaces/{ns}/secrets", {
                "metadata": {"name": f"{name}-secrets"},
                "stringData": {"config.yaml": config_yaml},
            }),
            ("POST", f"/api/v1/namespaces/{ns}/configmaps", {
                "metadata": {"name": f"{name}-config"},
                "data": {"pipeline_id": str(spec.pipeline_id),
                         "tenant_id": spec.tenant_id},
            }),
            ("POST", f"/apis/apps/v1/namespaces/{ns}/statefulsets", {
                "metadata": {"name": name,
                             "labels": {"app": "etl-replicator",
                                        "pipeline_id": str(spec.pipeline_id),
                                        "tenant_id": spec.tenant_id}},
                "spec": {
                    "serviceName": name, "replicas": 1,
                    "selector": {"matchLabels": {"app": name}},
                    "template": {
                        "metadata": {
                            "labels": {"app": name},
                            "annotations": {
                                "etl/restarted-at": restarted_at}},
                        "spec": {"containers": [{
                            "name": "replicator",
                            "image": spec.image or self.image,
                            "args": ["--config-dir", "/etc/etl"],
                            "volumeMounts": [{"name": "config",
                                              "mountPath": "/etc/etl"}],
                        }], "volumes": [{
                            "name": "config",
                            "secret": {"secretName": f"{name}-secrets"},
                        }]},
                    },
                },
            }),
        ]
        for method, path, body in resources:
            status, _ = await self._api(method, path, body)
            if status == 409:  # exists → strategic-merge PATCH (rollout)
                patch_path = f"{path}/{body['metadata']['name']}"
                status, _ = await self._api("PATCH", patch_path, body)
            if status >= 400:
                raise EtlError(ErrorKind.DESTINATION_FAILED,
                               f"k8s {method} {path} → {status}")

    async def restart_pipeline(self, spec: ReplicatorSpec) -> None:
        """Rolling restart, NOT the base class's delete+recreate: re-apply
        the resource triple — the fresh restarted-at template annotation
        makes the StatefulSet controller roll the pods even when the
        config did not change (`kubectl rollout restart` semantics,
        reference k8s/http.rs:1676,1708)."""
        await self.start_pipeline(spec)

    async def stop_pipeline(self, pipeline_id: int) -> None:
        ns = self.namespace
        name = self._name(pipeline_id)
        for path in (f"/apis/apps/v1/namespaces/{ns}/statefulsets/{name}",
                     f"/api/v1/namespaces/{ns}/secrets/{name}-secrets",
                     f"/api/v1/namespaces/{ns}/configmaps/{name}-config"):
            status, _ = await self._api("DELETE", path)
            if status >= 400 and status != 404:
                raise EtlError(ErrorKind.DESTINATION_FAILED,
                               f"k8s DELETE {path} → {status}")

    async def status(self, pipeline_id: int) -> ReplicatorStatus:
        ns = self.namespace
        name = self._name(pipeline_id)
        status, doc = await self._api(
            "GET", f"/apis/apps/v1/namespaces/{ns}/statefulsets/{name}")
        if status == 404:
            return ReplicatorStatus(pipeline_id, "stopped")
        if status >= 400:
            return ReplicatorStatus(pipeline_id, "failed",
                                    f"k8s status {status}")
        ready = doc.get("status", {}).get("readyReplicas", 0)
        return ReplicatorStatus(pipeline_id,
                                "running" if ready else "starting")

    async def shutdown(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


class LocalOrchestrator(Orchestrator):
    """Runs `python -m etl_tpu.replicator` subprocesses on this host."""

    def __init__(self, work_dir: str):
        self.work_dir = Path(work_dir)
        self._procs: dict[int, asyncio.subprocess.Process] = {}
        self._specs: dict[int, ReplicatorSpec] = {}

    async def start_pipeline(self, spec: ReplicatorSpec) -> None:
        existing = self._procs.get(spec.pipeline_id)
        if existing is not None and existing.returncode is None:
            if self._specs.get(spec.pipeline_id) == spec:
                return  # unchanged: keep the running process
            # config or image changed → restart with the new spec (the
            # single-host analogue of the StatefulSet template roll)
            await self.stop_pipeline(spec.pipeline_id)
        conf_dir = self.work_dir / f"pipeline-{spec.pipeline_id}"
        conf_dir.mkdir(parents=True, exist_ok=True)
        (conf_dir / "base.yaml").write_text(yaml.safe_dump(spec.config))
        # logs go to a file: an unread PIPE would block the replicator once
        # the OS buffer fills (~64KB of log output)
        log = open(conf_dir / "replicator.log", "ab")
        try:
            proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "etl_tpu.replicator",
                "--config-dir", str(conf_dir),
                cwd=str(Path(__file__).resolve().parents[2]),
                stdout=log, stderr=asyncio.subprocess.STDOUT)
        finally:
            log.close()
        self._procs[spec.pipeline_id] = proc
        self._specs[spec.pipeline_id] = spec

    async def stop_pipeline(self, pipeline_id: int) -> None:
        self._specs.pop(pipeline_id, None)
        proc = self._procs.pop(pipeline_id, None)
        if proc is None or proc.returncode is not None:
            return
        proc.send_signal(signal.SIGTERM)
        try:
            await asyncio.wait_for(proc.wait(), timeout=30)
        except asyncio.TimeoutError:
            proc.kill()
            await proc.wait()

    async def status(self, pipeline_id: int) -> ReplicatorStatus:
        proc = self._procs.get(pipeline_id)
        if proc is None:
            return ReplicatorStatus(pipeline_id, "stopped")
        if proc.returncode is None:
            return ReplicatorStatus(pipeline_id, "running")
        return ReplicatorStatus(
            pipeline_id, "failed" if proc.returncode else "stopped",
            f"exit code {proc.returncode}")

    async def shutdown(self) -> None:
        for pid in list(self._procs):
            await self.stop_pipeline(pid)
