"""Replicator orchestration: where pipelines actually run.

Reference parity: the `K8sClient` trait (crates/etl-api/src/k8s/base.rs:197)
with its HTTP implementation (k8s/http.rs, 3.2k LoC) creating per-pipeline
StatefulSets/Secrets/ConfigMaps — and, crucially, the trait seam that makes
multi-node fully testable without a cluster (SURVEY §4.7).

Implementations:
  - K8sOrchestrator: talks to the Kubernetes API over HTTP (fake server in
    tests) creating the same resource triple per pipeline;
  - LocalOrchestrator: runs replicator subprocesses on this host — the
    single-node deployment and the demo path.
"""

from __future__ import annotations

import abc
import asyncio
import json
import signal
import sys
from dataclasses import dataclass
from pathlib import Path

import aiohttp
import yaml

from ..models.errors import ErrorKind, EtlError


@dataclass(frozen=True)
class ReplicatorSpec:
    pipeline_id: int
    tenant_id: str
    config: dict  # full replicator config document (plaintext)
    image: "str | None" = None  # container image override (images CRUD)
    # horizontal scale-out (docs/sharding.md): shard_count > 1 splits the
    # publication across K replica sets — the orchestrator creates ONE
    # StatefulSet per shard, each pod told its slice via `shard` /
    # `shard_count` config keys. `shard` set on a spec pins it to one
    # shard (the per-shard spec the fan-out derives); shard_count 0 =
    # derive from the config document's own shard_count key.
    shard: "int | None" = None
    shard_count: int = 0

    def effective_shard_count(self) -> int:
        if self.shard_count:
            return self.shard_count
        try:
            return max(1, int(self.config.get("shard_count", 1) or 1))
        except (TypeError, ValueError):
            return 1


@dataclass
class ReplicatorStatus:
    pipeline_id: int
    state: str  # "stopped" | "starting" | "running" | "failed"
    detail: str = ""
    # degraded reasons from the pod's live /health probe (supervision
    # health state machine) — a pipeline can be `running` yet degraded;
    # the /fleet endpoint aggregates these across the whole fleet
    reasons: tuple = ()


class Orchestrator(abc.ABC):
    @abc.abstractmethod
    async def start_pipeline(self, spec: ReplicatorSpec) -> None: ...

    @abc.abstractmethod
    async def stop_pipeline(self, pipeline_id: int) -> None: ...

    @abc.abstractmethod
    async def status(self, pipeline_id: int) -> ReplicatorStatus: ...

    async def list_pipelines(self) -> "dict[int, int]":
        """Enumerate every pipeline this orchestrator runs:
        pipeline_id → live shard count. The fleet reconciler's observe
        step and the chaos leak checks both depend on it; orchestrators
        that cannot enumerate cannot join a fleet."""
        raise EtlError(
            ErrorKind.CONFIG_INVALID,
            f"{type(self).__name__} cannot enumerate pipelines — fleet "
            f"reconciliation needs a list-capable orchestrator")

    async def restart_pipeline(self, spec: ReplicatorSpec) -> None:
        await self.stop_pipeline(spec.pipeline_id)
        await self.start_pipeline(spec)

    async def scale_pipeline(self, spec: ReplicatorSpec,
                             shard_count: int) -> None:
        """Roll the deployment onto a new shard count (the autoscale
        controller's actuation seam, etl_tpu/autoscale). Re-applies the
        spec with the new K: start_pipeline's own fan-out/reap semantics
        do the rest — one replica set (or subprocess) per shard, stale
        higher-index shards and rolled-back-to-unsharded fleets reaped,
        pods told their slice via shard/shard_count config keys. Must be
        called AFTER the ShardCoordinator's epoch flip: the store fence
        refuses any stale pod that outlives the roll, so ordering errors
        degrade to refused writes, never double ownership."""
        import dataclasses

        if shard_count < 1:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           f"shard_count must be >= 1, got {shard_count}")
        # strip a stale per-shard pin: the fan-out re-derives each pod's
        # `shard` key; carrying an old one would pin every pod to it
        base_config = {k: v for k, v in spec.config.items() if k != "shard"}
        base_config["shard_count"] = shard_count
        await self.start_pipeline(dataclasses.replace(
            spec, shard=None, shard_count=shard_count, config=base_config))

    async def delete_pipeline(self, pipeline_id: int) -> None:
        """Permanent teardown. Unlike stop (a pause, paired with start),
        delete may destroy pipeline-owned storage."""
        await self.stop_pipeline(pipeline_id)

    async def shutdown(self) -> None:
        return None


# config keys whose values are credentials: they move to the per-pipeline
# Secret and re-enter the replicator through the APP_ env overlay
# (reference k8s/base.rs create_or_update_{postgres,bigquery,clickhouse,
# iceberg,ducklake,snowflake}_secret — one seam per credential type; here
# one Secret whose keys are the env names)
_SECRET_KEYS = frozenset({
    "password", "private_key_pem", "token", "api_key", "catalog_token",
    "s3_access_key_id", "s3_secret_access_key", "service_account_key",
})


def split_secrets(config: dict) -> tuple[dict, dict[str, str]]:
    """(sanitized config, {APP_ env name: secret value}).

    Secret-valued keys are REMOVED from the config document that lands in
    the (world-readable) ConfigMap and injected back at runtime via the
    config loader's `APP_A__B` env overlay, sourced from the Secret."""
    env: dict[str, str] = {}

    def walk(doc: dict, path: tuple[str, ...]) -> dict:
        out = {}
        for k, v in doc.items():
            if isinstance(v, dict):
                out[k] = walk(v, path + (k,))
            elif k in _SECRET_KEYS and isinstance(v, str) and v:
                env["APP_" + "__".join(path + (k,)).upper()] = v
            else:
                out[k] = v
        return out

    return walk(config, ()), env


def derive_pod_status(doc: dict | None) -> str:
    """Kubernetes pod document → operational state (reference
    k8s/base.rs PodStatus: Stopped | Starting | Started | Stopping |
    Failed | Unknown), combining phase, deletion timestamp, and container
    states — readyReplicas alone cannot distinguish CrashLoopBackOff from
    a slow start."""
    if doc is None:
        return "stopped"
    if doc.get("metadata", {}).get("deletionTimestamp"):
        return "stopping"
    status = doc.get("status", {})
    phase = status.get("phase", "")
    for cs in status.get("containerStatuses", []):
        waiting = cs.get("state", {}).get("waiting", {})
        if waiting.get("reason") in ("CrashLoopBackOff", "ErrImagePull",
                                     "ImagePullBackOff"):
            return "failed"
        terminated = cs.get("state", {}).get("terminated", {})
        if terminated and terminated.get("exitCode", 0) != 0:
            return "failed"
    if phase == "Pending":
        return "starting"
    if phase == "Running":
        ready = all(cs.get("ready") for cs in
                    status.get("containerStatuses", [{"ready": False}]))
        return "started" if ready else "starting"
    if phase == "Succeeded":
        return "stopped"
    if phase == "Failed":
        return "failed"
    return "unknown"


class K8sOrchestrator(Orchestrator):
    """Creates Secret + ConfigMap + StatefulSet (and, for lake
    destinations, a maintenance CronJob) per pipeline, mirroring the
    reference resource layout (k8s/http.rs): credentials live in the
    Secret and reach the replicator as APP_ env vars, the sanitized
    config document rides the ConfigMap."""

    def __init__(self, *, api_url: str, namespace: str = "etl",
                 image: str = "etl-tpu-replicator:latest",
                 token: str = "", control_api_url: str = "",
                 control_api_key_secret: str = ""):
        self.api_url = api_url
        self.namespace = namespace
        self.image = image
        self.token = token
        # where maintenance jobs reach the CONTROL-PLANE API (etl-api) for
        # the stop/start pause gate — NOT the replicator pod, which serves
        # only /metrics + /health
        self.control_api_url = control_api_url
        # name of a deployer-managed Secret holding the control-plane
        # bearer token under key "api-key"; injected as ETL_API_KEY
        # (maintenance.py reads it) so secured APIs don't 401 every run
        self.control_api_key_secret = control_api_key_secret
        self._session: aiohttp.ClientSession | None = None

    #: probing bound for shard discovery (stop/delete/status find a
    #: sharded deployment's replica sets by walking `-s0, -s1, …` until
    #: the first 404; a fleet larger than this is not a thing this
    #: orchestrator ever creates)
    MAX_SHARDS = 64

    def _name(self, pipeline_id: int, shard: "int | None" = None) -> str:
        base = f"etl-replicator-{pipeline_id}"
        return base if shard is None else f"{base}-s{shard}"

    async def _api(self, method: str, path: str,
                   body: dict | None = None) -> tuple[int, dict]:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        headers = {"Authorization": f"Bearer {self.token}"} if self.token \
            else {}
        data = None
        if body is not None:
            data = json.dumps(body).encode()
            # the k8s API rejects PATCH bodies that aren't declared as a
            # patch type (415); strategic merge matches the partial
            # template documents sent here
            headers["Content-Type"] = \
                "application/strategic-merge-patch+json" \
                if method == "PATCH" else "application/json"
        async with self._session.request(
                method, f"{self.api_url}{path}", data=data,
                headers=headers) as resp:
            text = await resp.text()
            try:
                doc = json.loads(text) if text else {}
            except json.JSONDecodeError:
                doc = {"raw": text}
            return resp.status, doc

    async def start_pipeline(self, spec: ReplicatorSpec) -> None:
        """Create (or roll) the pipeline's workload. shard_count > 1
        fans out to ONE replica set per shard — each pod's config names
        its `shard`/`shard_count` slice, so the replicator binary scopes
        itself (runtime/pipeline.py); a later start with a different K
        re-applies the new topology (the coordinator's epoch fence
        refuses any stale pod that outlives the roll)."""
        k = spec.effective_shard_count()
        if spec.shard is not None:
            await self._start_one(spec, spec.shard)
            return
        if k <= 1:
            await self._start_one(spec, None)
            # a deployment rolled back from sharded to unsharded must
            # not leave the old per-shard fleet running beside it
            # (discovery AFTER creation: scripted/409 re-apply flows see
            # the same request order as before sharding existed)
            for name in await self._shard_names(spec.pipeline_id):
                await self._stop_one(name)
            return
        import dataclasses

        for shard in range(k):
            shard_spec = dataclasses.replace(
                spec, shard=shard, shard_count=k,
                config=dict(spec.config, shard=shard, shard_count=k))
            await self._start_one(shard_spec, shard)
        # a resharded deployment must not leave the old unsharded
        # replica set — or, on a SHRINK, the higher-index shards — the
        # new fleet won't reuse running beside it (their slots would
        # pin WAL and their writes are only refused, never reaped)
        status, _ = await self._api(
            "DELETE", f"/apis/apps/v1/namespaces/{self.namespace}"
                      f"/statefulsets/{self._name(spec.pipeline_id)}")
        if status >= 400 and status != 404:
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           f"k8s DELETE stale unsharded set → {status}")
        wanted = {self._name(spec.pipeline_id, s) for s in range(k)}
        for name in await self._shard_names(spec.pipeline_id):
            if name not in wanted:
                await self._stop_one(name)

    async def _start_one(self, spec: ReplicatorSpec,
                         shard: "int | None") -> None:
        ns = self.namespace
        name = self._name(spec.pipeline_id, shard)
        sanitized, secret_env = split_secrets(spec.config)
        import time

        # fresh restarted-at template annotation on EVERY create-or-update:
        # a config/image change patches the pod template, and the changed
        # annotation makes the StatefulSet controller roll the pods even
        # when nothing else in the template moved (reference
        # k8s/http.rs:1676,1708 restart checksum)
        restarted_at = f"{time.time():.6f}"
        statefulset = ("POST",
                       f"/apis/apps/v1/namespaces/{ns}/statefulsets", {
            "metadata": {"name": name,
                         "labels": {"app": "etl-replicator",
                                    "pipeline_id": str(spec.pipeline_id),
                                    "tenant_id": spec.tenant_id,
                                    **({"shard": str(shard)}
                                       if shard is not None else {})}},
            "spec": {
                "serviceName": name, "replicas": 1,
                "selector": {"matchLabels": {"app": name}},
                "template": {
                    "metadata": {
                        "labels": {"app": name},
                        "annotations": {
                            "etl/restarted-at": restarted_at}},
                    "spec": {"containers": [{
                        "name": "replicator",
                        "image": spec.image or self.image,
                        "args": ["--config-dir", "/etc/etl"],
                        # credentials re-enter via the APP_ env
                        # overlay, never the config document
                        "envFrom": [{"secretRef": {
                            "name": f"{name}-secrets"}}],
                        "volumeMounts": [{"name": "config",
                                          "mountPath": "/etc/etl"}],
                    }], "volumes": [{
                        "name": "config",
                        "configMap": {"name": f"{name}-config"},
                    }]},
                },
            },
        })
        resources = [
            ("POST", f"/api/v1/namespaces/{ns}/secrets", {
                "metadata": {"name": f"{name}-secrets"},
                "stringData": secret_env,
            }),
            ("POST", f"/api/v1/namespaces/{ns}/configmaps", {
                "metadata": {"name": f"{name}-config"},
                # key MUST be base.yaml: the config loader reads
                # base.yaml/{env}.yaml from --config-dir (load.py), same
                # as LocalOrchestrator writes
                "data": {"base.yaml": yaml.safe_dump(sanitized),
                         "pipeline_id": str(spec.pipeline_id),
                         "tenant_id": spec.tenant_id},
            }),
            statefulset,
        ]
        if spec.config.get("destination", {}).get("type") == "lake":
            # lake pipelines: replicator + maintenance job operate on ONE
            # shared warehouse volume — without it each pod sees its own
            # empty pod-local filesystem and compaction is a no-op
            resources.insert(0, self._warehouse_pvc(spec, name))
            sts_spec = statefulset[2]["spec"]["template"]["spec"]
            sts_spec["volumes"].append({
                "name": "warehouse", "persistentVolumeClaim": {
                    "claimName": f"{name}-warehouse"}})
            sts_spec["containers"][0]["volumeMounts"].append({
                "name": "warehouse",
                "mountPath": self._warehouse_mount(spec)})
            # per-pipeline external-maintenance CronJob (reference
            # k8s/base.rs create_or_update_ducklake_maintenance)
            resources.append(self._maintenance_cronjob(spec, name))
        for method, path, body in resources:
            status, _ = await self._api(method, path, body)
            if status == 409:  # resource exists → update strategy below
                obj_path = f"{path}/{body['metadata']['name']}"
                if "/secrets" in path or "/configmaps" in path:
                    # REPLACE, don't merge: a strategic-merge PATCH keeps
                    # stale keys alive, so a rotated-away credential (or a
                    # pre-upgrade full-config blob) would keep reaching
                    # pods through envFrom forever. PUT replaces the
                    # object atomically — no delete-to-create window in
                    # which a concurrently starting pod would fail
                    # envFrom/volume resolution
                    status, _ = await self._api("PUT", obj_path, body)
                elif "persistentvolumeclaims" in path:
                    # reconcile the size: volume EXPANSION is a legal PVC
                    # update, and silently keeping the old claim would
                    # drop an operator's warehouse_size raise on restart.
                    # 403/422 = shrink or no-expansion storage class —
                    # keep the existing claim rather than fail the start
                    status, _ = await self._api("PATCH", obj_path, {
                        "spec": {"resources": body["spec"]["resources"]}})
                    if status in (403, 422):
                        status = 200
                else:
                    # StatefulSet/CronJob: strategic-merge PATCH rolls the
                    # pod template without recreating the workload
                    status, _ = await self._api("PATCH", obj_path, body)
            if status >= 400:
                raise EtlError(ErrorKind.DESTINATION_FAILED,
                               f"k8s {method} {path} → {status}")

    @staticmethod
    def _warehouse_mount(spec: ReplicatorSpec) -> str:
        # warehouse_path is a DIRECTORY (parquet files + catalog,
        # lake.py:52) — mount the shared volume exactly there
        return spec.config.get("destination", {}).get(
            "warehouse_path", "") or "/var/lib/etl/warehouse"

    def _warehouse_pvc(self, spec: ReplicatorSpec,
                       name: str) -> tuple[str, str, dict]:
        size = spec.config.get("destination", {}).get(
            "warehouse_size", "10Gi")
        return (
            "POST",
            f"/api/v1/namespaces/{self.namespace}/persistentvolumeclaims", {
                "metadata": {"name": f"{name}-warehouse"},
                "spec": {
                    "accessModes": ["ReadWriteOnce"],
                    "resources": {"requests": {"storage": size}},
                },
            })

    def _maintenance_cronjob(self, spec: ReplicatorSpec,
                             name: str) -> tuple[str, str, dict]:
        maint = spec.config.get("maintenance", {})
        schedule = maint.get("schedule", "*/30 * * * *")
        # --warehouse must equal the volume mountPath (including the
        # fallback when warehouse_path is unset) or the job would compact
        # an unmounted pod-local directory
        args = ["--warehouse", self._warehouse_mount(spec),
                "--pipeline-id", str(spec.pipeline_id)]
        if maint.get("coordination"):
            # lease-based coordination rides the SHARED warehouse catalog
            # (the replicator runs the agent side) — no API round-trip
            args.append("--coordinate")
        env = []
        if not maint.get("coordination") and self.control_api_url:
            # uncoordinated pipelines fall back to the stop/start pause
            # gate, which talks to the CONTROL-PLANE API with the
            # pipeline's tenant identity — and its bearer token, when the
            # deployer secured the API (401s would otherwise fail every
            # scheduled run, silently stopping compaction)
            args += ["--api-url", self.control_api_url,
                     "--tenant-id", spec.tenant_id]
            if self.control_api_key_secret:
                env.append({"name": "ETL_API_KEY", "valueFrom": {
                    "secretKeyRef": {"name": self.control_api_key_secret,
                                     "key": "api-key"}}})
        # with neither coordination nor a control-plane URL the job runs
        # ungated — lake catalog writes are transactional, so the risk is
        # churn, not corruption
        return (
            "POST",
            f"/apis/batch/v1/namespaces/{self.namespace}/cronjobs", {
                "metadata": {"name": f"{name}-maintenance",
                             "labels": {"app": "etl-maintenance",
                                        "pipeline_id":
                                            str(spec.pipeline_id)}},
                "spec": {
                    "schedule": schedule,
                    # explicit False: start_pipeline's 409→PATCH path must
                    # UNSUSPEND a CronJob that stop_pipeline suspended
                    "suspend": False,
                    "concurrencyPolicy": "Forbid",
                    "jobTemplate": {"spec": {"template": {"spec": {
                        "restartPolicy": "Never",
                        # the warehouse PVC is ReadWriteOnce: it can only
                        # attach to one node, so pin the job to whatever
                        # node runs the replicator pod
                        "affinity": {"podAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution":
                            [{"labelSelector": {"matchLabels": {
                                "app": name}},
                              "topologyKey": "kubernetes.io/hostname"}]}},
                        "containers": [{
                            "name": "maintenance",
                            "image": spec.image or self.image,
                            # explicit command: the image's entrypoint is
                            # the REPLICATOR; the job must run the
                            # maintenance module regardless
                            "command": ["python", "-m",
                                        "etl_tpu.maintenance"],
                            "args": args,
                            "env": env,
                            "volumeMounts": [{
                                "name": "warehouse",
                                "mountPath": self._warehouse_mount(spec)}],
                        }],
                        "volumes": [{
                            "name": "warehouse",
                            "persistentVolumeClaim": {
                                "claimName": f"{name}-warehouse"}}],
                    }}}},
                },
            })

    async def restart_pipeline(self, spec: ReplicatorSpec) -> None:
        """Rolling restart, NOT the base class's delete+recreate: re-apply
        the resource triple — the fresh restarted-at template annotation
        makes the StatefulSet controller roll the pods even when the
        config did not change (`kubectl rollout restart` semantics,
        reference k8s/http.rs:1676,1708)."""
        await self.start_pipeline(spec)

    async def _shard_names(self, pipeline_id: int) -> "list[str]":
        """Discover a deployment's per-shard replica-set names by walking
        `-s0, -s1, …` until the first absent StatefulSet — stop/delete/
        status need the live topology without being told K (the caller
        may not know it, e.g. after a rebalance changed it)."""
        ns = self.namespace
        # preferred: ONE labelSelector list — gap-proof (a half-finished
        # teardown that already removed -s0 must not hide -s1/-s2)
        status, doc = await self._api(
            "GET", f"/apis/apps/v1/namespaces/{ns}/statefulsets"
                   f"?labelSelector=pipeline_id%3D{pipeline_id}")
        if status < 400 and isinstance(doc, dict) \
                and isinstance(doc.get("items"), list):
            base = self._name(pipeline_id)
            names = []
            for item in doc["items"]:
                name = item.get("metadata", {}).get("name", "")
                if name.startswith(f"{base}-s") \
                        and name[len(base) + 2:].isdigit():
                    names.append(name)
            return sorted(names,
                          key=lambda n: int(n.rsplit("-s", 1)[1]))
        # fallback (API servers/stubs without list support): walk the
        # deterministic names until the first absent set
        names = []
        for shard in range(self.MAX_SHARDS):
            name = self._name(pipeline_id, shard)
            status, doc = await self._api(
                "GET", f"/apis/apps/v1/namespaces/{ns}/statefulsets/{name}")
            if status == 404:
                break
            if status >= 400:
                raise EtlError(ErrorKind.DESTINATION_FAILED,
                               f"k8s GET statefulset {name} → {status}")
            if not isinstance(doc, dict) \
                    or not ({"metadata", "spec"} & set(doc)):
                # a real StatefulSet document always carries metadata —
                # an empty 200 is a permissive stub/proxy, not a replica
                # set; treat it as absent rather than fabricating shards
                break
            names.append(name)
        return names

    async def _stop_one(self, name: str) -> None:
        ns = self.namespace
        for path in (f"/apis/apps/v1/namespaces/{ns}/statefulsets/{name}",
                     f"/api/v1/namespaces/{ns}/secrets/{name}-secrets",
                     f"/api/v1/namespaces/{ns}/configmaps/{name}-config"):
            status, _ = await self._api("DELETE", path)
            if status >= 400 and status != 404:
                raise EtlError(ErrorKind.DESTINATION_FAILED,
                               f"k8s DELETE {path} → {status}")
        # SUSPEND (not delete) the maintenance CronJob: a scheduled run
        # against a paused pipeline would otherwise auto-restart it via
        # the pause gate's finally-/start; start_pipeline's re-apply sets
        # suspend back to False. 404 = non-lake pipeline, fine.
        status, _ = await self._api(
            "PATCH",
            f"/apis/batch/v1/namespaces/{ns}/cronjobs/{name}-maintenance",
            {"spec": {"suspend": True}})
        if status >= 400 and status != 404:
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           f"k8s suspend cronjob {name} → {status}")

    async def stop_pipeline(self, pipeline_id: int) -> None:
        """Pause: remove the workload resources but KEEP the warehouse
        PVC and the maintenance CronJob. Stop is paired with start: the
        lake data must survive the pause (run_maintenance itself stops
        the pipeline before compacting the very warehouse that volume
        holds), and deleting the CronJob here would cascade-GC its OWN
        running Job mid-compaction — the pause gate calls /stop, and in
        real Kubernetes the Job's ownerReference makes the delete
        garbage-collect the pod that issued it. Sharded deployments stop
        EVERY shard's replica set (discovered, not assumed)."""
        shard_names = await self._shard_names(pipeline_id)
        await self._stop_one(self._name(pipeline_id))
        for name in shard_names:
            await self._stop_one(name)

    async def _delete_owned(self, name: str) -> None:
        ns = self.namespace
        for path in (f"/apis/batch/v1/namespaces/{ns}/cronjobs/"
                     f"{name}-maintenance",
                     f"/api/v1/namespaces/{ns}/persistentvolumeclaims/"
                     f"{name}-warehouse"):
            status, _ = await self._api("DELETE", path)
            if status >= 400 and status != 404:
                raise EtlError(ErrorKind.DESTINATION_FAILED,
                               f"k8s DELETE {path} → {status}")

    async def delete_pipeline(self, pipeline_id: int) -> None:
        """Permanent teardown: stop, then drop the maintenance CronJob
        and the warehouse PVC — an orphaned claim would be silently
        re-adopted by a future pipeline with the same id, running it
        against stale warehouse data (old catalog, old replay epochs).
        Sharded deployments tear down every shard's owned resources."""
        shard_names = await self._shard_names(pipeline_id)
        await self.stop_pipeline(pipeline_id)
        await self._delete_owned(self._name(pipeline_id))
        for name in shard_names:
            await self._delete_owned(name)

    async def list_pipelines(self) -> "dict[int, int]":
        """Enumerate the fleet from the StatefulSet inventory: one
        labelSelector list over `app=etl-replicator`, grouped by the
        `pipeline_id` label — shard count is the number of `-sN` replica
        sets (or 1 for an unsharded deployment)."""
        ns = self.namespace
        status, doc = await self._api(
            "GET", f"/apis/apps/v1/namespaces/{ns}/statefulsets"
                   f"?labelSelector=app%3Detl-replicator")
        if status >= 400 or not isinstance(doc, dict) \
                or not isinstance(doc.get("items"), list):
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           f"k8s LIST statefulsets → {status}")
        fleet: "dict[int, int]" = {}
        sharded: "dict[int, set]" = {}
        for item in doc["items"]:
            meta = item.get("metadata", {})
            labels = meta.get("labels", {})
            try:
                pid = int(labels.get("pipeline_id", ""))
            except ValueError:
                continue
            name = meta.get("name", "")
            base = self._name(pid)
            if name.startswith(f"{base}-s") \
                    and name[len(base) + 2:].isdigit():
                sharded.setdefault(pid, set()).add(name)
            elif name == base:
                fleet.setdefault(pid, 1)
        for pid, names in sharded.items():
            # a sharded deployment's per-shard sets win over a stale
            # unsharded one caught mid-roll
            fleet[pid] = len(names)
        return fleet

    async def probe_pod_health(self, pipeline_id: int,
                               app_name: "str | None" = None
                               ) -> "dict | None":
        """GET the replicator pod's live /health JSON through the API
        server's pod proxy (the in-cluster observability app,
        replicator.py build_observability_app). Returns the body dict —
        `{"status": "ok"|"degraded"|"faulted"|..., "reasons": {...}}` —
        or None when there is no pod / no proxy / no parseable body;
        callers treat None as "no evidence", never as failure."""
        ns = self.namespace
        name = app_name or self._name(pipeline_id)
        status, doc = await self._api(
            "GET", f"/api/v1/namespaces/{ns}/pods"
                   f"?labelSelector=app%3D{name}")
        if status >= 400:
            return None
        items = doc.get("items", []) if isinstance(doc, dict) else []
        pod_name = (items[0].get("metadata", {}).get("name", "")
                    if items else "")
        if not pod_name:
            return None
        status, body = await self._api(
            "GET", f"/api/v1/namespaces/{ns}/pods/{pod_name}"
                   f"/proxy/health")
        # 503 is a MEANINGFUL health answer (faulted/starting pods serve
        # it with a JSON body); only a transport-level miss is None
        if status == 404 or not isinstance(body, dict) \
                or "status" not in body:
            return None
        return body

    async def pod_status(self, pipeline_id: int,
                         app_name: "str | None" = None) -> str:
        """Pod-level state (reference get_replicator_pod_status): derives
        stopped/starting/started/stopping/failed/unknown from the pod
        document rather than StatefulSet replica counts. `app_name`
        selects one shard's replica set in a sharded deployment."""
        ns = self.namespace
        name = app_name or self._name(pipeline_id)
        status, doc = await self._api(
            "GET", f"/api/v1/namespaces/{ns}/pods"
                   f"?labelSelector=app%3D{name}")
        if status == 404:
            return "stopped"
        if status >= 400:
            return "unknown"
        items = doc.get("items", [])
        return derive_pod_status(items[0] if items else None)

    async def _status_one(self, pipeline_id: int,
                          name: str) -> ReplicatorStatus:
        ns = self.namespace
        status, doc = await self._api(
            "GET", f"/apis/apps/v1/namespaces/{ns}/statefulsets/{name}")
        if status == 404:
            return ReplicatorStatus(pipeline_id, "stopped")
        if status >= 400:
            return ReplicatorStatus(pipeline_id, "failed",
                                    f"k8s status {status}")
        pod = await self.pod_status(pipeline_id, app_name=name)
        if pod == "failed":
            return ReplicatorStatus(pipeline_id, "failed",
                                    "pod failed (see pod status)")
        ready = doc.get("status", {}).get("readyReplicas", 0)
        if not ready:
            return ReplicatorStatus(pipeline_id, "starting")
        # the pod is ready at the Kubernetes level — now ask the
        # REPLICATOR what it thinks: the live /health probe surfaces the
        # supervision health state readiness cannot see (a pod can be
        # Ready while its apply loop is faulted behind a dead heartbeat)
        health = await self.probe_pod_health(pipeline_id, app_name=name)
        if health is not None:
            h = str(health.get("status", ""))
            if h == "faulted":
                return ReplicatorStatus(
                    pipeline_id, "failed",
                    f"pod /health faulted: {health.get('fatal', '')}")
            if h == "degraded":
                reasons = health.get("reasons") or {}
                if isinstance(reasons, dict):
                    flat = tuple(f"{k}: {v}" for k, v in
                                 sorted(reasons.items()))
                else:
                    flat = (str(reasons),)
                return ReplicatorStatus(
                    pipeline_id, "running",
                    "degraded: " + "; ".join(flat), reasons=flat)
        return ReplicatorStatus(pipeline_id, "running")

    async def status(self, pipeline_id: int) -> ReplicatorStatus:
        """Aggregate over the deployment's replica sets: a sharded
        pipeline is `running` only when EVERY shard is; any failed shard
        fails the whole, any starting shard keeps it starting — one
        hidden dead shard must never read as healthy."""
        shard_names = await self._shard_names(pipeline_id)
        if not shard_names:
            return await self._status_one(pipeline_id,
                                          self._name(pipeline_id))
        states = []
        details = []
        reasons: list = []
        for i, name in enumerate(shard_names):
            st = await self._status_one(pipeline_id, name)
            states.append(st.state)
            details.append(f"s{i}={st.state}"
                           + (f" ({st.detail})" if st.detail else ""))
            reasons.extend(f"s{i} {r}" for r in st.reasons)
        detail = ", ".join(details)
        if any(s == "failed" for s in states):
            return ReplicatorStatus(pipeline_id, "failed", detail,
                                    reasons=tuple(reasons))
        if any(s in ("starting", "stopped") for s in states):
            return ReplicatorStatus(pipeline_id, "starting", detail,
                                    reasons=tuple(reasons))
        return ReplicatorStatus(pipeline_id, "running", detail,
                                reasons=tuple(reasons))

    async def shutdown(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


class LocalOrchestrator(Orchestrator):
    """Runs `python -m etl_tpu.replicator` subprocesses on this host.

    Sharded deployments (`shard_count` > 1 in the spec/config) run ONE
    subprocess per shard — keyed `(pipeline_id, shard)`; unsharded
    pipelines keep their plain `pipeline_id` key (and the existing
    restart-on-spec-change semantics)."""

    def __init__(self, work_dir: str):
        self.work_dir = Path(work_dir)
        # key: pipeline_id (unsharded) | (pipeline_id, shard) (sharded)
        self._procs: dict = {}
        self._specs: dict = {}

    def _keys_for(self, pipeline_id: int) -> list:
        return [k for k in self._procs
                if k == pipeline_id
                or (isinstance(k, tuple) and k[0] == pipeline_id)]

    async def start_pipeline(self, spec: ReplicatorSpec) -> None:
        k = spec.effective_shard_count()
        if spec.shard is None and k > 1:
            import dataclasses

            # a topology change (unsharded→K or K→K') stops whatever is
            # running under keys the new fleet won't reuse
            wanted = {(spec.pipeline_id, s) for s in range(k)}
            for key in self._keys_for(spec.pipeline_id):
                if key not in wanted:
                    await self._stop_key(key)
            for shard in range(k):
                await self.start_pipeline(dataclasses.replace(
                    spec, shard=shard, shard_count=k,
                    config=dict(spec.config, shard=shard, shard_count=k)))
            return
        key = spec.pipeline_id if spec.shard is None \
            else (spec.pipeline_id, spec.shard)
        existing = self._procs.get(key)
        if existing is not None and existing.returncode is None:
            if self._specs.get(key) == spec:
                return  # unchanged: keep the running process
            # config or image changed → restart with the new spec (the
            # single-host analogue of the StatefulSet template roll)
            await self._stop_key(key)
        suffix = "" if spec.shard is None else f"-s{spec.shard}"
        conf_dir = self.work_dir / f"pipeline-{spec.pipeline_id}{suffix}"
        conf_dir.mkdir(parents=True, exist_ok=True)
        (conf_dir / "base.yaml").write_text(yaml.safe_dump(spec.config))
        # logs go to a file: an unread PIPE would block the replicator once
        # the OS buffer fills (~64KB of log output)
        log = open(conf_dir / "replicator.log", "ab")
        try:
            proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "etl_tpu.replicator",
                "--config-dir", str(conf_dir),
                cwd=str(Path(__file__).resolve().parents[2]),
                stdout=log, stderr=asyncio.subprocess.STDOUT)
        finally:
            log.close()
        self._procs[key] = proc
        self._specs[key] = spec

    async def _stop_key(self, key) -> None:
        self._specs.pop(key, None)
        proc = self._procs.pop(key, None)
        if proc is None or proc.returncode is not None:
            return
        proc.send_signal(signal.SIGTERM)
        try:
            await asyncio.wait_for(proc.wait(), timeout=30)
        except asyncio.TimeoutError:
            proc.kill()
            await proc.wait()

    async def stop_pipeline(self, pipeline_id: int) -> None:
        for key in self._keys_for(pipeline_id):
            await self._stop_key(key)

    async def list_pipelines(self) -> "dict[int, int]":
        """Enumerate from the process table: shard count is the number
        of `(pipeline_id, shard)` keys (1 for an unsharded scalar key).
        Exited processes still count — presence is registration, health
        is `status()`'s job; the fleet reconciler must not re-create a
        pipeline just because its process crashed between ticks."""
        fleet: "dict[int, int]" = {}
        for key in self._procs:
            pid = key[0] if isinstance(key, tuple) else key
            fleet[pid] = fleet.get(pid, 0) + 1
        return fleet

    async def status(self, pipeline_id: int) -> ReplicatorStatus:
        keys = self._keys_for(pipeline_id)
        if not keys:
            return ReplicatorStatus(pipeline_id, "stopped")
        states = []
        details = []
        for key in sorted(keys, key=str):
            proc = self._procs[key]
            if proc.returncode is None:
                states.append("running")
            else:
                states.append("failed" if proc.returncode else "stopped")
                details.append(f"{key}: exit code {proc.returncode}")
        if any(s == "failed" for s in states):
            return ReplicatorStatus(pipeline_id, "failed",
                                    "; ".join(details))
        if all(s == "running" for s in states):
            return ReplicatorStatus(pipeline_id, "running")
        if all(s == "stopped" for s in states):
            return ReplicatorStatus(pipeline_id, "stopped",
                                    "; ".join(details))
        # mixed running/exited shard fleet: part of the publication is
        # still replicating — never report 'stopped' over a live process
        # (the K8s aggregate's stance: one incomplete shard degrades the
        # whole to 'starting')
        return ReplicatorStatus(pipeline_id, "starting",
                                "; ".join(details))

    async def shutdown(self) -> None:
        for key in list(self._procs):
            await self._stop_key(key)
