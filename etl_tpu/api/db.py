"""Control-plane storage seam: the API's own tables on sqlite OR
Postgres.

Reference parity: crates/etl-api owns a Postgres database with sqlx
migrations (crates/etl-api/migrations/) — the control plane's tenants/
sources/destinations/images/pipelines live in their own database, not
the data plane's `etl` store schema. Here the same statement set runs
on either backend (mirroring store/sql.py's one-statement-set stance):

  - `SqliteApiDb`: file-backed sqlite3, `?` placeholders;
  - `PostgresApiDb`: the SAME statements over the from-scratch wire
    client pool (extended protocol, server-side binding) against the
    connection's default schema — point it at the PostgresStore's
    database or a dedicated control-plane database.

Inserts use `RETURNING id` so both backends report new row ids without
driver-specific lastrowid. Uniqueness violations raise
ApiIntegrityError on both backends so routes can 409 uniformly.
"""

from __future__ import annotations

import abc
import asyncio
import sqlite3
from pathlib import Path

from ..models.errors import EtlError

API_TABLE_NAMES = ("api_tenants", "api_sources", "api_destinations",
                   "api_images", "api_pipelines")

API_MIGRATIONS: list[tuple[str, str]] = [
    ("20250901000000_api_base", """
CREATE TABLE IF NOT EXISTS api_tenants (
    id TEXT PRIMARY KEY, name TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS api_sources (
    id {bigserial} PRIMARY KEY, tenant_id TEXT NOT NULL,
    name TEXT NOT NULL, config_enc TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS api_destinations (
    id {bigserial} PRIMARY KEY, tenant_id TEXT NOT NULL,
    name TEXT NOT NULL, config_enc TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS api_images (
    id {bigserial} PRIMARY KEY, tenant_id TEXT NOT NULL,
    name TEXT NOT NULL, is_default INTEGER NOT NULL DEFAULT 0,
    UNIQUE (tenant_id, name));
CREATE TABLE IF NOT EXISTS api_pipelines (
    id {bigserial} PRIMARY KEY, tenant_id TEXT NOT NULL,
    source_id BIGINT NOT NULL, destination_id BIGINT NOT NULL,
    publication_name TEXT NOT NULL,
    config_json TEXT NOT NULL DEFAULT '{{}}',
    store_path TEXT NOT NULL DEFAULT '');
"""),
    ("20260729000000_pipeline_image", """
ALTER TABLE api_pipelines ADD COLUMN image_name TEXT NOT NULL DEFAULT ''
"""),
]


class ApiIntegrityError(Exception):
    """Uniqueness/constraint violation, backend-uniform."""


class ApiDbUnavailable(Exception):
    """The storage backend is closed/unreachable — surfaces as a 5xx,
    NEVER as a 409 (a 'tenant exists' answer to a downed database would
    mislead the client)."""


def _is_integrity_message(msg: str) -> bool:
    m = msg.lower()
    return ("unique constraint" in m or "duplicate key" in m
            or "integrityerror" in m)


class ApiDb(abc.ABC):
    """One `run()` seam; statements use `?` placeholders."""

    @abc.abstractmethod
    async def connect(self) -> None: ...

    @abc.abstractmethod
    async def close(self) -> None: ...

    @abc.abstractmethod
    async def run(self, sql: str, params: tuple = ()) -> list[tuple]: ...

    async def _migrate(self) -> None:
        for _name, ddl in API_MIGRATIONS:
            for stmt in ddl.format(bigserial=self.bigserial).split(";"):
                if stmt.strip():
                    try:
                        await self.run(stmt)
                    except Exception as e:
                        # idempotent ALTER: the column already existing
                        # is the only acceptable failure. sqlite says
                        # 'duplicate column name'; Postgres says
                        # 'column ... already exists'
                        m = str(e).lower()
                        if "duplicate column" not in m \
                                and "already exists" not in m:
                            raise


#: sqlite grew RETURNING in 3.35; older runtimes (debian bullseye ships
#: 3.34) get the clause stripped and the id synthesized from lastrowid
_SQLITE_HAS_RETURNING = sqlite3.sqlite_version_info >= (3, 35, 0)
_RETURNING_ID = " returning id"


class SqliteApiDb(ApiDb):
    bigserial = "INTEGER"

    def __init__(self, path: str | Path):
        self.path = str(path)
        self._db: sqlite3.Connection | None = None

    async def connect(self) -> None:
        self._db = sqlite3.connect(self.path)
        await self._migrate()

    async def close(self) -> None:
        if self._db is not None:
            self._db.close()
            self._db = None

    async def run(self, sql: str, params: tuple = ()) -> list[tuple]:
        assert self._db is not None, "api db not connected"
        emulate_returning = (not _SQLITE_HAS_RETURNING
                             and sql.rstrip().lower()
                                 .endswith(_RETURNING_ID))
        if emulate_returning:
            sql = sql.rstrip()[:-len(_RETURNING_ID)]
        try:
            cur = self._db.execute(sql, params)
            if emulate_returning:
                rows = [(cur.lastrowid,)]
            else:
                rows = cur.fetchall() if cur.description is not None \
                    else []
            self._db.commit()
        except sqlite3.IntegrityError as e:
            self._db.rollback()
            raise ApiIntegrityError(str(e)) from e
        return rows


class PostgresApiDb(ApiDb):
    """The same statements over the wire-client pool (reference: the
    API's sqlx PgPool). Tables live flat in the connection's default
    schema — the control plane owns its database the way the reference
    API owns its own Postgres."""

    bigserial = "BIGINT GENERATED BY DEFAULT AS IDENTITY"

    def __init__(self, connection_config, pool_size: int = 2):
        self._config = connection_config
        self.pool_size = max(1, pool_size)
        self._free: "asyncio.Queue | None" = None
        self._connected = False

    def _new_conn(self):
        from ..postgres.client import wire_connection_from_config

        return wire_connection_from_config(self._config,
                                           application_name="etl_tpu_api")

    async def connect(self) -> None:
        first = self._new_conn()
        await first.connect()
        self._free = asyncio.Queue()
        self._free.put_nowait(first)
        for _ in range(self.pool_size - 1):
            self._free.put_nowait(None)  # lazy connect on first acquire
        self._connected = True
        await self._migrate()

    async def close(self) -> None:
        self._connected = False
        if self._free is None:
            return
        while not self._free.empty():
            conn = self._free.get_nowait()
            if conn is not None:
                try:
                    await conn.close()
                except Exception:
                    pass
        # keep the queue so in-flight run()s can hand their connection
        # back (they see _connected False and close it, not re-pool it)

    async def run(self, sql: str, params: tuple = ()) -> list[tuple]:
        from ..store.sql import to_dollar_params

        if not self._connected or self._free is None:
            raise ApiDbUnavailable("api db not connected")
        conn = await self._free.get()
        if conn is None:
            try:
                conn = self._new_conn()
                await conn.connect()
            except BaseException:
                self._free.put_nowait(None)  # slot stays reconnectable
                raise
        broken = False
        try:
            if params:
                texts = [None if v is None else str(v) for v in params]
                result = await conn.query_params(
                    to_dollar_params(sql, len(params)), texts)
            else:
                result = await conn.query(sql)
            return [tuple(r) for r in result.rows]
        except EtlError as e:
            # PG error responses leave the connection at ReadyForQuery
            # (reusable); anything else poisons the wire framing
            if _is_integrity_message(str(e)):
                raise ApiIntegrityError(str(e)) from e
            raise
        except BaseException:
            # includes CancelledError: a query abandoned mid-response
            # leaves unread frames — the NEXT query would read the stale
            # ReadyForQuery and take the old query's rows
            broken = True
            raise
        finally:
            if broken or not self._connected:
                if conn is not None:
                    try:
                        await conn.close()
                    except BaseException:  # etl-lint: ignore[cancellation-swallow]
                        # a cancelled task raises at the next await —
                        # the socket still gets GC'd; the slot MUST go
                        # back regardless
                        pass
                conn = None
            if self._free is not None:
                self._free.put_nowait(conn)
