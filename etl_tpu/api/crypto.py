"""Config encryption at rest.

Reference parity: etl-api encrypted source/destination configs
(crates/etl-api/src/configs/encryption.rs) — AES-256-GCM with random
nonces, key from configuration, plus key-id tagging so keys can rotate
(the reference ships an encryption-key rotation xtask)."""

from __future__ import annotations

import base64
import json
import os
from dataclasses import dataclass

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

from ..models.errors import ErrorKind, EtlError


@dataclass(frozen=True)
class EncryptionKey:
    key_id: int
    key: bytes  # 32 bytes

    @classmethod
    def generate(cls, key_id: int = 0) -> "EncryptionKey":
        return cls(key_id, AESGCM.generate_key(256))

    @classmethod
    def from_base64(cls, key_id: int, b64: str) -> "EncryptionKey":
        raw = base64.b64decode(b64)
        if len(raw) != 32:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           "encryption key must be 32 bytes")
        return cls(key_id, raw)


class ConfigCipher:
    """Encrypt/decrypt JSON config documents; supports multiple keys for
    rotation (encrypt with the primary, decrypt with any known key)."""

    def __init__(self, primary: EncryptionKey,
                 others: list[EncryptionKey] | None = None):
        self._keys = {primary.key_id: primary}
        for k in others or []:
            self._keys[k.key_id] = k
        self._primary = primary

    def encrypt(self, doc: dict) -> str:
        nonce = os.urandom(12)
        ct = AESGCM(self._primary.key).encrypt(
            nonce, json.dumps(doc).encode(), None)
        envelope = {
            "key_id": self._primary.key_id,
            "nonce": base64.b64encode(nonce).decode(),
            "ciphertext": base64.b64encode(ct).decode(),
        }
        return json.dumps(envelope)

    def decrypt(self, raw: str) -> dict:
        try:
            env = json.loads(raw)
            key = self._keys.get(env["key_id"])
            if key is None:
                raise EtlError(ErrorKind.CONFIG_INVALID,
                               f"unknown encryption key id {env['key_id']}")
            pt = AESGCM(key.key).decrypt(
                base64.b64decode(env["nonce"]),
                base64.b64decode(env["ciphertext"]), None)
            return json.loads(pt)
        except EtlError:
            raise
        except Exception as e:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           f"config decryption failed: {type(e).__name__}")

    def rotate(self, raw: str) -> str:
        """Re-encrypt an envelope under the primary key (xtask parity)."""
        return self.encrypt(self.decrypt(raw))
