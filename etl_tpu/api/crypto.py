"""Config encryption at rest.

Reference parity: etl-api encrypted source/destination configs
(crates/etl-api/src/configs/encryption.rs) — AES-256-GCM with random
nonces, key from configuration, plus key-id tagging so keys can rotate
(the reference ships an encryption-key rotation xtask).

When the `cryptography` package is not installed (minimal CI images),
the cipher degrades to a pure-stdlib authenticated scheme with the SAME
interface and envelope shape: SHA-256 counter-mode keystream +
truncated HMAC-SHA-256 tag (encrypt-then-MAC, constant-time compare).
Envelopes are self-consistent within one backend — a deployment must
not mix backends over the same database, so which backend is live is
exported as `CIPHER_BACKEND` and logged by the API at startup."""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
from dataclasses import dataclass

from ..models.errors import ErrorKind, EtlError

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    CIPHER_BACKEND = "aes-256-gcm"
except ImportError:  # minimal image: stdlib fallback, same interface
    class AESGCM:  # type: ignore[no-redef]
        """Drop-in stand-in for cryptography's AESGCM: SHA-256-CTR
        keystream XOR + 16-byte HMAC-SHA-256 tag appended to the
        ciphertext (the same ct||tag layout AES-GCM emits), so the
        envelope format and every call site stay identical."""

        _TAG_LEN = 16

        def __init__(self, key: bytes):
            if len(key) != 32:
                raise ValueError("key must be 32 bytes")
            self._key = key

        @staticmethod
        def generate_key(bit_length: int) -> bytes:
            if bit_length != 256:
                raise ValueError("only 256-bit keys are supported")
            return os.urandom(32)

        def _keystream(self, nonce: bytes, n: int) -> bytes:
            out = bytearray()
            counter = 0
            while len(out) < n:
                out += hashlib.sha256(
                    b"etl-ks|" + self._key + b"|" + nonce + b"|"
                    + counter.to_bytes(8, "big")).digest()
                counter += 1
            return bytes(out[:n])

        def _tag(self, nonce: bytes, ct: bytes,
                 aad: "bytes | None") -> bytes:
            return hmac.new(
                self._key,
                b"etl-tag|" + nonce + b"|" + (aad or b"") + b"|" + ct,
                hashlib.sha256).digest()[:self._TAG_LEN]

        def encrypt(self, nonce: bytes, data: bytes,
                    aad: "bytes | None") -> bytes:
            ct = bytes(a ^ b for a, b in
                       zip(data, self._keystream(nonce, len(data))))
            return ct + self._tag(nonce, ct, aad)

        def decrypt(self, nonce: bytes, data: bytes,
                    aad: "bytes | None") -> bytes:
            if len(data) < self._TAG_LEN:
                raise ValueError("ciphertext too short")
            ct, tag = data[:-self._TAG_LEN], data[-self._TAG_LEN:]
            if not hmac.compare_digest(tag, self._tag(nonce, ct, aad)):
                raise ValueError("authentication tag mismatch")
            return bytes(a ^ b for a, b in
                         zip(ct, self._keystream(nonce, len(ct))))

    CIPHER_BACKEND = "stdlib-hmac-ctr"

    import logging

    # loud by design: a production image missing the `cryptography`
    # wheel silently changing the at-rest cipher would be a security
    # posture change nobody asked for — and envelopes written by the
    # two backends are mutually undecryptable, so adding the wheel
    # later strands every stored config. CI/test images are the
    # intended audience of this fallback.
    logging.getLogger("etl_tpu.api.crypto").warning(
        "cryptography not installed: config encryption degraded to the "
        "stdlib HMAC-CTR fallback (CIPHER_BACKEND=%s); envelopes are "
        "NOT interchangeable with the AES-256-GCM backend — install "
        "`cryptography` for production deployments", CIPHER_BACKEND)


@dataclass(frozen=True)
class EncryptionKey:
    key_id: int
    key: bytes  # 32 bytes

    @classmethod
    def generate(cls, key_id: int = 0) -> "EncryptionKey":
        return cls(key_id, AESGCM.generate_key(256))

    @classmethod
    def from_base64(cls, key_id: int, b64: str) -> "EncryptionKey":
        raw = base64.b64decode(b64)
        if len(raw) != 32:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           "encryption key must be 32 bytes")
        return cls(key_id, raw)


class ConfigCipher:
    """Encrypt/decrypt JSON config documents; supports multiple keys for
    rotation (encrypt with the primary, decrypt with any known key)."""

    def __init__(self, primary: EncryptionKey,
                 others: list[EncryptionKey] | None = None):
        self._keys = {primary.key_id: primary}
        for k in others or []:
            self._keys[k.key_id] = k
        self._primary = primary

    def encrypt(self, doc: dict) -> str:
        nonce = os.urandom(12)
        ct = AESGCM(self._primary.key).encrypt(
            nonce, json.dumps(doc).encode(), None)
        envelope = {
            "key_id": self._primary.key_id,
            "nonce": base64.b64encode(nonce).decode(),
            "ciphertext": base64.b64encode(ct).decode(),
        }
        return json.dumps(envelope)

    def decrypt(self, raw: str) -> dict:
        try:
            env = json.loads(raw)
            key = self._keys.get(env["key_id"])
            if key is None:
                raise EtlError(ErrorKind.CONFIG_INVALID,
                               f"unknown encryption key id {env['key_id']}")
            pt = AESGCM(key.key).decrypt(
                base64.b64decode(env["nonce"]),
                base64.b64decode(env["ciphertext"]), None)
            return json.loads(pt)
        except EtlError:
            raise
        except Exception as e:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           f"config decryption failed: {type(e).__name__}")

    def rotate(self, raw: str) -> str:
        """Re-encrypt an envelope under the primary key (xtask parity)."""
        return self.encrypt(self.decrypt(raw))
