"""Control-plane REST API.

Reference parity: crates/etl-api (19k LoC) — tenants / sources /
destinations / pipelines CRUD with per-tenant isolation via the `tenant_id`
header (routes/mod.rs:40-73), encrypted source/destination configs,
pipeline lifecycle routes `start/stop/restart/status/replication-status/
rollback-tables` (routes/pipelines.rs:662-1618), orchestration through the
fakeable deploy seam (k8s/base.rs:197), OpenAPI document, /metrics.

Storage: sqlite (the reference uses its own Postgres with sqlx migrations).
"""

from __future__ import annotations

import json
import sqlite3
from pathlib import Path

from aiohttp import web

from ..store.sql import SqliteStore
from ..telemetry.metrics import registry
from .crypto import ConfigCipher
from .orchestrator import Orchestrator, ReplicatorSpec

TENANT_HEADER = "tenant_id"
MAX_TENANT_ID_LEN = 64


def _require_tenant(request: web.Request) -> str:
    tenant = request.headers.get(TENANT_HEADER, "")
    if not tenant or len(tenant) > MAX_TENANT_ID_LEN \
            or not tenant.replace("-", "").replace("_", "").isalnum():
        raise web.HTTPUnauthorized(
            text=json.dumps({"error": "missing or invalid tenant_id header"}),
            content_type="application/json")
    return tenant


def _path_id(request: web.Request) -> int:
    raw = request.match_info["id"]
    if not raw.isdigit():
        raise _json_error(404, "not found")
    return int(raw)


async def _json_body(request: web.Request) -> dict:
    try:
        doc = await request.json()
    except Exception:
        raise _json_error(400, "request body must be JSON")
    if not isinstance(doc, dict):
        raise _json_error(400, "request body must be a JSON object")
    return doc


def _json_error(status: int, message: str) -> web.HTTPException:
    cls = {400: web.HTTPBadRequest, 404: web.HTTPNotFound,
           409: web.HTTPConflict}.get(status, web.HTTPInternalServerError)
    return cls(text=json.dumps({"error": message}),
               content_type="application/json")


class ApiState:
    def __init__(self, db_path: str, cipher: ConfigCipher,
                 orchestrator: Orchestrator):
        self.cipher = cipher
        self.orchestrator = orchestrator
        self.db = sqlite3.connect(db_path)
        self.db.executescript("""
CREATE TABLE IF NOT EXISTS api_tenants (
    id TEXT PRIMARY KEY, name TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS api_sources (
    id INTEGER PRIMARY KEY AUTOINCREMENT, tenant_id TEXT NOT NULL,
    name TEXT NOT NULL, config_enc TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS api_destinations (
    id INTEGER PRIMARY KEY AUTOINCREMENT, tenant_id TEXT NOT NULL,
    name TEXT NOT NULL, config_enc TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS api_pipelines (
    id INTEGER PRIMARY KEY AUTOINCREMENT, tenant_id TEXT NOT NULL,
    source_id INTEGER NOT NULL, destination_id INTEGER NOT NULL,
    publication_name TEXT NOT NULL, config_json TEXT NOT NULL DEFAULT '{}',
    store_path TEXT NOT NULL DEFAULT '');
""")
        self.db.commit()

    # -- row helpers ------------------------------------------------------------

    def fetch_owned(self, table: str, row_id: int, tenant: str):
        row = self.db.execute(
            f"SELECT * FROM {table} WHERE id = ? AND tenant_id = ?",
            (row_id, tenant)).fetchone()
        return row

    def pipeline_config(self, row) -> dict:
        """Assemble the full replicator config for a pipeline row."""
        _, tenant, source_id, dest_id, publication, config_json, store_path = row
        src = self.fetch_owned("api_sources", source_id, tenant)
        dst = self.fetch_owned("api_destinations", dest_id, tenant)
        if src is None or dst is None:
            raise _json_error(404, "source or destination missing")
        extra = json.loads(config_json)
        doc = {
            "pipeline_id": row[0],
            "publication_name": publication,
            "pg_connection": self.cipher.decrypt(src[3]),
            "destination": self.cipher.decrypt(dst[3]),
            **extra,
        }
        if store_path:
            doc["store"] = {"type": "sqlite", "path": store_path}
        return doc


def build_app(state: ApiState) -> web.Application:
    app = web.Application()
    r = app.router

    # -- health / metrics / openapi --------------------------------------------

    async def health(_req):
        return web.json_response({"status": "ok"})

    async def metrics(_req):
        return web.Response(text=registry.render_prometheus(),
                            content_type="text/plain")

    async def openapi(_req):
        return web.json_response(OPENAPI_DOC)

    r.add_get("/health", health)
    r.add_get("/metrics", metrics)
    r.add_get("/openapi.json", openapi)

    # -- tenants ----------------------------------------------------------------

    async def create_tenant(req: web.Request):
        doc = await _json_body(req)
        tid, name = doc.get("id"), doc.get("name")
        if not tid or not name:
            raise _json_error(400, "id and name required")
        try:
            state.db.execute("INSERT INTO api_tenants (id, name) VALUES (?, ?)",
                             (tid, name))
            state.db.commit()
        except sqlite3.IntegrityError:
            raise _json_error(409, f"tenant {tid} exists")
        return web.json_response({"id": tid, "name": name}, status=201)

    async def list_tenants(_req):
        rows = state.db.execute("SELECT id, name FROM api_tenants").fetchall()
        return web.json_response([{"id": i, "name": n} for i, n in rows])

    r.add_post("/v1/tenants", create_tenant)
    r.add_get("/v1/tenants", list_tenants)

    # -- sources / destinations (same shape) ------------------------------------

    def make_config_routes(table: str, path: str):
        async def create(req: web.Request):
            tenant = _require_tenant(req)
            doc = await _json_body(req)
            name, config = doc.get("name"), doc.get("config")
            if not name or not isinstance(config, dict):
                raise _json_error(400, "name and config required")
            cur = state.db.execute(
                f"INSERT INTO {table} (tenant_id, name, config_enc) "
                "VALUES (?, ?, ?)", (tenant, name, state.cipher.encrypt(config)))
            state.db.commit()
            return web.json_response({"id": cur.lastrowid, "name": name},
                                     status=201)

        async def list_(req: web.Request):
            tenant = _require_tenant(req)
            rows = state.db.execute(
                f"SELECT id, name FROM {table} WHERE tenant_id = ?",
                (tenant,)).fetchall()
            return web.json_response([{"id": i, "name": n} for i, n in rows])

        async def get(req: web.Request):
            tenant = _require_tenant(req)
            row = state.fetch_owned(table, _path_id(req), tenant)
            if row is None:
                raise _json_error(404, "not found")
            return web.json_response({
                "id": row[0], "name": row[2],
                "config": state.cipher.decrypt(row[3])})

        async def update(req: web.Request):
            tenant = _require_tenant(req)
            row = state.fetch_owned(table, _path_id(req), tenant)
            if row is None:
                raise _json_error(404, "not found")
            doc = await _json_body(req)
            config = doc.get("config")
            name = doc.get("name", row[2])
            enc = state.cipher.encrypt(config) if config is not None else row[3]
            state.db.execute(
                f"UPDATE {table} SET name = ?, config_enc = ? WHERE id = ?",
                (name, enc, row[0]))
            state.db.commit()
            return web.json_response({"id": row[0], "name": name})

        async def delete(req: web.Request):
            tenant = _require_tenant(req)
            row_id = _path_id(req)
            ref_col = "source_id" if table == "api_sources" \
                else "destination_id"
            used = state.db.execute(
                f"SELECT id FROM api_pipelines WHERE {ref_col} = ? AND "
                "tenant_id = ?", (row_id, tenant)).fetchall()
            if used:
                raise _json_error(
                    409, f"in use by pipelines {[r[0] for r in used]}")
            state.db.execute(
                f"DELETE FROM {table} WHERE id = ? AND tenant_id = ?",
                (row_id, tenant))
            state.db.commit()
            return web.json_response({}, status=204)

        r.add_post(path, create)
        r.add_get(path, list_)
        r.add_get(path + "/{id}", get)
        r.add_put(path + "/{id}", update)
        r.add_delete(path + "/{id}", delete)

    make_config_routes("api_sources", "/v1/sources")
    make_config_routes("api_destinations", "/v1/destinations")

    # -- pipelines ----------------------------------------------------------------

    async def create_pipeline(req: web.Request):
        tenant = _require_tenant(req)
        doc = await _json_body(req)
        try:
            source_id = int(doc["source_id"])
            dest_id = int(doc["destination_id"])
            publication = doc["publication_name"]
        except (KeyError, TypeError, ValueError):
            raise _json_error(
                400, "source_id, destination_id, publication_name required")
        if state.fetch_owned("api_sources", source_id, tenant) is None:
            raise _json_error(404, f"source {source_id} not found")
        if state.fetch_owned("api_destinations", dest_id, tenant) is None:
            raise _json_error(404, f"destination {dest_id} not found")
        cur = state.db.execute(
            "INSERT INTO api_pipelines (tenant_id, source_id, destination_id,"
            " publication_name, config_json, store_path) VALUES "
            "(?, ?, ?, ?, ?, ?)",
            (tenant, source_id, dest_id, publication,
             json.dumps(doc.get("config", {})), doc.get("store_path", "")))
        state.db.commit()
        return web.json_response({"id": cur.lastrowid}, status=201)

    async def list_pipelines(req: web.Request):
        tenant = _require_tenant(req)
        rows = state.db.execute(
            "SELECT id, source_id, destination_id, publication_name FROM "
            "api_pipelines WHERE tenant_id = ?", (tenant,)).fetchall()
        return web.json_response([
            {"id": i, "source_id": s, "destination_id": d,
             "publication_name": p} for i, s, d, p in rows])

    def _pipeline_row(req: web.Request, tenant: str):
        row = state.fetch_owned("api_pipelines",
                                _path_id(req), tenant)
        if row is None:
            raise _json_error(404, "pipeline not found")
        return row

    async def get_pipeline(req: web.Request):
        tenant = _require_tenant(req)
        row = _pipeline_row(req, tenant)
        return web.json_response({
            "id": row[0], "source_id": row[2], "destination_id": row[3],
            "publication_name": row[4], "config": json.loads(row[5])})

    async def delete_pipeline(req: web.Request):
        tenant = _require_tenant(req)
        row = _pipeline_row(req, tenant)
        await state.orchestrator.stop_pipeline(row[0])
        state.db.execute("DELETE FROM api_pipelines WHERE id = ?", (row[0],))
        state.db.commit()
        return web.json_response({}, status=204)

    async def start_pipeline(req: web.Request):
        tenant = _require_tenant(req)
        row = _pipeline_row(req, tenant)
        config = state.pipeline_config(row)
        await state.orchestrator.start_pipeline(ReplicatorSpec(
            pipeline_id=row[0], tenant_id=tenant, config=config))
        return web.json_response({"status": "starting"}, status=202)

    async def stop_pipeline(req: web.Request):
        tenant = _require_tenant(req)
        row = _pipeline_row(req, tenant)
        await state.orchestrator.stop_pipeline(row[0])
        return web.json_response({"status": "stopping"}, status=202)

    async def restart_pipeline(req: web.Request):
        tenant = _require_tenant(req)
        row = _pipeline_row(req, tenant)
        config = state.pipeline_config(row)
        await state.orchestrator.restart_pipeline(ReplicatorSpec(
            pipeline_id=row[0], tenant_id=tenant, config=config))
        return web.json_response({"status": "restarting"}, status=202)

    async def pipeline_status(req: web.Request):
        tenant = _require_tenant(req)
        row = _pipeline_row(req, tenant)
        st = await state.orchestrator.status(row[0])
        return web.json_response({"pipeline_id": st.pipeline_id,
                                  "state": st.state, "detail": st.detail})

    async def replication_status(req: web.Request):
        """Table states from the pipeline's durable store
        (reference routes/pipelines.rs replication-status)."""
        tenant = _require_tenant(req)
        row = _pipeline_row(req, tenant)
        store_path = row[6]
        if not store_path or not Path(store_path).exists():
            raise _json_error(404, "pipeline has no durable store")
        store = SqliteStore(store_path, row[0])
        await store.connect()
        try:
            states = await store.get_table_states()
            out = []
            for tid, st in sorted(states.items()):
                doc = {"table_id": tid, "state": st.type.value}
                if st.lsn is not None:
                    doc["lsn"] = str(st.lsn)
                if st.is_errored:
                    doc.update(reason=st.reason,
                               retry_policy=st.retry_policy.value,
                               retry_attempts=st.retry_attempts)
                out.append(doc)
            slot_lag = await _try_slot_lag(row, tenant)
            return web.json_response({"tables": out, "slot_lag": slot_lag})
        finally:
            await store.close()

    _slot_lag_cache: dict[int, tuple[float, object]] = {}
    _SLOT_LAG_TTL_S = 5.0

    async def _try_slot_lag(pipeline_row, tenant: str):
        """Source-side slot lag for the replication-status surface
        (reference etl-postgres/src/lag.rs via routes/pipelines.rs).
        Best-effort: an unreachable source yields null, not a 5xx.
        Briefly cached per pipeline so a polling dashboard doesn't pay a
        fresh connect+auth against the customer's database per request."""
        import time as _time

        from ..postgres.lag import query_slot_lag
        from ..postgres.wire import PgWireConnection

        pid = pipeline_row[0]
        cached = _slot_lag_cache.get(pid)
        if cached is not None and _time.monotonic() - cached[0] \
                < _SLOT_LAG_TTL_S:
            return cached[1]
        src = state.fetch_owned("api_sources", pipeline_row[2], tenant)
        if src is None:
            return None
        try:
            cfg = state.cipher.decrypt(src[3])  # → dict
            conn = PgWireConnection(
                host=cfg.get("host", "localhost"),
                port=int(cfg.get("port", 5432)),
                database=cfg.get("database", "postgres"),
                user=cfg.get("user", "postgres"),
                password=cfg.get("password"),
                application_name="etl_tpu_api", connect_timeout_s=3.0)
            await conn.connect()
            try:
                metrics = await query_slot_lag(conn)
            finally:
                await conn.close()
            result = [{
                "slot_name": m.slot_name, "active": m.active,
                "wal_status": m.wal_status,
                "restart_lsn_lag_bytes": m.restart_lsn_lag_bytes,
                "confirmed_flush_lag_bytes": m.confirmed_flush_lag_bytes,
                "safe_wal_size_bytes": m.safe_wal_size_bytes,
                "write_lag_ms": m.write_lag_ms,
                "flush_lag_ms": m.flush_lag_ms,
                "replay_lag_ms": m.replay_lag_ms,
            } for m in metrics]
        except Exception:
            result = None
        _slot_lag_cache[pid] = (_time.monotonic(), result)
        return result

    async def rollback_tables(req: web.Request):
        """Repair op: reset errored tables to Init so they resync
        (reference routes/pipelines.rs:1372 rollback-tables)."""
        tenant = _require_tenant(req)
        row = _pipeline_row(req, tenant)
        store_path = row[6]
        if not store_path or not Path(store_path).exists():
            raise _json_error(404, "pipeline has no durable store")
        doc = await _json_body(req)
        table_ids = doc.get("table_ids")
        store = SqliteStore(store_path, row[0])
        await store.connect()
        try:
            states = await store.get_table_states()
            targets = [tid for tid in states
                       if table_ids is None or tid in table_ids]
            rolled = []
            for tid in targets:
                if table_ids is not None or states[tid].is_errored:
                    await store.reset_table(tid)
                    rolled.append(tid)
            return web.json_response({"rolled_back": sorted(rolled)})
        finally:
            await store.close()

    r.add_post("/v1/pipelines", create_pipeline)
    r.add_get("/v1/pipelines", list_pipelines)
    r.add_get("/v1/pipelines/{id}", get_pipeline)
    r.add_delete("/v1/pipelines/{id}", delete_pipeline)
    r.add_post("/v1/pipelines/{id}/start", start_pipeline)
    r.add_post("/v1/pipelines/{id}/stop", stop_pipeline)
    r.add_post("/v1/pipelines/{id}/restart", restart_pipeline)
    r.add_get("/v1/pipelines/{id}/status", pipeline_status)
    r.add_get("/v1/pipelines/{id}/replication-status", replication_status)
    r.add_post("/v1/pipelines/{id}/rollback-tables", rollback_tables)
    return app


OPENAPI_DOC = {
    "openapi": "3.0.0",
    "info": {"title": "etl_tpu control plane", "version": "0.1.0"},
    "paths": {
        "/v1/tenants": {"post": {}, "get": {}},
        "/v1/sources": {"post": {}, "get": {}},
        "/v1/sources/{id}": {"get": {}, "put": {}, "delete": {}},
        "/v1/destinations": {"post": {}, "get": {}},
        "/v1/destinations/{id}": {"get": {}, "put": {}, "delete": {}},
        "/v1/pipelines": {"post": {}, "get": {}},
        "/v1/pipelines/{id}": {"get": {}, "delete": {}},
        "/v1/pipelines/{id}/start": {"post": {}},
        "/v1/pipelines/{id}/stop": {"post": {}},
        "/v1/pipelines/{id}/restart": {"post": {}},
        "/v1/pipelines/{id}/status": {"get": {}},
        "/v1/pipelines/{id}/replication-status": {"get": {}},
        "/v1/pipelines/{id}/rollback-tables": {"post": {}},
    },
}
