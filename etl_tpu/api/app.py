"""Control-plane REST API.

Reference parity: crates/etl-api (19k LoC) — tenants / sources /
destinations / pipelines CRUD with per-tenant isolation via the `tenant_id`
header (routes/mod.rs:40-73), encrypted source/destination configs,
pipeline lifecycle routes `start/stop/restart/status/replication-status/
rollback-tables` (routes/pipelines.rs:662-1618), orchestration through the
fakeable deploy seam (k8s/base.rs:197), OpenAPI document, /metrics.

Storage: the ApiDb seam (api/db.py) — sqlite file OR Postgres over the
wire-client pool, mirroring the reference API owning its own Postgres
database with sqlx migrations (crates/etl-api/migrations/).
"""

from __future__ import annotations

import json
from pathlib import Path

from aiohttp import web

from ..store.sql import SqliteStore
from ..telemetry.metrics import registry
from .crypto import ConfigCipher
from .db import ApiDb, ApiIntegrityError, SqliteApiDb
from .orchestrator import Orchestrator, ReplicatorSpec

TENANT_HEADER = "tenant_id"
MAX_TENANT_ID_LEN = 64


def _require_tenant(request: web.Request) -> str:
    tenant = request.headers.get(TENANT_HEADER, "")
    if not tenant or len(tenant) > MAX_TENANT_ID_LEN \
            or not tenant.replace("-", "").replace("_", "").isalnum():
        raise web.HTTPUnauthorized(
            text=json.dumps({"error": "missing or invalid tenant_id header"}),
            content_type="application/json")
    return tenant


def _path_id(request: web.Request) -> int:
    raw = request.match_info["id"]
    if not raw.isdigit():
        raise _json_error(404, "not found")
    return int(raw)


async def _json_body(request: web.Request) -> dict:
    try:
        doc = await request.json()
    except Exception:
        raise _json_error(400, "request body must be JSON")
    if not isinstance(doc, dict):
        raise _json_error(400, "request body must be a JSON object")
    return doc


def _json_error(status: int, message: str) -> web.HTTPException:
    cls = {400: web.HTTPBadRequest, 404: web.HTTPNotFound,
           409: web.HTTPConflict}.get(status, web.HTTPInternalServerError)
    return cls(text=json.dumps({"error": message}),
               content_type="application/json")


def _int(v) -> int:
    """DB-value → int: the Postgres wire path returns text cells."""
    return int(v)


def _bool(v) -> bool:
    return bool(int(v))


class ApiState:
    def __init__(self, db: "str | ApiDb", cipher: ConfigCipher,
                 orchestrator: Orchestrator, api_key: str | None = None,
                 fleet_store=None, fleet_lag_of=None):
        self.cipher = cipher
        self.orchestrator = orchestrator
        # fleet control plane (docs/fleet.md): the StateStore holding the
        # FleetSpec + actuation journals the /v1/fleet endpoint reports
        # on (None = this deployment runs no fleet), and an optional
        # async pipeline_id -> lag-bytes reader (None = lag unreported)
        self.fleet_store = fleet_store
        self.fleet_lag_of = fleet_lag_of
        # deployment API key (reference etl-api authentication module):
        # when set, every /v1 route requires `Authorization: Bearer <key>`
        # BEFORE tenant routing — the tenant header alone is an assertion,
        # not an authentication
        self.api_key = api_key
        self.db: ApiDb = SqliteApiDb(db) if isinstance(db, str) else db
        self._connected = False

    async def connect(self) -> None:
        if not self._connected:
            await self.db.connect()
            self._connected = True

    async def close(self) -> None:
        if self._connected:
            await self.db.close()
            self._connected = False

    # -- row helpers ------------------------------------------------------------

    async def fetch_owned(self, table: str, row_id: int, tenant: str):
        rows = await self.db.run(
            f"SELECT * FROM {table} WHERE id = ? AND tenant_id = ?",
            (row_id, tenant))
        return rows[0] if rows else None

    async def default_image(self, tenant: str) -> "str | None":
        rows = await self.db.run(
            "SELECT name FROM api_images WHERE tenant_id = ? AND "
            "is_default = 1", (tenant,))
        return rows[0][0] if rows else None

    async def pipeline_image(self, row) -> "str | None":
        """The image a pipeline runs: its pinned version if set (the
        /version route), else the tenant default."""
        pinned = row[7] if len(row) > 7 else ""
        return pinned or await self.default_image(row[1])

    async def pipeline_config(self, row) -> dict:
        """Assemble the full replicator config for a pipeline row."""
        tenant, source_id, dest_id = row[1], _int(row[2]), _int(row[3])
        publication, config_json, store_path = row[4], row[5], row[6]
        src = await self.fetch_owned("api_sources", source_id, tenant)
        dst = await self.fetch_owned("api_destinations", dest_id, tenant)
        if src is None or dst is None:
            raise _json_error(404, "source or destination missing")
        extra = json.loads(config_json)
        doc = {
            "pipeline_id": _int(row[0]),
            "publication_name": publication,
            "pg_connection": self.cipher.decrypt(src[3]),
            "destination": self.cipher.decrypt(dst[3]),
            **extra,
        }
        if store_path:
            doc["store"] = {"type": "sqlite", "path": store_path}
        return doc


_SECRET_KEY_HINTS = ("password", "secret", "token", "key", "credential")


MASKED = "********"


def redact_config(doc):
    """Decrypted configs never leave the API verbatim: ANY value under a
    secret-looking key is masked, whatever its type (ADVICE r1: GET
    previously echoed decrypted source/destination credentials)."""
    if isinstance(doc, dict):
        return {k: (MASKED if any(h in k.lower()
                                  for h in _SECRET_KEY_HINTS)
                    else redact_config(v))
                for k, v in doc.items()}
    if isinstance(doc, list):
        return [redact_config(v) for v in doc]
    return doc


def unmask_config(new, stored):
    """Read-modify-write support: a client that PUTs back a GET response
    carries the mask sentinel — restore the stored value there instead of
    encrypting the literal '********' as the credential."""
    if new == MASKED:
        return stored
    if isinstance(new, dict) and isinstance(stored, dict):
        return {k: unmask_config(v, stored.get(k)) for k, v in new.items()}
    if isinstance(new, list) and isinstance(stored, list):
        return [unmask_config(v, s) for v, s in zip(new, stored)] \
            + new[len(stored):]
    return new


def build_app(state: ApiState) -> web.Application:
    @web.middleware
    async def auth_middleware(request: web.Request, handler):
        if state.api_key is not None \
                and request.path.startswith("/v1"):
            import hmac as _hmac

            header = request.headers.get("Authorization", "")
            if not _hmac.compare_digest(header,
                                        f"Bearer {state.api_key}"):
                return web.json_response({"error": "unauthorized"},
                                         status=401)
        return await handler(request)

    app = web.Application(middlewares=[auth_middleware])

    async def _startup(_app):
        await state.connect()

    async def _cleanup(_app):
        await state.close()

    app.on_startup.append(_startup)
    app.on_cleanup.append(_cleanup)
    r = app.router

    # -- health / metrics / openapi --------------------------------------------

    async def health(_req):
        return web.json_response({"status": "ok"})

    async def metrics(_req):
        return web.Response(text=registry.render_prometheus(),
                            content_type="text/plain")

    async def openapi(_req):
        return web.json_response(OPENAPI_DOC)

    async def docs(_req):
        # the reference serves Swagger UI (utoipa-swagger-ui); this env
        # has zero egress, so /docs is a SELF-CONTAINED renderer of the
        # same /openapi.json — no CDN assets
        return web.Response(text=_DOCS_HTML, content_type="text/html")

    r.add_get("/health", health)
    r.add_get("/metrics", metrics)
    r.add_get("/docs", docs)
    r.add_get("/openapi.json", openapi)

    # -- tenants ----------------------------------------------------------------

    async def create_tenant(req: web.Request):
        doc = await _json_body(req)
        tid, name = doc.get("id"), doc.get("name")
        if not tid or not name:
            raise _json_error(400, "id and name required")
        try:
            await state.db.run(
                "INSERT INTO api_tenants (id, name) VALUES (?, ?)",
                (tid, name))
        except ApiIntegrityError:
            raise _json_error(409, f"tenant {tid} exists")
        return web.json_response({"id": tid, "name": name}, status=201)

    async def list_tenants(_req):
        rows = await state.db.run("SELECT id, name FROM api_tenants")
        return web.json_response([{"id": i, "name": n} for i, n in rows])

    r.add_post("/v1/tenants", create_tenant)
    r.add_get("/v1/tenants", list_tenants)

    # -- sources / destinations (same shape) ------------------------------------

    def make_config_routes(table: str, path: str):
        from .validation import (validate_destination_shape,
                                 validate_source_shape)

        shape_check = validate_source_shape if table == "api_sources" \
            else validate_destination_shape

        def _reject_invalid(config: dict) -> None:
            """Reject-before-store (reference routes validate configs at
            deserialization): static shape failures → 400 with the same
            failure list the :validate routes return."""
            failures = shape_check(config)
            if failures:
                raise web.HTTPBadRequest(
                    text=json.dumps({
                        "error": "invalid config",
                        "validation_failures": [f.to_json()
                                                for f in failures]}),
                    content_type="application/json")

        async def create(req: web.Request):
            tenant = _require_tenant(req)
            doc = await _json_body(req)
            name, config = doc.get("name"), doc.get("config")
            if not name or not isinstance(config, dict):
                raise _json_error(400, "name and config required")
            _reject_invalid(config)
            rows = await state.db.run(
                f"INSERT INTO {table} (tenant_id, name, config_enc) "
                "VALUES (?, ?, ?) RETURNING id",
                (tenant, name, state.cipher.encrypt(config)))
            return web.json_response({"id": _int(rows[0][0]),
                                      "name": name}, status=201)

        async def list_(req: web.Request):
            tenant = _require_tenant(req)
            rows = await state.db.run(
                f"SELECT id, name FROM {table} WHERE tenant_id = ?",
                (tenant,))
            return web.json_response([{"id": _int(i), "name": n}
                                      for i, n in rows])

        async def get(req: web.Request):
            tenant = _require_tenant(req)
            row = await state.fetch_owned(table, _path_id(req), tenant)
            if row is None:
                raise _json_error(404, "not found")
            return web.json_response({
                "id": _int(row[0]), "name": row[2],
                "config": redact_config(state.cipher.decrypt(row[3]))})

        async def update(req: web.Request):
            tenant = _require_tenant(req)
            row = await state.fetch_owned(table, _path_id(req), tenant)
            if row is None:
                raise _json_error(404, "not found")
            doc = await _json_body(req)
            config = doc.get("config")
            name = doc.get("name", row[2])
            if config is not None:
                config = unmask_config(config,
                                       state.cipher.decrypt(row[3]))
                _reject_invalid(config)
            enc = state.cipher.encrypt(config) if config is not None else row[3]
            await state.db.run(
                f"UPDATE {table} SET name = ?, config_enc = ? WHERE id = ?",
                (name, enc, row[0]))
            return web.json_response({"id": _int(row[0]), "name": name})

        async def delete(req: web.Request):
            tenant = _require_tenant(req)
            row_id = _path_id(req)
            ref_col = "source_id" if table == "api_sources" \
                else "destination_id"
            used = await state.db.run(
                f"SELECT id FROM api_pipelines WHERE {ref_col} = ? AND "
                "tenant_id = ?", (row_id, tenant))
            if used:
                raise _json_error(
                    409,
                    f"in use by pipelines {[_int(r[0]) for r in used]}")
            await state.db.run(
                f"DELETE FROM {table} WHERE id = ? AND tenant_id = ?",
                (row_id, tenant))
            return web.json_response({}, status=204)

        r.add_post(path, create)
        r.add_get(path, list_)
        r.add_get(path + "/{id}", get)
        r.add_put(path + "/{id}", update)
        r.add_delete(path + "/{id}", delete)

    make_config_routes("api_sources", "/v1/sources")
    make_config_routes("api_destinations", "/v1/destinations")

    # -- validation routes (reference routes/destinations.rs:468-516,
    # routes/common.rs:67-79): static shape + LIVE probes, returning
    # `validation_failures` with severity instead of erroring ------------------

    async def validate_source_route(req: web.Request):
        from .validation import validate_source

        _require_tenant(req)
        doc = await _json_body(req)
        config = doc.get("config")
        if not isinstance(config, dict):
            raise _json_error(400, "config required")
        pipeline_config = doc.get("pipeline_config") or {}
        failures = await validate_source(
            config, publication=pipeline_config.get("publication_name"))
        return web.json_response(
            {"validation_failures": [f.to_json() for f in failures]})

    async def validate_destination_route(req: web.Request):
        from .validation import validate_destination

        tenant = _require_tenant(req)
        doc = await _json_body(req)
        config = doc.get("config")
        if not isinstance(config, dict):
            raise _json_error(400, "config required")
        pipeline_config = doc.get("pipeline_config")
        source_id = doc.get("source_id")
        # source_id + pipeline_config travel together (destinations.rs:500)
        if (source_id is None) != (pipeline_config is None):
            raise _json_error(
                400, "source_id and pipeline_config must be provided "
                     "together")
        if source_id is not None:
            try:
                source_id = int(source_id)
            except (TypeError, ValueError):
                raise _json_error(400, "source_id must be an integer")
            if await state.fetch_owned("api_sources", source_id,
                                       tenant) is None:
                raise _json_error(404, "source not found")
        failures = await validate_destination(config, pipeline_config)
        return web.json_response(
            {"validation_failures": [f.to_json() for f in failures]})

    r.add_post("/v1/sources:validate", validate_source_route)
    r.add_post("/v1/destinations:validate", validate_destination_route)

    # -- images (replicator container images; reference etl-api images CRUD)

    async def create_image(req: web.Request):
        tenant = _require_tenant(req)
        doc = await _json_body(req)
        name = doc.get("name")
        if not name:
            raise _json_error(400, "name required")
        try:
            rows = await state.db.run(
                "INSERT INTO api_images (tenant_id, name, is_default) "
                "VALUES (?, ?, ?) RETURNING id",
                (tenant, name, 1 if doc.get("default") else 0))
        except ApiIntegrityError:
            raise _json_error(409, f"image {name} exists")
        iid = _int(rows[0][0])
        if doc.get("default"):
            await state.db.run(
                "UPDATE api_images SET is_default = 0 "
                "WHERE tenant_id = ? AND id <> ?", (tenant, iid))
        return web.json_response(
            {"id": iid, "name": name,
             "default": bool(doc.get("default"))}, status=201)

    async def list_images(req: web.Request):
        tenant = _require_tenant(req)
        rows = await state.db.run(
            "SELECT id, name, is_default FROM api_images WHERE "
            "tenant_id = ?", (tenant,))
        return web.json_response([
            {"id": _int(i), "name": n, "default": _bool(d)}
            for i, n, d in rows])

    async def set_default_image(req: web.Request):
        tenant = _require_tenant(req)
        iid = _path_id(req)
        rows = await state.db.run(
            "SELECT id FROM api_images WHERE id = ? AND tenant_id = ?",
            (iid, tenant))
        if not rows:
            raise _json_error(404, "image not found")
        await state.db.run("UPDATE api_images SET is_default = 0 WHERE "
                           "tenant_id = ?", (tenant,))
        await state.db.run("UPDATE api_images SET is_default = 1 "
                           "WHERE id = ?", (iid,))
        return web.json_response({"id": iid, "default": True})

    async def delete_image(req: web.Request):
        tenant = _require_tenant(req)
        iid = _path_id(req)
        row = await state.fetch_owned("api_images", iid, tenant)
        if row is not None:
            # a pipeline pinned to this image (the /version route) would
            # silently deploy an unregistered name after the delete
            pinned = await state.db.run(
                "SELECT id FROM api_pipelines WHERE tenant_id = ? AND "
                "image_name = ?", (tenant, row[2]))
            if pinned:
                raise _json_error(
                    409, f"image pinned by pipelines "
                         f"{sorted(_int(r[0]) for r in pinned)}")
        await state.db.run(
            "DELETE FROM api_images WHERE id = ? AND tenant_id = ?",
            (iid, tenant))
        return web.json_response({}, status=204)

    r.add_post("/v1/images", create_image)
    r.add_get("/v1/images", list_images)
    r.add_post("/v1/images/{id}/set-default", set_default_image)
    r.add_delete("/v1/images/{id}", delete_image)

    # -- pipelines ----------------------------------------------------------------

    async def create_pipeline(req: web.Request):
        tenant = _require_tenant(req)
        doc = await _json_body(req)
        try:
            source_id = int(doc["source_id"])
            dest_id = int(doc["destination_id"])
            publication = doc["publication_name"]
        except (KeyError, TypeError, ValueError):
            raise _json_error(
                400, "source_id, destination_id, publication_name required")
        if await state.fetch_owned("api_sources", source_id,
                                   tenant) is None:
            raise _json_error(404, f"source {source_id} not found")
        if await state.fetch_owned("api_destinations", dest_id,
                                   tenant) is None:
            raise _json_error(404, f"destination {dest_id} not found")
        rows = await state.db.run(
            "INSERT INTO api_pipelines (tenant_id, source_id, destination_id,"
            " publication_name, config_json, store_path) VALUES "
            "(?, ?, ?, ?, ?, ?) RETURNING id",
            (tenant, source_id, dest_id, publication,
             json.dumps(doc.get("config", {})), doc.get("store_path", "")))
        return web.json_response({"id": _int(rows[0][0])}, status=201)

    async def list_pipelines(req: web.Request):
        tenant = _require_tenant(req)
        rows = await state.db.run(
            "SELECT id, source_id, destination_id, publication_name FROM "
            "api_pipelines WHERE tenant_id = ?", (tenant,))
        return web.json_response([
            {"id": _int(i), "source_id": _int(s),
             "destination_id": _int(d), "publication_name": p}
            for i, s, d, p in rows])

    async def _pipeline_row(req: web.Request, tenant: str):
        row = await state.fetch_owned("api_pipelines",
                                      _path_id(req), tenant)
        if row is None:
            raise _json_error(404, "pipeline not found")
        return row

    async def get_pipeline(req: web.Request):
        tenant = _require_tenant(req)
        row = await _pipeline_row(req, tenant)
        doc = {
            "id": _int(row[0]), "source_id": _int(row[2]),
            "destination_id": _int(row[3]),
            "publication_name": row[4], "config": json.loads(row[5])}
        if len(row) > 7 and row[7]:
            doc["image"] = row[7]
        return web.json_response(doc)

    async def delete_pipeline(req: web.Request):
        tenant = _require_tenant(req)
        row = await _pipeline_row(req, tenant)
        # delete, not stop: permanent teardown may also drop
        # pipeline-owned storage (the k8s warehouse PVC)
        await state.orchestrator.delete_pipeline(_int(row[0]))
        await state.db.run("DELETE FROM api_pipelines WHERE id = ?",
                           (row[0],))
        return web.json_response({}, status=204)

    async def start_pipeline(req: web.Request):
        tenant = _require_tenant(req)
        row = await _pipeline_row(req, tenant)
        config = await state.pipeline_config(row)
        await state.orchestrator.start_pipeline(ReplicatorSpec(
            pipeline_id=_int(row[0]), tenant_id=tenant, config=config,
            image=await state.pipeline_image(row)))
        return web.json_response({"status": "starting"}, status=202)

    async def stop_pipeline(req: web.Request):
        tenant = _require_tenant(req)
        row = await _pipeline_row(req, tenant)
        await state.orchestrator.stop_pipeline(_int(row[0]))
        return web.json_response({"status": "stopping"}, status=202)

    async def restart_pipeline(req: web.Request):
        tenant = _require_tenant(req)
        row = await _pipeline_row(req, tenant)
        config = await state.pipeline_config(row)
        await state.orchestrator.restart_pipeline(ReplicatorSpec(
            pipeline_id=_int(row[0]), tenant_id=tenant, config=config,
            image=await state.pipeline_image(row)))
        return web.json_response({"status": "restarting"}, status=202)

    async def update_pipeline_version(req: web.Request):
        """Pin/roll the replicator image a pipeline runs (reference
        routes/pipelines.rs:662-735 update_pipeline_version): body
        names an image by id, or omits it to track the tenant default.
        A RUNNING pipeline is re-applied so the StatefulSet rolls to
        the new image; a stopped one picks it up at next start."""
        tenant = _require_tenant(req)
        row = await _pipeline_row(req, tenant)
        doc = await _json_body(req)
        image_id = doc.get("image_id")
        if image_id is not None:
            try:
                image_id = int(image_id)
            except (TypeError, ValueError):
                raise _json_error(400, "image_id must be an integer")
            img = await state.fetch_owned("api_images", image_id, tenant)
            if img is None:
                raise _json_error(404, "image not found")
            image_name = img[2]
        else:
            image_name = ""  # back to tracking the tenant default
        await state.db.run(
            "UPDATE api_pipelines SET image_name = ? WHERE id = ?",
            (image_name, row[0]))
        effective = image_name or await state.default_image(tenant)
        st = await state.orchestrator.status(_int(row[0]))
        rolled = False
        if st.state not in ("stopped", "unknown"):
            config = await state.pipeline_config(row)
            await state.orchestrator.start_pipeline(ReplicatorSpec(
                pipeline_id=_int(row[0]), tenant_id=tenant,
                config=config, image=effective))
            rolled = True
        return web.json_response({
            "id": _int(row[0]), "image": effective,
            "pinned": bool(image_name), "rolled_out": rolled})

    async def pipeline_status(req: web.Request):
        tenant = _require_tenant(req)
        row = await _pipeline_row(req, tenant)
        st = await state.orchestrator.status(_int(row[0]))
        return web.json_response({"pipeline_id": st.pipeline_id,
                                  "state": st.state, "detail": st.detail})

    async def replication_status(req: web.Request):
        """Table states from the pipeline's durable store
        (reference routes/pipelines.rs replication-status)."""
        tenant = _require_tenant(req)
        row = await _pipeline_row(req, tenant)
        store_path = row[6]
        if not store_path or not Path(store_path).exists():
            raise _json_error(404, "pipeline has no durable store")
        store = SqliteStore(store_path, _int(row[0]))
        await store.connect()
        try:
            states = await store.get_table_states()
            out = []
            for tid, st in sorted(states.items()):
                doc = {"table_id": tid, "state": st.type.value}
                if st.lsn is not None:
                    doc["lsn"] = str(st.lsn)
                if st.is_errored:
                    doc.update(reason=st.reason,
                               retry_policy=st.retry_policy.value,
                               retry_attempts=st.retry_attempts)
                out.append(doc)
            slot_lag = await _try_slot_lag(row, tenant)
            return web.json_response({"tables": out, "slot_lag": slot_lag})
        finally:
            await store.close()

    _slot_lag_cache: dict[int, tuple[float, object]] = {}
    _SLOT_LAG_TTL_S = 5.0

    async def _try_slot_lag(pipeline_row, tenant: str):
        """Source-side slot lag for the replication-status surface
        (reference etl-postgres/src/lag.rs via routes/pipelines.rs).
        Best-effort: an unreachable source yields null, not a 5xx.
        Briefly cached per pipeline so a polling dashboard doesn't pay a
        fresh connect+auth against the customer's database per request."""
        import time as _time

        from ..postgres.lag import query_slot_lag
        from ..postgres.wire import PgWireConnection

        pid = _int(pipeline_row[0])
        cached = _slot_lag_cache.get(pid)
        if cached is not None and _time.monotonic() - cached[0] \
                < _SLOT_LAG_TTL_S:
            return cached[1]
        src = await state.fetch_owned("api_sources",
                                      _int(pipeline_row[2]), tenant)
        if src is None:
            return None
        try:
            cfg = state.cipher.decrypt(src[3])  # → dict
            conn = PgWireConnection(
                host=cfg.get("host", "localhost"),
                port=int(cfg.get("port", 5432)),
                # canonical source-config keys (name/username), with the
                # legacy aliases as fallback
                database=cfg.get("name", cfg.get("database", "postgres")),
                user=cfg.get("username", cfg.get("user", "postgres")),
                password=cfg.get("password"),
                application_name="etl_tpu_api", connect_timeout_s=3.0)
            await conn.connect()
            try:
                metrics = await query_slot_lag(conn)
            finally:
                await conn.close()
            result = [{
                "slot_name": m.slot_name, "active": m.active,
                "wal_status": m.wal_status,
                "restart_lsn_lag_bytes": m.restart_lsn_lag_bytes,
                "confirmed_flush_lag_bytes": m.confirmed_flush_lag_bytes,
                "safe_wal_size_bytes": m.safe_wal_size_bytes,
                "write_lag_ms": m.write_lag_ms,
                "flush_lag_ms": m.flush_lag_ms,
                "replay_lag_ms": m.replay_lag_ms,
            } for m in metrics]
        except Exception:
            result = None
        _slot_lag_cache[pid] = (_time.monotonic(), result)
        return result

    async def rollback_tables(req: web.Request):
        """Repair op: reset errored tables to Init so they resync
        (reference routes/pipelines.rs:1372 rollback-tables)."""
        tenant = _require_tenant(req)
        row = await _pipeline_row(req, tenant)
        store_path = row[6]
        if not store_path or not Path(store_path).exists():
            raise _json_error(404, "pipeline has no durable store")
        doc = await _json_body(req)
        table_ids = doc.get("table_ids")
        from ..postgres.slots import table_sync_slot_name

        store = SqliteStore(store_path, _int(row[0]))
        await store.connect()
        try:
            states = await store.get_table_states()
            targets = [tid for tid in states
                       if table_ids is None or tid in table_ids]
            rolled = []
            for tid in targets:
                if table_ids is not None or states[tid].is_errored:
                    prior = states[tid]
                    await store.reset_table(tid)
                    # a stale sync-slot progress row would fence the fresh
                    # copy's catchup below its real position
                    await store.delete_durable_progress(
                        table_sync_slot_name(_int(row[0]), tid))
                    rolled.append({
                        "table_id": tid,
                        "previous_state": prior.type.value,
                        "previous_reason": prior.reason
                        if prior.is_errored else None,
                    })
            unknown = [] if table_ids is None else \
                [t for t in table_ids if t not in states]
            return web.json_response({
                "rolled_back": sorted(r["table_id"] for r in rolled),
                "tables": sorted(rolled, key=lambda r: r["table_id"]),
                "unknown_table_ids": sorted(unknown)})
        finally:
            await store.close()

    r.add_post("/v1/pipelines", create_pipeline)
    r.add_get("/v1/pipelines", list_pipelines)
    r.add_get("/v1/pipelines/{id}", get_pipeline)
    r.add_delete("/v1/pipelines/{id}", delete_pipeline)
    r.add_post("/v1/pipelines/{id}/start", start_pipeline)
    r.add_post("/v1/pipelines/{id}/stop", stop_pipeline)
    r.add_post("/v1/pipelines/{id}/restart", restart_pipeline)
    r.add_get("/v1/pipelines/{id}/status", pipeline_status)
    r.add_get("/v1/pipelines/{id}/replication-status", replication_status)
    r.add_post("/v1/pipelines/{id}/version", update_pipeline_version)
    r.add_post("/v1/pipelines/{id}/rollback-tables", rollback_tables)

    # -- fleet (docs/fleet.md) --------------------------------------------------

    async def fleet_status(_req: web.Request):
        """ONE aggregated view of every pipeline the fleet runs:
        desired vs observed shard counts, orchestrator health with the
        pod /health degraded reasons, per-pipeline lag when a reader is
        wired, and the fleet-wide degraded-reason tally. Deliberately
        tenant-headerless: this is the operator's fleet console, behind
        the same bearer auth as every /v1 route."""
        from ..fleet.reconciler import place_fleet
        from ..fleet.spec import FleetSpec
        from ..models.errors import EtlError

        spec_doc = None
        if state.fleet_store is not None:
            spec_doc = await state.fleet_store.get_fleet_spec()
        spec = FleetSpec.from_json(spec_doc)
        targets = place_fleet(spec)
        by_id = spec.by_id()
        try:
            observed = await state.orchestrator.list_pipelines()
        except EtlError:
            observed = {}
        pipelines = []
        reason_tally: dict[str, int] = {}
        states_tally: dict[str, int] = {}
        for pid in sorted(set(targets) | set(observed)):
            st = await state.orchestrator.status(pid)
            lag = None
            if state.fleet_lag_of is not None:
                lag = await state.fleet_lag_of(pid)
            for reason in st.reasons:
                reason_tally[reason] = reason_tally.get(reason, 0) + 1
            states_tally[st.state] = states_tally.get(st.state, 0) + 1
            p = by_id.get(pid)
            pipelines.append({
                "pipeline_id": pid,
                "tenant_id": p.tenant_id if p else None,
                "profile": p.profile if p else None,
                "desired_shards": targets.get(pid, 0),
                "observed_shards": observed.get(pid, 0),
                "state": st.state,
                "detail": st.detail,
                "degraded_reasons": list(st.reasons),
                "lag_bytes": lag,
            })
        return web.json_response({
            "spec_version": spec.spec_version,
            "pipelines": pipelines,
            "counts": {
                "desired": len(targets),
                "observed": len(observed),
                "by_state": states_tally,
            },
            "converged": dict(observed) == targets,
            "degraded_reasons": reason_tally,
            "quotas": {t: q.to_json()
                       for t, q in sorted(spec.quotas.items())},
        })

    r.add_get("/v1/fleet", fleet_status)
    return app


OPENAPI_DOC = {
    "openapi": "3.0.3",
    "info": {
        "title": "etl_tpu control plane",
        "version": "0.2.0",
        "description": (
            "Multi-tenant control plane for replication pipelines: "
            "sources/destinations with encrypted configs, pipeline "
            "lifecycle via the orchestrator seam, replicator images, "
            "and repair operations."),
    },
    "components": {
        "securitySchemes": {
            "bearer": {"type": "http", "scheme": "bearer"},
            "tenant": {"type": "apiKey", "in": "header",
                       "name": "tenant_id"},
        },
        "schemas": {
            "Error": {"type": "object",
                      "properties": {"error": {"type": "string"}}},
            "Tenant": {"type": "object",
                       "properties": {"id": {"type": "string"},
                                      "name": {"type": "string"}},
                       "required": ["id", "name"]},
            "ConfigResource": {
                "type": "object",
                "properties": {"id": {"type": "integer"},
                               "name": {"type": "string"},
                               "config": {"type": "object"}},
                "description": "GET responses mask secret-looking config "
                               "values."},
            "Image": {"type": "object",
                      "properties": {"id": {"type": "integer"},
                                     "name": {"type": "string"},
                                     "default": {"type": "boolean"}}},
            "Pipeline": {
                "type": "object",
                "properties": {"id": {"type": "integer"},
                               "source_id": {"type": "integer"},
                               "destination_id": {"type": "integer"},
                               "publication_name": {"type": "string"},
                               "config": {"type": "object"},
                               "store_path": {"type": "string"}},
                "required": ["source_id", "destination_id",
                             "publication_name"]},
            "PipelineStatus": {
                "type": "object",
                "properties": {"pipeline_id": {"type": "integer"},
                               "state": {"type": "string",
                                         "enum": ["stopped", "starting",
                                                  "running", "failed"]},
                               "detail": {"type": "string"}}},
            "ReplicationStatus": {
                "type": "object",
                "properties": {
                    "tables": {"type": "array", "items": {
                        "type": "object",
                        "properties": {
                            "table_id": {"type": "integer"},
                            "state": {"type": "string"},
                            "lsn": {"type": "string"},
                            "reason": {"type": "string"},
                            "retry_policy": {"type": "string"},
                            "retry_attempts": {"type": "integer"}}}},
                    "slot_lag": {"type": "array", "nullable": True,
                                 "items": {"type": "object"}}}},
            "RollbackRequest": {
                "type": "object",
                "properties": {"table_ids": {
                    "type": "array", "items": {"type": "integer"},
                    "description": "omit to roll back every errored "
                                   "table"}}},
            "RollbackResponse": {
                "type": "object",
                "properties": {
                    "rolled_back": {"type": "array",
                                    "items": {"type": "integer"}},
                    "tables": {"type": "array", "items": {"type": "object"}},
                    "unknown_table_ids": {"type": "array",
                                          "items": {"type": "integer"}}}},
        },
    },
    "security": [{"bearer": [], "tenant": []}],
}


def _op(summary, *, body=None, resp=None, params=None):
    doc = {"summary": summary, "responses": {
        "default": {"description": "response", "content": {
            "application/json": {"schema": resp or {"type": "object"}}}}}}
    if body is not None:
        doc["requestBody"] = {"content": {"application/json": {
            "schema": body}}}
    if params:
        doc["parameters"] = params
    return doc


_ID_PARAM = [{"name": "id", "in": "path", "required": True,
              "schema": {"type": "integer"}}]


def _ref(name):
    return {"$ref": f"#/components/schemas/{name}"}


OPENAPI_DOC["paths"] = {
    "/health": {"get": _op("liveness probe")},
    "/metrics": {"get": _op("Prometheus metrics (text exposition)")},
    "/docs": {"get": _op("this spec rendered as HTML (self-contained)")},
    "/v1/tenants": {
        "post": _op("create tenant", body=_ref("Tenant"),
                    resp=_ref("Tenant")),
        "get": _op("list tenants")},
    "/v1/sources": {
        "post": _op("create source (config encrypted at rest)",
                    body=_ref("ConfigResource")),
        "get": _op("list this tenant's sources")},
    "/v1/sources/{id}": {
        "get": _op("get source (secrets masked)", params=_ID_PARAM,
                   resp=_ref("ConfigResource")),
        "put": _op("update source", params=_ID_PARAM),
        "delete": _op("delete source (409 while referenced)",
                      params=_ID_PARAM)},
    "/v1/destinations": {
        "post": _op("create destination (config encrypted at rest)",
                    body=_ref("ConfigResource")),
        "get": _op("list this tenant's destinations")},
    "/v1/destinations/{id}": {
        "get": _op("get destination (secrets masked)", params=_ID_PARAM,
                   resp=_ref("ConfigResource")),
        "put": _op("update destination", params=_ID_PARAM),
        "delete": _op("delete destination (409 while referenced)",
                      params=_ID_PARAM)},
    "/v1/images": {
        "post": _op("register replicator image", body=_ref("Image"),
                    resp=_ref("Image")),
        "get": _op("list replicator images")},
    "/v1/images/{id}": {
        "delete": _op("delete image", params=_ID_PARAM)},
    "/v1/images/{id}/set-default": {
        "post": _op("make this the image new pipelines deploy with",
                    params=_ID_PARAM)},
    "/v1/pipelines": {
        "post": _op("create pipeline", body=_ref("Pipeline")),
        "get": _op("list this tenant's pipelines")},
    "/v1/pipelines/{id}": {
        "get": _op("get pipeline", params=_ID_PARAM, resp=_ref("Pipeline")),
        "delete": _op("stop and delete pipeline", params=_ID_PARAM)},
    "/v1/pipelines/{id}/start": {
        "post": _op("deploy the replicator (202: starting)",
                    params=_ID_PARAM)},
    "/v1/pipelines/{id}/stop": {
        "post": _op("tear down the replicator (202: stopping)",
                    params=_ID_PARAM)},
    "/v1/pipelines/{id}/restart": {
        "post": _op("stop then start", params=_ID_PARAM)},
    "/v1/pipelines/{id}/status": {
        "get": _op("orchestrator state", params=_ID_PARAM,
                   resp=_ref("PipelineStatus"))},
    "/v1/pipelines/{id}/replication-status": {
        "get": _op("table states from the durable store + source slot lag",
                   params=_ID_PARAM, resp=_ref("ReplicationStatus"))},
    "/v1/pipelines/{id}/version": {
        "post": _op("pin the replicator image (or track the tenant "
                    "default when image_id is omitted); rolls out a "
                    "running pipeline", params=_ID_PARAM)},
    "/v1/pipelines/{id}/rollback-tables": {
        "post": _op("reset errored (or listed) tables for resync",
                    params=_ID_PARAM, body=_ref("RollbackRequest"),
                    resp=_ref("RollbackResponse"))},
    "/v1/fleet": {
        "get": _op("aggregated fleet view: desired vs observed shards, "
                   "health + pod degraded reasons, lag per pipeline, "
                   "tenant quotas (docs/fleet.md)")},
}


# self-contained /docs page (reference: utoipa-swagger-ui serving): renders
# /openapi.json client-side with zero external assets
_DOCS_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>etl_tpu API</title><style>
body{font-family:system-ui,sans-serif;margin:2rem auto;max-width:60rem;
     line-height:1.45;color:#1a1a2e}
h1{font-size:1.4rem} .path{margin:.8rem 0;padding:.6rem .8rem;
border:1px solid #d8d8e4;border-radius:6px}
.m{display:inline-block;min-width:4.2rem;font-weight:700;
   text-transform:uppercase;font-size:.8rem}
.m.get{color:#0a7} .m.post{color:#06c} .m.put{color:#a60}
.m.delete{color:#c33} .m.patch{color:#849}
code{background:#f1f1f7;padding:.1rem .3rem;border-radius:3px}
.desc{color:#555;margin-left:4.6rem;font-size:.92rem}
</style></head><body>
<h1>etl_tpu control-plane API</h1>
<p>Spec: <a href="/openapi.json">/openapi.json</a>. Authenticated routes
need <code>Authorization: Bearer &lt;key&gt;</code> and a
<code>tenant_id</code> header.</p>
<div id="paths">loading…</div>
<script>
fetch('/openapi.json').then(r=>r.json()).then(doc=>{
  const el=document.getElementById('paths');el.innerHTML='';
  for(const [p,ops] of Object.entries(doc.paths||{})){
    const d=document.createElement('div');d.className='path';
    for(const [m,op] of Object.entries(ops)){
      const row=document.createElement('div');
      const mm=document.createElement('span');mm.className='m '+m;
      mm.textContent=m;row.appendChild(mm);
      const pc=document.createElement('code');pc.textContent=p;
      row.appendChild(pc);d.appendChild(row);
      const ds=document.createElement('div');ds.className='desc';
      ds.textContent=op.summary||op.description||'';d.appendChild(ds);
    }
    el.appendChild(d);
  }
}).catch(e=>{document.getElementById('paths').textContent=
  'failed to load /openapi.json: '+e});
</script></body></html>"""
