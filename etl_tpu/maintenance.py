"""Lake maintenance binary: `python -m etl_tpu.maintenance`.

Reference parity: crates/etl-maintenance + the etl-ducklake-maintenance
binary (etl-replicator/src/bin/etl-ducklake-maintenance.rs) — external
maintenance (compaction/vacuum) coordinated with live writers through the
catalog maintenance flag, optionally pausing/resuming the pipeline through
the control-plane API around the operation (the reference's
pause-replicator-around-compaction coordination).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from .destinations.lake import LakeConfig, LakeDestination


async def run_maintenance(warehouse: str, *, vacuum: bool,
                          api_url: str | None, pipeline_id: int | None,
                          tenant_id: str | None,
                          api_key: str | None = None,
                          stop_timeout_s: float = 120.0,
                          min_cdc_files: int = 2) -> dict:
    """Operation policy (reference etl-maintenance operation policies): a
    table is compacted only when its current generation holds at least
    `min_cdc_files` CDC files — churning small tables is pure write
    amplification. Every operation lands in the catalog's
    lake_maintenance_history for the --history surface."""
    paused = False
    session = None
    if api_url and pipeline_id is not None:
        import aiohttp

        headers = {"tenant_id": tenant_id or ""}
        if api_key:
            # the control plane's bearer-auth middleware rejects
            # unauthenticated /v1 calls with 401 — coordination against a
            # secured API needs the key on every pause/status/resume call
            headers["Authorization"] = f"Bearer {api_key}"
        session = aiohttp.ClientSession(headers=headers)
    try:
        if session is not None:
            async with session.post(
                    f"{api_url}/v1/pipelines/{pipeline_id}/stop") as resp:
                paused = resp.status in (200, 202)
            if not paused:
                # the operator asked for coordination; running maintenance
                # against a live writer is exactly what they tried to avoid
                raise RuntimeError(
                    f"could not pause pipeline {pipeline_id}: "
                    f"HTTP {resp.status} — aborting maintenance")
            # 202 means 'stopping': the orchestrator deletes the workload
            # but the pod may still be draining — poll until the pipeline
            # reports stopped so compaction never overlaps a live writer
            # (ADVICE r1: pause coordination race). A timeout here still
            # flows through the resume in the finally below — aborted
            # maintenance must not leave replication down.
            deadline = asyncio.get_event_loop().time() + stop_timeout_s
            while True:
                async with session.get(
                        f"{api_url}/v1/pipelines/{pipeline_id}/status") as st:
                    body = await st.json() if st.status == 200 else {}
                if body.get("state") == "stopped":
                    break
                if asyncio.get_event_loop().time() > deadline:
                    raise RuntimeError(
                        f"pipeline {pipeline_id} did not reach 'stopped' "
                        f"within {stop_timeout_s}s "
                        f"(state={body.get('state')!r}) — "
                        f"aborting maintenance")
                await asyncio.sleep(min(0.5, stop_timeout_s / 10))
        lake = LakeDestination(LakeConfig(warehouse))
        await lake.startup()
        table_ids = lake.table_ids()
        compacted = 0
        vacuumed = 0
        skipped_by_policy = 0
        for tid in table_ids:
            if lake.current_cdc_file_count(tid) >= min_cdc_files:
                compacted += await lake.compact(tid)
            else:
                skipped_by_policy += 1
                lake.record_maintenance_skip(tid, "compact")
            if vacuum:
                vacuumed += await lake.vacuum(tid)
        history = lake.maintenance_history(limit=20)
        await lake.shutdown()
        return {"tables": len(table_ids), "compacted_files": compacted,
                "vacuumed_files": vacuumed,
                "skipped_by_policy": skipped_by_policy,
                "paused_pipeline": paused, "history": history}
    finally:
        if session is not None:
            try:
                if paused:
                    async with session.post(
                            f"{api_url}/v1/pipelines/{pipeline_id}/start") \
                            as resp:
                        if resp.status not in (200, 202):
                            import logging

                            logging.getLogger("etl_tpu.maintenance").error(
                                "failed to resume pipeline %s: HTTP %s — "
                                "resume it manually", pipeline_id,
                                resp.status)
            except Exception as e:
                import logging

                logging.getLogger("etl_tpu.maintenance").error(
                    "failed to resume pipeline %s (%r) — resume it "
                    "manually", pipeline_id, e)
            finally:
                await session.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="etl_tpu.maintenance")
    p.add_argument("--warehouse", required=True)
    p.add_argument("--vacuum", action="store_true",
                   help="also delete files from superseded generations")
    p.add_argument("--api-url", default=None,
                   help="control-plane URL: pause/resume the pipeline "
                        "around maintenance")
    p.add_argument("--pipeline-id", type=int, default=None)
    p.add_argument("--tenant-id", default=None)
    p.add_argument("--api-key", default=None,
                   help="bearer token for a secured control plane "
                        "(falls back to $ETL_API_KEY)")
    p.add_argument("--min-cdc-files", type=int, default=2,
                   help="compact a table only when it has >= this many "
                        "CDC files (operation policy)")
    p.add_argument("--history", action="store_true",
                   help="print maintenance history and exit (no ops)")
    p.add_argument("--coordinate", action="store_true",
                   help="run ONE coordinated controller pass through the "
                        "catalog coordination store (operation requests, "
                        "pause lease, history) instead of direct ops")
    p.add_argument("--wait-for-pause", type=float, default=30.0,
                   help="seconds to wait for the replicator to honor the "
                        "pause lease before proceeding (coordinate mode)")
    args = p.parse_args(argv)
    if args.coordinate:
        if args.pipeline_id is None:
            # the coordination row is keyed by pipeline id; defaulting
            # would silently coordinate against a row no replicator reads
            p.error("--coordinate requires --pipeline-id")

        async def coordinate() -> dict:
            from .maintenance_coordination import (CatalogMaintenanceStore,
                                                   MaintenanceController,
                                                   MaintenancePolicy)

            lake = LakeDestination(LakeConfig(args.warehouse))
            await lake.startup()
            store = CatalogMaintenanceStore(args.warehouse,
                                            args.pipeline_id)
            ctrl = MaintenanceController(
                store, lake,
                MaintenancePolicy(merge_min_cdc_files=args.min_cdc_files,
                                  cleanup_old_files_enabled=args.vacuum))
            try:
                return await ctrl.run_once(
                    wait_for_pause_s=args.wait_for_pause)
            finally:
                store.close()
                await lake.shutdown()

        try:
            print(json.dumps(asyncio.run(coordinate())))
            return 0
        except Exception as e:
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}),
                  file=sys.stderr)
            return 1
    if args.history:
        async def show() -> dict:
            lake = LakeDestination(LakeConfig(args.warehouse))
            await lake.startup()
            h = lake.maintenance_history(limit=100)
            await lake.shutdown()
            return {"history": h}

        print(json.dumps(asyncio.run(show())))
        return 0
    try:
        import os

        out = asyncio.run(run_maintenance(
            args.warehouse, vacuum=args.vacuum, api_url=args.api_url,
            pipeline_id=args.pipeline_id, tenant_id=args.tenant_id,
            api_key=args.api_key or os.environ.get("ETL_API_KEY"),
            min_cdc_files=args.min_cdc_files))
    except Exception as e:
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}),
              file=sys.stderr)
        return 1
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
