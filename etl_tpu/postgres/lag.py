"""Replication-lag queries.

Reference parity: crates/etl-postgres/src/lag.rs:14-82 —
`pg_replication_slots` ⟕ `pg_stat_replication` join producing
`SlotLagMetrics{wal_status, restart/confirmed_flush lag bytes,
safe_wal_size, write/flush/replay lag ms}` for the API's
replication-status surface.
"""

from __future__ import annotations

from dataclasses import dataclass

from .wire import PgWireConnection


@dataclass(frozen=True)
class SlotLagMetrics:
    slot_name: str
    active: bool
    wal_status: str  # reserved | extended | unreserved | lost
    restart_lsn_lag_bytes: int
    confirmed_flush_lag_bytes: int
    safe_wal_size_bytes: int | None
    write_lag_ms: float | None
    flush_lag_ms: float | None
    replay_lag_ms: float | None


LAG_QUERY = """
SELECT s.slot_name,
       s.active,
       COALESCE(s.wal_status, 'reserved'),
       pg_current_wal_lsn() - s.restart_lsn,
       pg_current_wal_lsn() - s.confirmed_flush_lsn,
       s.safe_wal_size,
       EXTRACT(EPOCH FROM r.write_lag) * 1000,
       EXTRACT(EPOCH FROM r.flush_lag) * 1000,
       EXTRACT(EPOCH FROM r.replay_lag) * 1000
FROM pg_replication_slots s
LEFT JOIN pg_stat_replication r ON r.pid = s.active_pid
WHERE s.slot_name LIKE 'supabase_etl_%'
""".strip()


def _opt_float(v: str | None) -> float | None:
    return float(v) if v not in (None, "") else None


async def query_slot_lag(conn: PgWireConnection) -> list[SlotLagMetrics]:
    result = await conn.query(LAG_QUERY)
    out = []
    for row in result.rows:
        out.append(SlotLagMetrics(
            slot_name=row[0],
            active=row[1] == "t",
            wal_status=row[2] or "reserved",
            restart_lsn_lag_bytes=int(row[3] or 0),
            confirmed_flush_lag_bytes=int(row[4] or 0),
            safe_wal_size_bytes=int(row[5]) if row[5] not in (None, "")
            else None,
            write_lag_ms=_opt_float(row[6]),
            flush_lag_ms=_opt_float(row[7]),
            replay_lag_ms=_opt_float(row[8])))
    return out
