"""Source-database migrations: the DDL event trigger installation.

Reference parity: `run_source_migrations` (crates/etl/src/pipeline.rs:153-164
+ postgres/migrations.rs:102-122) installing
`migrations/source/20260415100000_schema_change_messages.up.sql` — an
`etl` schema with catalog-snapshot functions and a
`supabase_etl_ddl_message_trigger` event trigger that emits one
`pg_logical_emit_message('supabase_etl_ddl', json)` per changed replicated
table on ALTER TABLE, so schema changes flow through the WAL in commit
order with the data they precede.

Behavior matched:
  - skippable via `PipelineConfig.run_source_migrations=False`;
  - skipped (not errored) on standbys — a read replica cannot run DDL,
    and the primary's migrations replicate down anyway;
  - idempotent: applied migration names are recorded in
    `etl.source_migrations` and re-runs are no-ops.

The JSON payload matches `codec/event.decode_schema_change`:
`{"table_id": oid, "dropped": bool, "schema": {"id", "schema", "name",
"columns": [{"name", "type_oid", "modifier", "nullable",
"primary_key_ordinal", "default_expression"}...]}}`.
"""

from __future__ import annotations

import logging

from .codec.event import DDL_MESSAGE_PREFIX  # noqa: F401 (re-export)
from .source import ReplicationSource

logger = logging.getLogger("etl_tpu.migrations")

# One entry per migration, applied in order; names are recorded in
# etl.source_migrations for idempotency.
SOURCE_MIGRATIONS: list[tuple[str, str]] = [
    ("20260415100000_schema_change_messages", r"""
CREATE SCHEMA IF NOT EXISTS etl;

CREATE TABLE IF NOT EXISTS etl.source_migrations (
    name text PRIMARY KEY,
    applied_at timestamptz NOT NULL DEFAULT now()
);

-- Catalog snapshot of one table as the decoder's JSON schema shape.
CREATE OR REPLACE FUNCTION etl.describe_table_schema(rel oid)
RETURNS jsonb LANGUAGE sql STABLE AS $fn$
    SELECT jsonb_build_object(
        'id', c.oid::bigint,
        'schema', n.nspname,
        'name', c.relname,
        'columns', COALESCE((
            SELECT jsonb_agg(jsonb_build_object(
                'name', a.attname,
                'type_oid', a.atttypid::bigint,
                'modifier', a.atttypmod,
                'nullable', NOT a.attnotnull,
                'primary_key_ordinal', pk.ordinal,
                'default_expression', pg_get_expr(d.adbin, d.adrelid)
            ) ORDER BY a.attnum)
            FROM pg_attribute a
            LEFT JOIN pg_attrdef d
                ON d.adrelid = a.attrelid AND d.adnum = a.attnum
            LEFT JOIN LATERAL (
                SELECT array_position(i.indkey::int2[], a.attnum) AS ordinal
                FROM pg_index i
                WHERE i.indrelid = a.attrelid AND i.indisprimary
            ) pk ON true
            WHERE a.attrelid = c.oid AND a.attnum > 0 AND NOT a.attisdropped
        ), '[]'::jsonb)
    )
    FROM pg_class c
    JOIN pg_namespace n ON n.oid = c.relnamespace
    WHERE c.oid = rel
$fn$;

-- Event trigger: one logical message per ALTERed table that belongs to
-- any publication (replicated tables are the only consumers).
CREATE OR REPLACE FUNCTION etl.emit_schema_change_messages()
RETURNS event_trigger LANGUAGE plpgsql AS $fn$
DECLARE
    cmd record;
BEGIN
    FOR cmd IN SELECT * FROM pg_event_trigger_ddl_commands() LOOP
        IF cmd.object_type IN ('table', 'table column')
           AND EXISTS (SELECT 1 FROM pg_publication_rel pr
                       WHERE pr.prrelid = cmd.objid) THEN
            PERFORM pg_logical_emit_message(
                true, 'supabase_etl_ddl',
                jsonb_build_object(
                    'table_id', cmd.objid::bigint,
                    'dropped', false,
                    'schema', etl.describe_table_schema(cmd.objid)
                )::text);
        END IF;
    END LOOP;
END
$fn$;

CREATE OR REPLACE FUNCTION etl.emit_table_drop_messages()
RETURNS event_trigger LANGUAGE plpgsql AS $fn$
DECLARE
    obj record;
BEGIN
    FOR obj IN SELECT * FROM pg_event_trigger_dropped_objects() LOOP
        IF obj.object_type = 'table' THEN
            PERFORM pg_logical_emit_message(
                true, 'supabase_etl_ddl',
                jsonb_build_object(
                    'table_id', obj.objid::bigint,
                    'dropped', true)::text);
        END IF;
    END LOOP;
END
$fn$;

DO $do$
BEGIN
    IF NOT EXISTS (SELECT 1 FROM pg_event_trigger
                   WHERE evtname = 'supabase_etl_ddl_message_trigger') THEN
        CREATE EVENT TRIGGER supabase_etl_ddl_message_trigger
            ON ddl_command_end
            WHEN TAG IN ('ALTER TABLE')
            EXECUTE FUNCTION etl.emit_schema_change_messages();
    END IF;
    IF NOT EXISTS (SELECT 1 FROM pg_event_trigger
                   WHERE evtname = 'supabase_etl_ddl_drop_trigger') THEN
        CREATE EVENT TRIGGER supabase_etl_ddl_drop_trigger
            ON sql_drop
            WHEN TAG IN ('DROP TABLE')
            EXECUTE FUNCTION etl.emit_table_drop_messages();
    END IF;
END
$do$;
"""),
]


async def run_source_migrations(source: ReplicationSource) -> bool:
    """Install/refresh the source-side DDL trigger machinery. Returns True
    when migrations ran, False when skipped (standby). Mirrors
    pipeline.rs:153-164 + postgres/migrations.rs:102-122."""
    if await source.is_in_recovery():
        logger.info("source is a standby; skipping source migrations "
                    "(they replicate from the primary)")
        return False
    applied = set(await source.applied_source_migrations())
    for name, sql in SOURCE_MIGRATIONS:
        if name in applied:
            continue
        await source.apply_source_migration(name, sql)
        logger.info("applied source migration %s", name)
    return True
