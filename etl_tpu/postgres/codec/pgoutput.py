"""pgoutput logical-streaming protocol: binary message decode + encode.

Decode is the production path (reference: crates/etl/src/postgres/codec/
event.rs message framing + the postgres-replication crate's protocol types).
Encode exists for tests and the in-process fake walsender — the same
differential strategy the reference gets from a real Postgres (SURVEY §4.4),
applied at the protocol layer.

Message formats follow the Postgres docs "Logical Streaming Replication
Protocol" (protocol version 1-2). Also includes the outer replication copy
stream framing: XLogData ('w'), Primary keepalive ('k'), Standby status
update ('r').

PG timestamps on the wire are microseconds since 2000-01-01; all decoded
times here are unix microseconds.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Iterator

from ...models.errors import ErrorKind, EtlError
from ...models.lsn import Lsn

PG_EPOCH_OFFSET_US = 946_684_800_000_000  # 2000-01-01 − 1970-01-01 in µs


def pg_time_to_unix_us(pg_us: int) -> int:
    return pg_us + PG_EPOCH_OFFSET_US


def unix_us_to_pg_time(unix_us: int) -> int:
    return unix_us - PG_EPOCH_OFFSET_US


class ByteReader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _take(self, n: int) -> bytes:
        if n < 0:
            raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                           f"negative length {n} at {self.pos}")
        if self.pos + n > len(self.buf):
            raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                           f"truncated message: need {n} bytes at {self.pos}, "
                           f"have {len(self.buf) - self.pos}")
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def u16(self) -> int:
        return struct.unpack(">H", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def u64(self) -> int:
        return struct.unpack(">Q", self._take(8))[0]

    def bytes(self, n: int) -> bytes:
        return self._take(n)

    def cstr(self) -> str:
        end = self.buf.find(b"\x00", self.pos)
        if end < 0:
            raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                           "unterminated cstring")
        out = self.buf[self.pos : end].decode("utf-8")
        self.pos = end + 1
        return out

    def remaining(self) -> int:
        return len(self.buf) - self.pos


# ---------------------------------------------------------------------------
# Tuple data
# ---------------------------------------------------------------------------

# per-column kinds inside TupleData
TUPLE_NULL = ord("n")
TUPLE_UNCHANGED_TOAST = ord("u")
TUPLE_TEXT = ord("t")
TUPLE_BINARY = ord("b")


@dataclass(slots=True)
class TupleData:
    """Raw tuple: per-column (kind, payload). Payload is None for
    null/unchanged, raw bytes for text/binary columns."""

    kinds: list[int]
    values: list[bytes | None]

    def __len__(self) -> int:
        return len(self.kinds)


def read_tuple_data(r: ByteReader) -> TupleData:
    ncols = r.i16()
    kinds: list[int] = []
    values: list[bytes | None] = []
    for _ in range(ncols):
        kind = r.u8()
        kinds.append(kind)
        if kind in (TUPLE_NULL, TUPLE_UNCHANGED_TOAST):
            values.append(None)
        elif kind in (TUPLE_TEXT, TUPLE_BINARY):
            ln = r.i32()
            values.append(r.bytes(ln))
        else:
            raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                           f"unknown tuple column kind {kind!r}")
    return TupleData(kinds, values)


def write_tuple_data(values: list[bytes | None], kinds: list[int] | None = None) -> bytes:
    out = bytearray(struct.pack(">h", len(values)))
    for i, v in enumerate(values):
        kind = kinds[i] if kinds else (TUPLE_NULL if v is None else TUPLE_TEXT)
        out.append(kind)
        if kind in (TUPLE_TEXT, TUPLE_BINARY):
            assert v is not None
            out += struct.pack(">i", len(v))
            out += v
    return bytes(out)


# ---------------------------------------------------------------------------
# Logical replication messages (inside XLogData payloads)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class BeginMessage:
    final_lsn: Lsn
    timestamp_us: int  # unix µs
    xid: int


@dataclass(slots=True)
class CommitMessage:
    flags: int
    commit_lsn: Lsn
    end_lsn: Lsn
    timestamp_us: int


@dataclass(slots=True)
class OriginMessage:
    commit_lsn: Lsn
    name: str


@dataclass(slots=True)
class RelationColumn:
    flags: int  # bit 0: part of replica identity key
    name: str
    type_oid: int
    modifier: int

    @property
    def is_key(self) -> bool:
        return bool(self.flags & 1)


@dataclass(slots=True)
class RelationMessage:
    relation_id: int
    namespace: str
    relation_name: str
    replica_identity: int  # b'd'efault / b'n'othing / b'f'ull / b'i'ndex
    columns: list[RelationColumn]


@dataclass(slots=True)
class TypeMessage:
    type_oid: int
    namespace: str
    name: str


@dataclass(slots=True)
class InsertMessage:
    relation_id: int
    new_tuple: TupleData


@dataclass(slots=True)
class UpdateMessage:
    relation_id: int
    old_tuple: TupleData | None  # from 'O' (old full tuple, replica identity full)
    key_tuple: TupleData | None  # from 'K' (key columns only)
    new_tuple: TupleData


@dataclass(slots=True)
class DeleteMessage:
    relation_id: int
    old_tuple: TupleData | None
    key_tuple: TupleData | None


@dataclass(slots=True)
class TruncateMessage:
    options: int  # 1 = CASCADE, 2 = RESTART IDENTITY
    relation_ids: list[int]


@dataclass(slots=True)
class LogicalMessage:
    """'M' — pg_logical_emit_message content (DDL messages ride on this;
    reference apply.rs:2160-2277)."""

    flags: int  # 1 = transactional
    lsn: Lsn
    prefix: str
    content: bytes


LogicalReplicationMessage = (
    BeginMessage | CommitMessage | OriginMessage | RelationMessage
    | TypeMessage | InsertMessage | UpdateMessage | DeleteMessage
    | TruncateMessage | LogicalMessage
)


def decode_logical_message(payload: bytes) -> LogicalReplicationMessage:
    r = ByteReader(payload)
    tag = r.u8()
    if tag == ord("B"):
        return BeginMessage(Lsn(r.u64()), pg_time_to_unix_us(r.i64()), r.u32())
    if tag == ord("C"):
        flags = r.u8()
        return CommitMessage(flags, Lsn(r.u64()), Lsn(r.u64()),
                             pg_time_to_unix_us(r.i64()))
    if tag == ord("O"):
        return OriginMessage(Lsn(r.u64()), r.cstr())
    if tag == ord("R"):
        rel_id = r.u32()
        ns = r.cstr()
        name = r.cstr()
        ident = r.u8()
        ncols = r.i16()
        cols = [RelationColumn(r.u8(), r.cstr(), r.u32(), r.i32())
                for _ in range(ncols)]
        return RelationMessage(rel_id, ns, name, ident, cols)
    if tag == ord("Y"):
        return TypeMessage(r.u32(), r.cstr(), r.cstr())
    if tag == ord("I"):
        rel_id = r.u32()
        marker = r.u8()
        if marker != ord("N"):
            raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                           f"insert tuple marker {marker!r}")
        return InsertMessage(rel_id, read_tuple_data(r))
    if tag == ord("U"):
        rel_id = r.u32()
        old_t = key_t = None
        marker = r.u8()
        if marker == ord("O"):
            old_t = read_tuple_data(r)
            marker = r.u8()
        elif marker == ord("K"):
            key_t = read_tuple_data(r)
            marker = r.u8()
        if marker != ord("N"):
            raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                           f"update new-tuple marker {marker!r}")
        return UpdateMessage(rel_id, old_t, key_t, read_tuple_data(r))
    if tag == ord("D"):
        rel_id = r.u32()
        marker = r.u8()
        old_t = key_t = None
        if marker == ord("O"):
            old_t = read_tuple_data(r)
        elif marker == ord("K"):
            key_t = read_tuple_data(r)
        else:
            raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                           f"delete tuple marker {marker!r}")
        return DeleteMessage(rel_id, old_t, key_t)
    if tag == ord("T"):
        n = r.i32()
        options = r.u8()
        return TruncateMessage(options, [r.u32() for _ in range(n)])
    if tag == ord("M"):
        flags = r.u8()
        lsn = Lsn(r.u64())
        prefix = r.cstr()
        ln = r.i32()
        return LogicalMessage(flags, lsn, prefix, r.bytes(ln))
    raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                   f"unknown pgoutput message tag {chr(tag)!r}")


# --- encoders (tests / fake walsender) -------------------------------------


def encode_begin(final_lsn: int, timestamp_us: int, xid: int) -> bytes:
    return b"B" + struct.pack(">QqI", final_lsn, unix_us_to_pg_time(timestamp_us), xid)


def encode_commit(commit_lsn: int, end_lsn: int, timestamp_us: int, flags: int = 0) -> bytes:
    return b"C" + struct.pack(">BQQq", flags, commit_lsn, end_lsn,
                              unix_us_to_pg_time(timestamp_us))


def encode_relation(relation_id: int, namespace: str, name: str,
                    columns: list[tuple[int, str, int, int]],
                    replica_identity: int = ord("d")) -> bytes:
    out = bytearray(b"R")
    out += struct.pack(">I", relation_id)
    out += namespace.encode() + b"\x00" + name.encode() + b"\x00"
    out += struct.pack(">Bh", replica_identity, len(columns))
    for flags, cname, oid, mod in columns:
        out += struct.pack(">B", flags) + cname.encode() + b"\x00"
        out += struct.pack(">Ii", oid, mod)
    return bytes(out)


def encode_insert(relation_id: int, values: list[bytes | None],
                  kinds: list[int] | None = None) -> bytes:
    return (b"I" + struct.pack(">I", relation_id) + b"N"
            + write_tuple_data(values, kinds))


def encode_update(relation_id: int, new_values: list[bytes | None],
                  old_values: list[bytes | None] | None = None,
                  key_values: list[bytes | None] | None = None,
                  new_kinds: list[int] | None = None) -> bytes:
    out = bytearray(b"U")
    out += struct.pack(">I", relation_id)
    if old_values is not None:
        out += b"O" + write_tuple_data(old_values)
    elif key_values is not None:
        out += b"K" + write_tuple_data(key_values)
    out += b"N" + write_tuple_data(new_values, new_kinds)
    return bytes(out)


def encode_delete(relation_id: int, key_values: list[bytes | None],
                  full_old: bool = False) -> bytes:
    marker = b"O" if full_old else b"K"
    return (b"D" + struct.pack(">I", relation_id) + marker
            + write_tuple_data(key_values))


def encode_truncate(relation_ids: list[int], options: int = 0) -> bytes:
    return (b"T" + struct.pack(">iB", len(relation_ids), options)
            + b"".join(struct.pack(">I", rid) for rid in relation_ids))


def encode_logical_message(prefix: str, content: bytes, lsn: int = 0,
                           transactional: bool = True) -> bytes:
    return (b"M" + struct.pack(">BQ", 1 if transactional else 0, lsn)
            + prefix.encode() + b"\x00" + struct.pack(">i", len(content)) + content)


# ---------------------------------------------------------------------------
# Replication copy-stream framing (outer layer, inside CopyData)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class XLogData:
    start_lsn: Lsn  # WAL position of this payload
    end_lsn: Lsn  # current end of WAL on server
    clock_us: int  # server clock, unix µs
    payload: bytes  # a logical replication message


@dataclass(slots=True)
class PrimaryKeepalive:
    end_lsn: Lsn
    clock_us: int
    reply_requested: bool


ReplicationFrame = XLogData | PrimaryKeepalive


def decode_replication_frame(data: bytes) -> ReplicationFrame:
    r = ByteReader(data)
    tag = r.u8()
    if tag == ord("w"):
        start = Lsn(r.u64())
        end = Lsn(r.u64())
        clock = pg_time_to_unix_us(r.i64())
        return XLogData(start, end, clock, data[r.pos:])
    if tag == ord("k"):
        return PrimaryKeepalive(Lsn(r.u64()), pg_time_to_unix_us(r.i64()),
                                bool(r.u8()))
    raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                   f"unknown replication frame tag {chr(tag)!r}")


def encode_xlog_data(start_lsn: int, end_lsn: int, clock_us: int,
                     payload: bytes) -> bytes:
    return b"w" + struct.pack(">QQq", start_lsn, end_lsn,
                              unix_us_to_pg_time(clock_us)) + payload


def encode_primary_keepalive(end_lsn: int, clock_us: int,
                             reply_requested: bool = False) -> bytes:
    return b"k" + struct.pack(">Qq?", end_lsn, unix_us_to_pg_time(clock_us),
                              reply_requested)


def encode_standby_status_update(written: int, flushed: int, applied: int,
                                 clock_us: int, reply_requested: bool = False) -> bytes:
    """'r' frame the client sends: ack/flow-control channel (reference:
    stream/replication_message.rs:111)."""
    return b"r" + struct.pack(">QQQq?", written, flushed, applied,
                              unix_us_to_pg_time(clock_us), reply_requested)


@dataclass(slots=True)
class StandbyStatusUpdate:
    written: Lsn
    flushed: Lsn
    applied: Lsn
    clock_us: int
    reply_requested: bool


def decode_standby_status_update(data: bytes) -> StandbyStatusUpdate:
    r = ByteReader(data)
    tag = r.u8()
    if tag != ord("r"):
        raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                       f"expected standby status update, got {chr(tag)!r}")
    return StandbyStatusUpdate(Lsn(r.u64()), Lsn(r.u64()), Lsn(r.u64()),
                               pg_time_to_unix_us(r.i64()), bool(r.u8()))
