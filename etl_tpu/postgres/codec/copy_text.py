"""COPY text-format row decode: one COPY line → field texts → TableRow.

Reference parity: `parse_table_row_from_postgres_copy_bytes`
(crates/etl/src/postgres/codec/table_row.rs:13-53).

Format invariant this exploits (same one the reference's memchr3 scan does):
in COPY text format a literal TAB/NEWLINE inside a value is always escaped
(`\\t`, `\\n`), so raw 0x09 bytes are exclusively field delimiters and raw
0x0A bytes exclusively row terminators. Field split is therefore a plain
`split(b"\\t")`; escape resolution runs per-field only when a backslash is
present. Batch-level vectorized scanning for the device path lives in
etl_tpu/ops/staging.py.
"""

from __future__ import annotations

from typing import Any, Sequence

from ...models.errors import ErrorKind, EtlError
from ...models.table_row import TableRow
from .text import parse_cell_text

NULL_FIELD = b"\\N"

_SIMPLE_ESCAPES = {
    ord("b"): 0x08, ord("f"): 0x0C, ord("n"): 0x0A, ord("r"): 0x0D,
    ord("t"): 0x09, ord("v"): 0x0B,
}
_HEX = b"0123456789abcdefABCDEF"


def unescape_copy_field(raw: bytes) -> bytes:
    """Resolve COPY text escapes in one field's raw bytes."""
    if b"\\" not in raw:
        return raw
    out = bytearray()
    i, n = 0, len(raw)
    while i < n:
        c = raw[i]
        if c != 0x5C:
            out.append(c)
            i += 1
            continue
        i += 1
        if i >= n:
            raise EtlError(ErrorKind.COPY_FORMAT_INVALID,
                           "dangling backslash in COPY field")
        e = raw[i]
        if e in _SIMPLE_ESCAPES:
            out.append(_SIMPLE_ESCAPES[e])
            i += 1
        elif e == 0x5C:
            out.append(0x5C)
            i += 1
        elif ord("0") <= e <= ord("7"):
            val = e - ord("0")
            i += 1
            for _ in range(2):
                if i < n and ord("0") <= raw[i] <= ord("7"):
                    val = (val << 3) | (raw[i] - ord("0"))
                    i += 1
            out.append(val & 0xFF)
        elif e == ord("x") and i + 1 < n and raw[i + 1] in _HEX:
            i += 1
            val = int(chr(raw[i]), 16)
            i += 1
            if i < n and raw[i] in _HEX:
                val = (val << 4) | int(chr(raw[i]), 16)
                i += 1
            out.append(val)
        else:
            # COPY FROM drops the backslash before any other character
            out.append(e)
            i += 1
    return bytes(out)


def split_copy_line(line: bytes) -> list[bytes | None]:
    """Split one COPY text line (no trailing newline) into unescaped field
    bytes; None = NULL (`\\N`)."""
    fields = line.split(b"\t")
    if b"\\" not in line:  # fast path: no NULLs, no escapes
        return fields  # type: ignore[return-value]
    return [None if f == NULL_FIELD else unescape_copy_field(f) for f in fields]


def parse_copy_row(line: bytes, type_oids: Sequence[int]) -> TableRow:
    """One COPY text line → typed TableRow against the given column OIDs."""
    fields = split_copy_line(line)
    if len(fields) != len(type_oids):
        raise EtlError(
            ErrorKind.COPY_FORMAT_INVALID,
            f"COPY row has {len(fields)} fields, schema expects {len(type_oids)}")
    values: list[Any] = []
    for raw, oid in zip(fields, type_oids):
        if raw is None:
            values.append(None)
        else:
            values.append(parse_cell_text(raw.decode("utf-8"), oid))
    return TableRow(values)


def parse_copy_chunk_columns(chunk: bytes, type_oids: Sequence[int]):
    """COPY text chunk → per-COLUMN typed value lists + row count (the
    columnar form of `parse_copy_row` over every line): the CPU-engine
    copy path feeds these straight into `ColumnarBatch.from_cells`,
    skipping the TableRow materialization + from_rows re-transpose that
    used to sit between the parse and the destination write
    (runtime/copy.py:177 row round-trip)."""
    n_cols = len(type_oids)
    cells: list[list[Any]] = [[] for _ in range(n_cols)]
    n = 0
    for line in chunk.split(b"\n"):
        if not line:
            continue
        fields = split_copy_line(line)
        if len(fields) != n_cols:
            raise EtlError(
                ErrorKind.COPY_FORMAT_INVALID,
                f"COPY row has {len(fields)} fields, schema expects {n_cols}")
        for j, (raw, oid) in enumerate(zip(fields, type_oids)):
            cells[j].append(
                None if raw is None
                else parse_cell_text(raw.decode("utf-8"), oid))
        n += 1
    return cells, n


def encode_copy_field(text: str | None) -> bytes:
    if text is None:
        return NULL_FIELD
    b = text.encode("utf-8")
    return (b.replace(b"\\", b"\\\\").replace(b"\t", b"\\t")
             .replace(b"\n", b"\\n").replace(b"\r", b"\\r")
             .replace(b"\x08", b"\\b").replace(b"\x0c", b"\\f")
             .replace(b"\x0b", b"\\v"))


def encode_copy_row(texts: Sequence[str | None]) -> bytes:
    """Encode pre-rendered field texts into one COPY text line (test/fixture
    helper — the framework never writes COPY, only reads it)."""
    return b"\t".join(encode_copy_field(t) for t in texts)
