"""pgoutput message + schema → typed Event decode (the CPU hot loop).

Reference parity: `parse_event_from_{begin,commit,insert,update,delete,
truncate}_message` (crates/etl/src/postgres/codec/event.rs, 1696 LoC):
old/new tuple merge by identity mask, TOAST-unchanged handling, DDL
`SchemaChangeMessage` JSON parse.

The TPU path replaces `decode_insert/update/delete` per-row text parsing
with batched device decode (etl_tpu/ops) — this module remains the oracle
and the fallback for rows the kernels cannot handle.
"""

from __future__ import annotations

import json
from typing import Any

from ...models.cell import TOAST_UNCHANGED
from ...models.errors import ErrorKind, EtlError
from ...models.event import (BeginEvent, CommitEvent, DeleteEvent,
                             InsertEvent, RelationEvent, SchemaChangeEvent,
                             TruncateEvent, UpdateEvent)
from ...models.lsn import Lsn
from ...models.schema import (ColumnMask, ColumnSchema, ReplicatedTableSchema,
                              TableName, TableSchema)
from ...models.table_row import PartialTableRow, TableRow
from .pgoutput import (TUPLE_BINARY, TUPLE_NULL, TUPLE_TEXT,
                       TUPLE_UNCHANGED_TOAST, BeginMessage, CommitMessage,
                       DeleteMessage, InsertMessage, LogicalMessage,
                       RelationMessage, TruncateMessage, TupleData,
                       UpdateMessage)
from .text import parse_cell_text

# prefix used by the source DDL event trigger (reference:
# migrations/source/20260415100000_schema_change_messages.up.sql)
DDL_MESSAGE_PREFIX = "supabase_etl_ddl"


def schema_from_relation_message(msg: RelationMessage) -> ReplicatedTableSchema:
    """Build the positional decode view from a RELATION message. pgoutput
    lists only replicated columns, in table order (ordering rationale:
    reference apply.rs:2386-2394), so the decode schema has exactly those
    columns and a full-set replication mask; identity bits come from the
    per-column key flag."""
    columns = tuple(
        ColumnSchema(
            name=c.name,
            type_oid=c.type_oid,
            modifier=c.modifier,
            nullable=not c.is_key,
            primary_key_ordinal=(i + 1) if c.is_key else None,
        )
        for i, c in enumerate(msg.columns)
    )
    schema = TableSchema(
        id=msg.relation_id,
        name=TableName(msg.namespace, msg.relation_name),
        columns=columns,
    )
    n = len(columns)
    identity = ColumnMask(c.is_key for c in msg.columns)
    if identity.count() == 0 and msg.replica_identity == ord("f"):
        identity = ColumnMask.all_set(n)
    return ReplicatedTableSchema(schema, ColumnMask.all_set(n), identity)


def _decode_tuple_values(tup: TupleData,
                         schema: ReplicatedTableSchema) -> list[Any]:
    cols = schema.replicated_columns
    if len(tup) != len(cols):
        raise EtlError(
            ErrorKind.SCHEMA_MISMATCH,
            f"tuple has {len(tup)} columns, schema {schema.name} expects {len(cols)}")
    values: list[Any] = []
    for kind, raw, col in zip(tup.kinds, tup.values, cols):
        if kind == TUPLE_NULL:
            values.append(None)
        elif kind == TUPLE_UNCHANGED_TOAST:
            values.append(TOAST_UNCHANGED)
        elif kind == TUPLE_TEXT:
            assert raw is not None
            values.append(parse_cell_text(raw.decode("utf-8"), col.type_oid))
        elif kind == TUPLE_BINARY:
            raise EtlError(ErrorKind.UNSUPPORTED_TYPE,
                           "binary tuple format not enabled in START_REPLICATION")
        else:  # unreachable: read_tuple_data validates kinds
            raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                           f"tuple kind {kind}")
    return values


def decode_begin(msg: BeginMessage, start_lsn: Lsn) -> BeginEvent:
    return BeginEvent(start_lsn=start_lsn, commit_lsn=msg.final_lsn,
                      timestamp_us=msg.timestamp_us, xid=msg.xid)


def decode_commit(msg: CommitMessage, start_lsn: Lsn) -> CommitEvent:
    return CommitEvent(start_lsn=start_lsn, commit_lsn=msg.commit_lsn,
                       end_lsn=msg.end_lsn, timestamp_us=msg.timestamp_us,
                       flags=msg.flags)


def decode_insert(msg: InsertMessage, schema: ReplicatedTableSchema,
                  start_lsn: Lsn, commit_lsn: Lsn, tx_ordinal: int) -> InsertEvent:
    row = TableRow(_decode_tuple_values(msg.new_tuple, schema))
    return InsertEvent(start_lsn, commit_lsn, tx_ordinal, schema, row)


def _old_row(tup: TupleData | None, key: TupleData | None,
             schema: ReplicatedTableSchema) -> PartialTableRow | TableRow | None:
    if tup is not None:  # 'O': full old tuple (replica identity full)
        return TableRow(_decode_tuple_values(tup, schema))
    if key is not None:  # 'K': identity columns populated, rest null
        values = _decode_tuple_values(key, schema)
        identity = schema.identity_mask
        idx = schema.replicated_indices
        present = [identity[idx[i]] for i in range(len(values))]
        return PartialTableRow(values, present)
    return None


def decode_update(msg: UpdateMessage, schema: ReplicatedTableSchema,
                  start_lsn: Lsn, commit_lsn: Lsn, tx_ordinal: int) -> UpdateEvent:
    new_values = _decode_tuple_values(msg.new_tuple, schema)
    old = _old_row(msg.old_tuple, msg.key_tuple, schema)
    # TOAST-unchanged merge: fill unchanged columns from the full old tuple
    # when the server sent one (reference codec/event.rs merge semantics)
    if isinstance(old, TableRow) and not isinstance(old, PartialTableRow):
        for i, v in enumerate(new_values):
            if v is TOAST_UNCHANGED:
                new_values[i] = old.values[i]
    return UpdateEvent(start_lsn, commit_lsn, tx_ordinal, schema,
                       TableRow(new_values), old)


def decode_delete(msg: DeleteMessage, schema: ReplicatedTableSchema,
                  start_lsn: Lsn, commit_lsn: Lsn, tx_ordinal: int) -> DeleteEvent:
    old = _old_row(msg.old_tuple, msg.key_tuple, schema)
    if old is None:
        raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                       "DELETE without old or key tuple")
    return DeleteEvent(start_lsn, commit_lsn, tx_ordinal, schema, old)


def decode_truncate(msg: TruncateMessage,
                    schemas: list[ReplicatedTableSchema], start_lsn: Lsn,
                    commit_lsn: Lsn, tx_ordinal: int) -> TruncateEvent:
    return TruncateEvent(start_lsn, commit_lsn, tx_ordinal, msg.options,
                         tuple(schemas))


def decode_schema_change(msg: LogicalMessage, start_lsn: Lsn,
                         commit_lsn: Lsn) -> SchemaChangeEvent:
    """Parse the DDL trigger's JSON payload (reference apply.rs:2160-2277).

    Payload shape: {"table_id": oid, "dropped": bool, "schema": {...}} where
    schema is the TableSchema JSON emitted by etl.describe_table_schema."""
    if msg.prefix != DDL_MESSAGE_PREFIX:
        raise EtlError(ErrorKind.REPLICATION_MESSAGE_INVALID,
                       f"unexpected logical message prefix {msg.prefix!r}")
    try:
        doc = json.loads(msg.content.decode("utf-8"))
        table_id = doc["table_id"]
        if doc.get("dropped"):
            return SchemaChangeEvent(start_lsn, commit_lsn, table_id, None)
        schema = TableSchema.from_json(doc["schema"])
    except (KeyError, ValueError, json.JSONDecodeError) as e:
        raise EtlError(ErrorKind.SCHEMA_SNAPSHOT_INVALID,
                       f"malformed DDL message: {e}")
    return SchemaChangeEvent(start_lsn, commit_lsn, table_id,
                             ReplicatedTableSchema.with_all_columns(schema))


def encode_schema_change(table_id: int, schema: TableSchema | None) -> bytes:
    """Test/fixture helper: the JSON the source event trigger would emit."""
    if schema is None:
        doc: dict[str, Any] = {"table_id": table_id, "dropped": True}
    else:
        doc = {"table_id": table_id, "dropped": False, "schema": schema.to_json()}
    return json.dumps(doc).encode("utf-8")
