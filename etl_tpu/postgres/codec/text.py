"""Text-format value parsing: Postgres text output → typed Python values.

This is the CPU reference decoder and correctness oracle for the TPU decode
kernels. Reference parity: `parse_cell_from_postgres_text`
(crates/etl/src/postgres/codec/text.rs, 1004 LoC), numeric codec
(crates/etl-postgres/src/numeric.rs), time codecs
(crates/etl-postgres/src/time.rs), bytea hex (codec/hex.rs), bool
(codec/bool.rs), array literals (text.rs array parsing).
"""

from __future__ import annotations

import datetime as dt
import json
import uuid as uuid_mod
from typing import Any, Callable

from ...models.cell import (JSON_NULL, PgInterval, PgNumeric,
                            PgSpecialDate, PgSpecialTimestamp, PgTimeTz)
from ...models.errors import ErrorKind, EtlError
from ...models.pgtypes import CellKind, Oid, array_element, kind_for_oid

# Postgres renders infinity dates/timestamps as literals; map them to
# out-of-band sentinels carrying PG's own internal magnitudes (i32::MAX
# days / i64::MAX µs — what the reference's chrono MIN/MAX serialize to).
# Using datetime.max/min here would collide with the GENUINE extreme
# values 9999-12-31 / 0001-01-01T00:00:00, silently dropping their tz
# offsets (datetime.min+15:59:59 would equal the -infinity sentinel).
DATE_POS_INFINITY = PgSpecialDate(2**31 - 1, "infinity")
DATE_NEG_INFINITY = PgSpecialDate(-(2**31), "-infinity")
TS_POS_INFINITY = PgSpecialTimestamp(2**63 - 1, "infinity")
TS_NEG_INFINITY = PgSpecialTimestamp(-(2**63), "-infinity")
TSTZ_POS_INFINITY = PgSpecialTimestamp(2**63 - 1, "infinity", tz_aware=True)
TSTZ_NEG_INFINITY = PgSpecialTimestamp(-(2**63), "-infinity", tz_aware=True)

# exact bounds of Python's datetime range in epoch microseconds
_MIN_TS_US = -62_135_596_800_000_000  # 0001-01-01 00:00:00
_MAX_TS_US = 253_402_300_799_999_999  # 9999-12-31 23:59:59.999999
_EPOCH_NAIVE = dt.datetime(1970, 1, 1)
_EPOCH_AWARE = dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc)
_US_TD = dt.timedelta(microseconds=1)


def _invalid(kind: str, text: str, exc: Exception | None = None) -> EtlError:
    return EtlError(ErrorKind.INVALID_DATA, f"invalid {kind} literal: {text!r}"
                    + (f" ({exc})" if exc else ""))


def parse_bool(text: str) -> bool:
    if text == "t":
        return True
    if text == "f":
        return False
    raise _invalid("bool", text)


def parse_int(text: str) -> int:
    # strict: Python's int() accepts underscores/whitespace which Postgres
    # never emits — the oracle must reject what the device rejects
    body = text[1:] if text[:1] in "+-" else text
    if not body.isdigit():
        raise _invalid("integer", text)
    return int(text)


def parse_float(text: str) -> float:
    if text == "NaN":
        return float("nan")
    if text == "Infinity":
        return float("inf")
    if text == "-Infinity":
        return float("-inf")
    try:
        return float(text)
    except ValueError as e:
        raise _invalid("float", text, e)


def parse_numeric(text: str) -> PgNumeric:
    t = text
    if t == "NaN":
        return PgNumeric("NaN")
    if t in ("Infinity", "inf"):
        return PgNumeric("Infinity")
    if t in ("-Infinity", "-inf"):
        return PgNumeric("-Infinity")
    try:
        return PgNumeric(t)
    except Exception as e:
        raise _invalid("numeric", text, e)


def parse_bytea(text: str) -> bytes:
    if text.startswith("\\x"):
        try:
            return bytes.fromhex(text[2:])
        except ValueError as e:
            raise _invalid("bytea", text, e)
    # legacy escape format: printable bytes verbatim, \\ for backslash,
    # \nnn octal (digits 0-7, value ≤ 255) — anything else is corrupt
    out = bytearray()
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c != "\\":
            if ord(c) > 255:
                raise _invalid("bytea", text)
            out.append(ord(c))
            i += 1
        elif i + 1 < n and text[i + 1] == "\\":
            out.append(0x5C)
            i += 2
        elif i + 3 < n and all(d in "01234567"
                               for d in text[i + 1 : i + 4]):
            v = int(text[i + 1 : i + 4], 8)
            if v > 255:
                raise _invalid("bytea", text)
            out.append(v)
            i += 4
        else:
            raise _invalid("bytea", text)
    return bytes(out)


def days_from_civil(y: int, m: int, d: int) -> int:
    """Proleptic-Gregorian days since 1970-01-01 for any year (Howard
    Hinnant's civil algorithm; handles year <= 0 exactly)."""
    y -= m <= 2
    era = (y if y >= 0 else y - 399) // 400
    yoe = y - era * 400
    doy = (153 * (m + (-3 if m > 2 else 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def parse_date(text: str) -> "dt.date | PgSpecialDate":
    if text == "infinity":
        return DATE_POS_INFINITY
    if text == "-infinity":
        return DATE_NEG_INFINITY
    t, bc = (text[:-3], True) if text.endswith(" BC") else (text, False)
    try:
        y, m, d = t.split("-")
        year, month, day = int(y), int(m), int(d)
        if bc:
            # Postgres year 1 BC = proleptic year 0 — below Python's MINYEAR,
            # so carry the exact day count instead of collapsing the value
            year = 1 - year
            return PgSpecialDate(days_from_civil(year, month, day), text)
        return dt.date(year, month, day)
    except (ValueError, AttributeError, OverflowError) as e:
        raise _invalid("date", text, e)


def _parse_hms(text: str) -> tuple[int, int, int, int]:
    hh, mm, rest = text.split(":")
    if "." in rest:
        ss, frac = rest.split(".")
        us = int(frac.ljust(6, "0")[:6])
    else:
        ss, us = rest, 0
    return int(hh), int(mm), int(ss), us


def parse_time(text: str) -> dt.time:
    try:
        h, m, s, us = _parse_hms(text)
        if h == 24 and m == 0 and s == 0 and us == 0:
            # Postgres allows 24:00:00; clamp to max representable
            return dt.time(23, 59, 59, 999999)
        return dt.time(h, m, s, us)
    except (ValueError, OverflowError) as e:
        raise _invalid("time", text, e)


def _split_tz(text: str) -> tuple[str, int]:
    """Split trailing ±HH[:MM[:SS]] offset; returns (body, offset_seconds)."""
    for i in range(len(text) - 1, max(len(text) - 10, 0), -1):
        c = text[i]
        if c in "+-":
            body, off = text[:i], text[i:]
            sign = 1 if off[0] == "+" else -1
            parts = off[1:].split(":")
            secs = 0
            for p, mult in zip(parts, (3600, 60, 1)):
                secs += int(p) * mult
            if secs > 57599:  # PG bound: ±15:59:59
                raise _invalid("tz offset", text)
            return body, sign * secs
        if c == ":" or c.isdigit() or c == ".":
            continue
        break
    raise _invalid("tz offset", text)


def parse_timetz(text: str) -> PgTimeTz:
    try:
        body, off = _split_tz(text)
        return PgTimeTz(parse_time(body), off)
    except (ValueError, EtlError) as e:
        if isinstance(e, EtlError):
            raise
        raise _invalid("timetz", text, e)


def parse_timestamp(text: str) -> "dt.datetime | PgSpecialTimestamp":
    if text == "infinity":
        return TS_POS_INFINITY
    if text == "-infinity":
        return TS_NEG_INFINITY
    t, bc = (text[:-3], True) if text.endswith(" BC") else (text, False)
    try:
        date_part, _, time_part = t.partition(" ")
        d = parse_date(date_part + (" BC" if bc else ""))
        tm = parse_time(time_part) if time_part else dt.time()
        if isinstance(d, PgSpecialDate):
            tod = ((tm.hour * 60 + tm.minute) * 60 + tm.second) * 1_000_000 \
                + tm.microsecond
            return PgSpecialTimestamp(d.days * 86_400_000_000 + tod, text)
        return dt.datetime.combine(d, tm)
    except (ValueError, OverflowError, EtlError) as e:
        if isinstance(e, EtlError) and "date" not in str(e) and "time" not in str(e):
            raise
        raise _invalid("timestamp", text, e)


def parse_timestamptz(text: str) -> "dt.datetime | PgSpecialTimestamp":
    if text == "infinity":
        return TSTZ_POS_INFINITY
    if text == "-infinity":
        return TSTZ_NEG_INFINITY
    t, bc = (text[:-3], True) if text.endswith(" BC") else (text, False)
    try:
        body, off = _split_tz(t)
        naive = parse_timestamp(body + (" BC" if bc else ""))
        if isinstance(naive, PgSpecialTimestamp):
            return PgSpecialTimestamp(naive.micros - off * 1_000_000, text,
                                      tz_aware=True)
        # integer µs arithmetic, not astimezone(): an offset can push an
        # edge value (0001-01-01+hh / 9999-12-31-hh) outside Python's
        # datetime range — those become out-of-band specials, not errors
        micros = (naive - _EPOCH_NAIVE) // _US_TD - off * 1_000_000
        if _MIN_TS_US <= micros <= _MAX_TS_US:
            return _EPOCH_AWARE + dt.timedelta(microseconds=micros)
        return PgSpecialTimestamp(micros, text, tz_aware=True)
    except (ValueError, OverflowError) as e:
        raise _invalid("timestamptz", text, e)


def parse_uuid(text: str) -> uuid_mod.UUID:
    try:
        return uuid_mod.UUID(text)
    except ValueError as e:
        raise _invalid("uuid", text, e)


def parse_json(text: str) -> Any:
    try:
        v = json.loads(text)
    except json.JSONDecodeError as e:
        raise _invalid("json", text, e)
    return JSON_NULL if v is None else v


_INTERVAL_UNITS = {
    "year": 12, "years": 12, "mon": 1, "mons": 1, "month": 1, "months": 1,
}


def parse_interval(text: str) -> PgInterval:
    """Parse Postgres' default interval output ('X years Y mons Z days
    [-]HH:MM:SS[.ffffff]')."""
    months = days = micros = 0
    tokens = text.split()
    i = 0
    try:
        while i < len(tokens):
            tok = tokens[i]
            if ":" in tok:
                neg = tok.startswith("-")
                h, m, s, us = _parse_hms(tok.lstrip("+-"))
                micros = ((h * 60 + m) * 60 + s) * 1_000_000 + us
                if neg:
                    micros = -micros
                i += 1
            else:
                qty = int(tok)
                unit = tokens[i + 1]
                if unit in _INTERVAL_UNITS:
                    months += qty * _INTERVAL_UNITS[unit]
                elif unit.startswith("day"):
                    days += qty
                elif unit.startswith("week"):
                    days += qty * 7
                else:
                    raise ValueError(f"unknown unit {unit}")
                i += 2
        return PgInterval(months, days, micros)
    except (ValueError, IndexError) as e:
        raise _invalid("interval", text, e)


def parse_array(text: str, elem_oid: int) -> list:
    """Parse a Postgres array literal: `{a,b,NULL,"c,d"}` with optional
    explicit bounds prefix `[l:u]=`. Nested arrays flatten is NOT done —
    nested braces produce nested lists."""
    if "=" in text and text.startswith("["):
        text = text.split("=", 1)[1]
    if not (text.startswith("{") and text.endswith("}")):
        raise _invalid("array", text)

    elem_parser = _parser_for_oid(elem_oid)
    pos = [0]
    s = text

    def parse_items(depth: int) -> list:
        assert s[pos[0]] == "{"
        pos[0] += 1
        items: list = []
        if s[pos[0]] == "}":
            pos[0] += 1
            return items
        while True:
            c = s[pos[0]]
            if c == "{":
                items.append(parse_items(depth + 1))
            elif c == '"':
                pos[0] += 1
                buf = []
                while s[pos[0]] != '"':
                    if s[pos[0]] == "\\":
                        pos[0] += 1
                    buf.append(s[pos[0]])
                    pos[0] += 1
                pos[0] += 1
                items.append(elem_parser("".join(buf)))
            else:
                start = pos[0]
                while s[pos[0]] not in ",}":
                    pos[0] += 1
                raw = s[start : pos[0]]
                items.append(None if raw == "NULL" else elem_parser(raw))
            c = s[pos[0]]
            pos[0] += 1
            if c == "}":
                return items
            if c != ",":
                raise _invalid("array", text)

    try:
        result = parse_items(0)
    except (IndexError, ValueError) as e:
        raise _invalid("array", text, e)
    if pos[0] != len(s):
        raise _invalid("array", text)
    return result


def _identity(text: str) -> str:
    return text


_PARSERS: dict[CellKind, Callable[[str], Any]] = {
    CellKind.BOOL: parse_bool,
    CellKind.STRING: _identity,
    CellKind.I16: parse_int,
    CellKind.I32: parse_int,
    CellKind.U32: parse_int,
    CellKind.I64: parse_int,
    CellKind.F32: parse_float,
    CellKind.F64: parse_float,
    CellKind.NUMERIC: parse_numeric,
    CellKind.DATE: parse_date,
    CellKind.TIME: parse_time,
    CellKind.TIMETZ: parse_timetz,
    CellKind.TIMESTAMP: parse_timestamp,
    CellKind.TIMESTAMPTZ: parse_timestamptz,
    CellKind.UUID: parse_uuid,
    CellKind.JSON: parse_json,
    CellKind.BYTES: parse_bytea,
    CellKind.INTERVAL: parse_interval,
}


def _parser_for_oid(oid: int) -> Callable[[str], Any]:
    kind = kind_for_oid(oid)
    if kind is CellKind.ARRAY:
        elem = array_element(oid)
        assert elem is not None
        elem_oid = elem[0]
        return lambda t: parse_array(t, elem_oid)
    return _PARSERS[kind]


def parse_cell_text(text: str | None, type_oid: int) -> Any:
    """Parse one text-format value for a column of `type_oid`. None stays
    None (NULL). Reference: parse_cell_from_postgres_text (codec/text.rs)."""
    if text is None:
        return None
    return _parser_for_oid(type_oid)(text)
