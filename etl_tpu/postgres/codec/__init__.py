"""CPU reference codecs: text values, COPY rows, pgoutput protocol, events.

These are the correctness oracle for the TPU decode engine (etl_tpu/ops)
and the fallback path for rows/types the device kernels don't handle.
"""

from .copy_text import (encode_copy_row, parse_copy_row, split_copy_line,
                        unescape_copy_field)
from .event import (DDL_MESSAGE_PREFIX, decode_begin, decode_commit,
                    decode_delete, decode_insert, decode_schema_change,
                    decode_truncate, decode_update, encode_schema_change,
                    schema_from_relation_message)
from .pgoutput import (decode_logical_message, decode_replication_frame,
                       decode_standby_status_update, encode_begin,
                       encode_commit, encode_delete, encode_insert,
                       encode_logical_message, encode_primary_keepalive,
                       encode_relation, encode_standby_status_update,
                       encode_truncate, encode_update, encode_xlog_data)
from .text import parse_cell_text
