"""FakeSource: an in-memory walsender with Postgres replication semantics.

Implements ReplicationSource faithfully enough to exercise every runtime
path the reference tests against a real Postgres (SURVEY §4.2): slots with
consistent points, MVCC row snapshots taken at slot creation, publication
row membership, pgoutput-encoded WAL with Begin/Commit/Relation framing,
confirmed_flush advancement from standby status updates, keepalives, slot
invalidation injection, and concurrent streams.

Tests drive it through `FakeDatabase`: create tables, add them to a
publication, and run transactions (`async with db.transaction() as tx`)
whose DML is encoded into real pgoutput bytes — so the entire decode stack
runs in end-to-end tests exactly as in production.
"""

from __future__ import annotations

import asyncio
import copy
import time
from dataclasses import dataclass, field
from typing import AsyncIterator

from ..models.errors import ErrorKind, EtlError
from ..models.lsn import Lsn
from ..models.schema import (ColumnMask, ReplicatedTableSchema, TableId,
                             TableSchema)
from .codec import pgoutput
from .codec.copy_text import encode_copy_row
from .source import (CopyStream, CreatedSlot, ReplicationSource,
                     ReplicationStream, SlotInfo)


def _now_us() -> int:
    return int(time.time() * 1_000_000)


class _ToastUnchanged:
    """Sentinel for an UNCHANGED TOASTED column in FakeTransaction.update:
    the walsender omits such values ('u' tuple kind) when the old image
    isn't being sent — the storage keeps the real value."""

    def __repr__(self) -> str:
        return "FAKE_TOAST_UNCHANGED_VALUE"


TOAST_UNCHANGED_VALUE = _ToastUnchanged()


@dataclass
class FakeTable:
    schema: TableSchema
    rows: list[list[str | None]] = field(default_factory=list)  # text-format
    replica_identity: int = ord("d")
    partition_parent: "TableId | None" = None  # leaf → its partitioned root
    partition_leaves: "list[TableId]" = field(default_factory=list)
    # COPY-text lines cached 1:1 with `rows` (a real walsender renders COPY
    # text server-side — keeping the Python encode off the pipeline's core
    # mirrors that). Maintained on append, dropped on in-place mutation.
    encoded: "list[bytes] | None" = None

    def append_row(self, values: list) -> None:
        self.rows.append(list(values))
        if self.encoded is not None:
            self.encoded.append(encode_copy_row(values))

    def invalidate_encoded(self) -> None:
        self.encoded = None


@dataclass
class _FakeSlot:
    name: str
    consistent_point: Lsn
    confirmed_flush: Lsn
    snapshot_id: str
    invalidated: bool = False
    active: bool = False


class FakeDatabase:
    """Shared source-database state; FakeSource connections attach to it."""

    def __init__(self) -> None:
        self.tables: dict[TableId, FakeTable] = {}
        self.publications: dict[str, list[TableId]] = {}
        # publication column filters: (publication, table) -> column names
        self.column_filters: dict[tuple[str, TableId], list[str]] = {}
        # PG15 row filters: (publication, table) -> predicate over the
        # row's text values (the walsender-side WHERE clause analogue);
        # row_filter_sql carries the textual predicate surfaced through
        # pg_publication_tables.rowfilter for the wire client's COPY
        self.row_filters: dict[tuple[str, TableId], "callable"] = {}
        self.row_filter_sql: dict[tuple[str, TableId], str] = {}
        # True (faithful PG15): the walsender/COPY evaluate row filters at
        # send time. False models the FILTER-OFFLOAD deployment (or a PG14
        # walsender): the server ships every row and the catalog still
        # surfaces the filter SQL, so the client's fused decode filter
        # (ops/predicate.py) is the only thing between excluded rows and
        # the destination — end-state verification then proves the
        # device-side filter, not the fake's
        self.server_row_filtering = True
        # (start_lsn, payload, table_id|None, row_texts|None) — the row
        # metadata lets streams evaluate publication row filters the way
        # the walsender evaluates WHERE clauses at send time
        self.wal: list[tuple[Lsn, bytes, TableId | None,
                             list[str | None] | None]] = []
        self._lsn = 0x1000
        # snapshot id → {table id → (rows, COPY-line cache | None)}
        self.snapshots: dict[
            str, dict[TableId,
                      tuple[list[list[str | None]], list[bytes] | None]]] = {}
        self.slots: dict[str, _FakeSlot] = {}
        self._wal_cond = asyncio.Condition()
        self.active_streams: list["_FakeReplicationStream"] = []
        self._snapshot_seq = 0
        self._relation_sent: set[tuple[int, int]] = set()  # (stream id, table)
        self.is_standby = False  # read replica: pg_is_in_recovery() = true
        self.applied_migrations: list[str] = []
        self.ddl_trigger_installed = False
        self.standbys: list["FakeStandby"] = []  # physical replicas
        # deterministic commit clock: when set, commit timestamps advance
        # from this value instead of reading the wall clock — one
        # (workload, seed) pair then replays a byte-identical WAL stream
        # (workloads/generator.py determinism contract)
        self.clock_us: int | None = None

    def commit_clock_us(self) -> int:
        if self.clock_us is not None:
            self.clock_us += 1_000
            return self.clock_us
        return _now_us()

    # -- test-facing setup ----------------------------------------------------

    def create_table(self, schema: TableSchema,
                     rows: list[list[str | None]] | None = None) -> FakeTable:
        t = FakeTable(schema=schema, rows=list(rows or []),
                      encoded=[encode_copy_row(r) for r in rows or []])
        self.tables[schema.id] = t
        return t

    def create_partitioned_table(
            self, parent: TableSchema,
            leaves: "dict[TableId, tuple[str, list[list[str | None]]]]"
    ) -> FakeTable:
        """Partitioned root + its leaf partitions. `leaves` maps
        leaf_id → (leaf_name, rows); leaves share the parent's columns.
        Publications list the ROOT (publish_via_partition_root): the
        walsender maps leaf row changes to the root relid."""
        p = FakeTable(schema=parent, rows=[], encoded=[])
        p.partition_leaves = list(leaves)
        self.tables[parent.id] = p
        for leaf_id, (leaf_name, rows) in leaves.items():
            leaf = FakeTable(schema=TableSchema(
                leaf_id, type(parent.name)(parent.name.schema, leaf_name),
                parent.columns), rows=list(rows),
                encoded=[encode_copy_row(r) for r in rows])
            leaf.partition_parent = parent.id
            self.tables[leaf_id] = leaf
        return p

    def wal_relid(self, table_id: TableId) -> TableId:
        """publish_via_partition_root mapping: a leaf's WAL changes are
        attributed to the published root."""
        t = self.tables.get(table_id)
        if t is not None and t.partition_parent is not None:
            parent = t.partition_parent
            if any(parent in tids for tids in self.publications.values()):
                return parent
        return table_id

    def set_replica_identity(self, table_id: TableId, identity: str) -> None:
        """'d' (default: PK) or 'f' (full) — ALTER TABLE ... REPLICA IDENTITY."""
        assert identity in ("d", "f"), identity
        self.tables[table_id].replica_identity = ord(identity)

    def create_publication(self, name: str, table_ids: list[TableId],
                           column_filters: dict[TableId, list[str]] | None = None,
                           row_filters: "dict[TableId, callable] | None" = None
                           ) -> None:
        self.publications[name] = list(table_ids)
        for tid, cols in (column_filters or {}).items():
            self.column_filters[(name, tid)] = cols
        for tid, pred in (row_filters or {}).items():
            if isinstance(pred, tuple):
                sql_text, fn = pred
                self.row_filter_sql[(name, tid)] = sql_text
                self.row_filters[(name, tid)] = fn
            else:
                self.row_filters[(name, tid)] = pred

    def next_lsn(self, advance: int = 8) -> Lsn:
        self._lsn += advance
        return Lsn(self._lsn)

    @property
    def current_lsn(self) -> Lsn:
        return Lsn(self._lsn)

    async def append_wal(self, payload: bytes, advance: int = 8,
                         table_id: TableId | None = None,
                         row: "list[str | None] | None" = None) -> Lsn:
        lsn = self.next_lsn(advance)
        self.wal.append((lsn, payload, table_id, row))
        async with self._wal_cond:
            self._wal_cond.notify_all()
        await self._replicate()
        return lsn

    async def append_wal_many(
            self, entries: "list[tuple[bytes, TableId | None, list | None]]"
    ) -> Lsn:
        """Append a transaction's entries with ONE reader wakeup — the
        per-entry condition-variable round trip otherwise dominates
        high-rate producers (each entry still advances the LSN by 8,
        identical to sequential append_wal calls)."""
        wal = self.wal
        lsn = self._lsn
        for payload, tid, row in entries:
            lsn += 8
            # plain int, not Lsn: the hot consumers (drain_spans, the
            # wire server loop) want ints anyway; Lsn construction per
            # entry measurably drags high-rate producers. Readers that
            # build frames wrap at the boundary (_next_buffered).
            wal.append((lsn, payload, tid, row))
        self._lsn = lsn
        async with self._wal_cond:
            self._wal_cond.notify_all()
        await self._replicate()
        return Lsn(lsn)

    def row_filter_allows(self, publication: str, table_id: TableId | None,
                          row: "list[str | None] | None") -> bool:
        if not self.server_row_filtering:
            return True  # filter-offload mode: the client's decode filters
        if table_id is None or row is None:
            return True
        pred = self.row_filters.get((publication, table_id))
        return True if pred is None else bool(pred(row))

    def transaction(self, xid: int | None = None) -> "FakeTransaction":
        return FakeTransaction(self, xid or (len(self.wal) + 100))

    # -- physical replication (reference pipeline_read_replica.rs) -------------

    def make_replica(self, snapshot_gate: bool = False) -> "FakeStandby":
        """Attach a physical read replica. `snapshot_gate=True` models
        PG16 logical-slot creation on a standby blocking until the
        primary logs a standby snapshot record."""
        sb = FakeStandby(self, snapshot_gate=snapshot_gate)
        self.standbys.append(sb)
        return sb

    async def _replicate(self) -> None:
        for sb in self.standbys:
            if sb.auto_replay:
                await sb.replay()

    async def log_standby_snapshot(self) -> None:
        """pg_log_standby_snapshot(): emits the running-xacts record that
        lets logical slot creation on a standby reach a consistent point
        (reference wait_with_standby_snapshots)."""
        for sb in self.standbys:
            sb._snapshot_logged.set()
            async with sb._wal_cond:
                sb._wal_cond.notify_all()

    async def wait_slot_creation_allowed(self) -> None:
        return None  # primaries never gate slot creation

    def invalidate_slot(self, name: str) -> None:
        self.slots[name].invalidated = True

    async def sever_streams(self) -> None:
        """Chaos helper: cut every live replication stream (the
        NetworkChaos partition analogue)."""
        for s in list(self.active_streams):
            await s.close()
        self.active_streams.clear()

    # -- walsender internals ---------------------------------------------------

    def take_snapshot(self) -> str:
        self._snapshot_seq += 1
        sid = f"fake-snap-{self._snapshot_seq}"
        # shallow list copies: row objects are immutable by convention
        # (updates REPLACE the row list, _apply_update) — deepcopy here
        # measured 4.7s/100k rows of pure machinery on the copy bench,
        # and even per-row copies cost 0.2s/snapshot
        self.snapshots[sid] = {
            tid: (list(t.rows),
                  list(t.encoded) if t.encoded is not None else None)
            for tid, t in self.tables.items()}
        return sid


class FakeTransaction:
    """Builds one transaction's pgoutput WAL entries, applying row changes
    to table state on commit (so later snapshots see them)."""

    def __init__(self, db: FakeDatabase, xid: int):
        self.db = db
        self.xid = xid
        self._ops: list[tuple] = []

    async def __aenter__(self) -> "FakeTransaction":
        return self

    async def __aexit__(self, et, ev, tb) -> None:
        if et is None:
            await self.commit()

    def insert(self, table_id: TableId, values: list[str | None]) -> None:
        self._ops.append(("I", table_id, values, None))

    def insert_preencoded(self, table_id: TableId, payload: bytes,
                          values: "list[str | None] | None" = None) -> None:
        """Insert whose pgoutput payload the caller already encoded (bench
        producers encode off the clock so the measured window holds only
        walsender framing + the pipeline). `values` feeds row filters and
        table state; None skips both (fine when neither is in play)."""
        self._ops.append(("P", table_id, payload, values))

    def update(self, table_id: TableId, key: list[str | None],
               new_values: list[str | None]) -> None:
        self._ops.append(("U", table_id, new_values, key))

    def delete(self, table_id: TableId, key: list[str | None]) -> None:
        self._ops.append(("D", table_id, None, key))

    def truncate(self, table_ids: list[TableId], options: int = 0) -> None:
        self._ops.append(("T", tuple(table_ids), options, None))

    def logical_message(self, prefix: str, content: bytes) -> None:
        self._ops.append(("M", prefix, content, None))

    def alter_table(self, table_id: TableId, new_schema: TableSchema) -> None:
        """ALTER TABLE: applies the new schema; if the source migrations
        installed the DDL event trigger AND the table is published, the
        trigger emits a supabase_etl_ddl logical message transactionally
        (reference migrations/source/...schema_change_messages.up.sql)."""
        self._ops.append(("A", table_id, new_schema, None))

    async def commit(self) -> Lsn:
        db = self.db
        ts = db.commit_clock_us()
        begin_at = db.current_lsn + 8

        # Relation messages are emitted lazily before a table's first row
        # op, with the schema CURRENT AT THAT POINT — an ALTER earlier in
        # the transaction must be reflected, exactly like the walsender's
        # per-connection relation cache invalidation. (PG sends per-
        # connection; putting them in the WAL makes replays self-
        # describing, which the apply loop tolerates — repeated RELATION
        # is idempotent.)
        relation_sent: set[TableId] = set()
        body_entries: list[bytes] = []

        def emit_relation(tid: TableId) -> None:
            t = db.tables[tid]
            cols = [((1 if c.is_primary_key else 0), c.name, c.type_oid,
                     c.modifier) for c in t.schema.columns]
            body_entries.append((pgoutput.encode_relation(
                tid, t.schema.name.schema, t.schema.name.name, cols,
                replica_identity=t.replica_identity), None, None))
            relation_sent.add(tid)

        if all(op[0] == "P" and op[3] is None for op in self._ops):
            # fast path for pre-encoded row bursts (bench producers):
            # same WAL as the general loop below — relation messages per
            # distinct target, then the payloads verbatim — without the
            # per-op dispatch, which otherwise gates how fast a producer
            # can feed the pipeline on a single core
            targets = {tid: db.wal_relid(tid)
                       for tid in {op[1] for op in self._ops}}
            for target in targets.values():
                if target not in relation_sent:
                    emit_relation(target)
            # the walsender knows every change's relation — carrying it on
            # the WAL entry spares readers a payload re-parse
            body_entries.extend((op[2], targets[op[1]], None)
                                for op in self._ops)
            self._ops = []
        for op in self._ops:
            kind = op[0]
            if kind in ("I", "U", "D", "P"):
                # publish_via_partition_root: leaf changes carry the root's
                # relid (and the root's RELATION message) in the WAL
                target = db.wal_relid(op[1])
                if target not in relation_sent:
                    emit_relation(target)
            if kind == "P":
                _, tid, payload, values = op
                body_entries.append(
                    (payload, target if values is not None else None, values))
                if values is not None:
                    db.tables[tid].append_row(values)
            elif kind == "I":
                _, tid, values, _ = op
                target = db.wal_relid(tid)
                body_entries.append((pgoutput.encode_insert(
                    target,
                    [None if v is None else v.encode() for v in values]),
                    target, list(values)))
                db.tables[tid].append_row(values)
            elif kind == "U":
                _, tid, values, key = op
                t = db.tables[tid]
                kcols = self._key_columns(t)
                old_row = self._find_row(t, key)

                def enc(vs):
                    return [None if v is None
                            or isinstance(v, _ToastUnchanged)
                            else v.encode() for v in vs]

                def kinds_of(vs):
                    return [pgoutput.TUPLE_UNCHANGED_TOAST
                            if isinstance(v, _ToastUnchanged)
                            else pgoutput.TUPLE_NULL if v is None
                            else pgoutput.TUPLE_TEXT for v in vs]
                # PG semantics: identity-full sends the full old row ('O');
                # default identity sends a key-only tuple ('K') ONLY when
                # an identity column changed; otherwise no old tuple
                old_values = key_values = None
                if t.replica_identity == ord("f") and old_row is not None:
                    old_values = enc(old_row)
                elif old_row is not None and any(
                        old_row[i] != values[i] for i in kcols):
                    key_values = enc([old_row[i] if i in kcols else None
                                      for i in range(len(old_row))])
                target = db.wal_relid(tid)
                # row filters evaluate against REAL tuple values (the
                # walsender resolves TOAST from storage before filtering)
                resolved = [
                    (old_row[i] if old_row is not None else None)
                    if isinstance(v, _ToastUnchanged) else v
                    for i, v in enumerate(values)]
                body_entries.append((pgoutput.encode_update(
                    target, enc(values), old_values=old_values,
                    key_values=key_values,
                    new_kinds=kinds_of(values)), target, resolved))
                self._apply_update(t, key, values)
            elif kind == "D":
                _, tid, _, key = op
                t = db.tables[tid]
                kcols = self._key_columns(t)
                old_row = self._find_row(t, key)
                if t.replica_identity == ord("f") and old_row is not None:
                    tup = old_row
                    full = True
                else:
                    src = old_row if old_row is not None else key
                    tup = [src[i] if i in kcols else None
                           for i in range(len(src))]
                    full = False
                target = db.wal_relid(tid)
                body_entries.append((pgoutput.encode_delete(
                    target, [None if v is None else v.encode() for v in tup],
                    full_old=full), target, list(key)))
                self._apply_delete(t, key)
            elif kind == "T":
                _, tids, options, _ = op
                body_entries.append((pgoutput.encode_truncate(
                    list(tids), options), None, None))
                for tid in tids:
                    db.tables[tid].rows.clear()
                    if db.tables[tid].encoded is not None:
                        db.tables[tid].encoded.clear()
            elif kind == "A":
                _, tid, new_schema, _ = op
                t = db.tables[tid]
                old_names = [c.name for c in t.schema.columns]
                new_names = [c.name for c in new_schema.columns]
                if new_names != old_names:
                    # ALTER with column changes rewrites storage: existing
                    # rows are projected onto the new column list by name
                    # (added columns NULL, dropped columns gone) — without
                    # this, a later update/delete's old image would carry
                    # the pre-ALTER column count against the post-ALTER
                    # RELATION message, which a real walsender can never
                    # produce
                    idx = {n: i for i, n in enumerate(old_names)}
                    t.rows[:] = [
                        [row[idx[n]] if n in idx else None
                         for n in new_names] for row in t.rows]
                    t.invalidate_encoded()
                t.schema = new_schema
                relation_sent.discard(tid)
                published = any(tid in tids
                                for tids in db.publications.values())
                if db.ddl_trigger_installed and published:
                    from .codec.event import (DDL_MESSAGE_PREFIX,
                                              encode_schema_change)

                    body_entries.append((pgoutput.encode_logical_message(
                        DDL_MESSAGE_PREFIX,
                        encode_schema_change(tid, new_schema),
                        lsn=int(db.current_lsn)), None, None))
            elif kind == "M":
                _, prefix, content, _ = op
                body_entries.append((pgoutput.encode_logical_message(
                    prefix, content, lsn=int(db.current_lsn)), None, None))

        n_entries = len(body_entries) + 2  # + begin + commit
        commit_lsn = Lsn(int(begin_at) + 8 * (n_entries - 1))
        entries = [(pgoutput.encode_begin(int(commit_lsn), ts, self.xid),
                    None, None)]
        entries.extend(body_entries)
        entries.append((pgoutput.encode_commit(int(commit_lsn),
                                               int(commit_lsn) + 8, ts),
                        None, None))
        await db.append_wal_many(entries)
        return commit_lsn

    def _key_columns(self, t: FakeTable) -> list[int]:
        pk = [i for i, c in enumerate(t.schema.columns) if c.is_primary_key]
        return pk or list(range(len(t.schema.columns)))

    def _find_row(self, t: FakeTable, key) -> list | None:
        kcols = self._key_columns(t)
        for row in t.rows:
            if all(row[i] == key[i] for i in kcols):
                return list(row)
        return None

    def _apply_update(self, t: FakeTable, key, values) -> None:
        t.invalidate_encoded()
        kcols = self._key_columns(t)
        for idx, row in enumerate(t.rows):
            if all(row[i] == key[i] for i in kcols):
                # REPLACE the row object (never mutate in place): snapshots
                # hold shallow references to row lists, so in-place writes
                # would leak post-snapshot state into exported snapshots.
                # unchanged-TOAST cells keep their stored value, exactly
                # like Postgres storage
                t.rows[idx] = [row[i] if isinstance(v, _ToastUnchanged)
                               else v for i, v in enumerate(values)]
                return

    def _apply_delete(self, t: FakeTable, key) -> None:
        t.invalidate_encoded()
        kcols = self._key_columns(t)
        t.rows[:] = [r for r in t.rows
                     if not all(r[i] == key[i] for i in kcols)]


class FakeStandby(FakeDatabase):
    """Physical read replica of a FakeDatabase (reference
    pipeline_read_replica.rs semantics on the fake):

    - shares cluster-wide logical state (tables, publications, filters)
      with the primary BY REFERENCE — physical replication replays the
      whole cluster;
    - maintains its OWN WAL view bounded by `replay()` — streams on the
      replica only see WAL the standby has replayed;
    - owns its OWN slot map: ETL's logical slots live on the replica, the
      primary keeps none (pipeline_read_replica.rs:294-297);
    - optionally gates slot creation until the primary logs a standby
      snapshot (PG16 logical decoding on standby,
      wait_with_standby_snapshots);
    - rejects writes (pg_is_in_recovery).

    Approximation: COPY snapshots read the shared table store, so they see
    the primary's latest rows — the reference tests likewise wait for full
    catch-up before starting copies."""

    def __init__(self, primary: FakeDatabase, *,
                 snapshot_gate: bool = False):
        super().__init__()
        self.primary = primary
        self.is_standby = True
        self.tables = primary.tables
        self.publications = primary.publications
        self.column_filters = primary.column_filters
        self.row_filters = primary.row_filters
        self.row_filter_sql = primary.row_filter_sql
        self.ddl_trigger_installed = primary.ddl_trigger_installed
        self.auto_replay = True
        self.snapshot_gate = snapshot_gate
        self._snapshot_logged = asyncio.Event()
        self._replay_index = 0
        self._lsn = primary._lsn

    async def replay(self, upto: Lsn | None = None) -> None:
        """Replay primary WAL up to `upto` (default: full catch-up) and
        wake streams waiting on the replica."""
        target = int(upto) if upto is not None else self.primary._lsn
        src = self.primary.wal
        while (self._replay_index < len(src)
               and int(src[self._replay_index][0]) <= target):
            self.wal.append(src[self._replay_index])
            self._lsn = int(src[self._replay_index][0])
            self._replay_index += 1
        # fully-replayed standbys track the primary's position even when
        # the trailing WAL carries no logical records (keepalive LSNs)
        self._lsn = max(self._lsn, min(target, self.primary._lsn))
        async with self._wal_cond:
            self._wal_cond.notify_all()

    def transaction(self, xid: int | None = None) -> "FakeTransaction":
        if self.is_standby:
            raise AssertionError(
                "cannot write to a standby (pg_is_in_recovery) — write "
                "to the primary and replay()")
        return super().transaction(xid)

    async def promote(self) -> None:
        """pg_promote(): final catch-up replay, detach from the primary,
        leave recovery. The node keeps its replayed WAL and its logical
        slots (slots survive promotion on PG16+) and accepts writes
        from here on; the old primary gets no further reads."""
        await self.replay()
        self.is_standby = False
        if self in self.primary.standbys:
            self.primary.standbys.remove(self)
        # private DEEP copies: post-promotion writes/DDL on the old
        # primary must not leak in by reference (FakeTable.rows and
        # .schema are mutated in place), and writes on the promoted
        # node must not mutate the old primary's storage
        self.tables = copy.deepcopy(self.tables)
        self.publications = {k: list(v)
                             for k, v in self.publications.items()}
        self.column_filters = copy.deepcopy(self.column_filters)
        self.row_filters = copy.deepcopy(self.row_filters)
        self.row_filter_sql = dict(self.row_filter_sql)

    async def wait_slot_creation_allowed(self) -> None:
        if self.snapshot_gate:
            await self._snapshot_logged.wait()


class _FakeReplicationStream(ReplicationStream):
    _ids = 0

    def __init__(self, db: FakeDatabase, slot: _FakeSlot, publication: str,
                 start_lsn: Lsn, keepalive_interval_s: float):
        self.db = db
        self.slot = slot
        self.publication = publication
        self.pos_lsn = start_lsn
        self._closed = False
        self._keepalive_interval = keepalive_interval_s
        self.status_updates: list[tuple[Lsn, Lsn, Lsn]] = []
        _FakeReplicationStream._ids += 1
        self.id = _FakeReplicationStream._ids
        self._wal_index = 0
        self._pub_tables = None
        db.active_streams.append(self)

    def __aiter__(self) -> AsyncIterator[pgoutput.ReplicationFrame]:
        return self._frames()

    def _next_buffered(self, clock_us: int | None = None
                       ) -> "pgoutput.XLogData | None":
        """Next already-written WAL frame, or None when caught up."""
        db = self.db
        if self._pub_tables is None:
            self._pub_tables = set(db.publications.get(self.publication, []))
        while self._wal_index < len(db.wal):
            lsn, payload, tid, row = db.wal[self._wal_index]
            self._wal_index += 1
            # START_REPLICATION is INCLUSIVE of the requested LSN: the
            # next tx's BEGIN sits exactly at the prior commit's end
            if lsn < self.pos_lsn:
                continue
            if not self._publication_allows(payload, self._pub_tables):
                continue
            if not db.row_filter_allows(self.publication, tid, row):
                continue
            return pgoutput.XLogData(
                start_lsn=Lsn(lsn), end_lsn=db.current_lsn,
                clock_us=clock_us if clock_us is not None else _now_us(),
                payload=payload)
        return None

    def drain_buffered(self, max_n: int) -> list:
        """Bulk-read already-buffered frames without event-loop round
        trips (the apply loop's per-frame asyncio overhead otherwise caps
        CDC throughput). One clock read serves the whole window."""
        out = []
        if self._closed or self.slot.invalidated:
            return out
        clock = _now_us()
        while len(out) < max_n:
            f = self._next_buffered(clock)
            if f is None:
                break
            out.append(f)
        return out

    def drain_spans(self, max_n: int) -> list:
        """Span-drain straight off the WAL: row runs become FrameSpans
        with int LSNs and the payload bytes already in hand — no XLogData
        / Lsn object per event (the walsender-side half of the CDC hot
        path; wal entries carry (lsn, payload, relid, row) so neither the
        relid nor the filters need a payload re-parse)."""
        from .source import SPAN_MAX_ROWS, FrameSpan

        out: list = []
        if self._closed or self.slot.invalidated:
            return out
        db = self.db
        if self._pub_tables is None:
            self._pub_tables = set(db.publications.get(self.publication, []))
        pub_tables = self._pub_tables
        wal = db.wal
        wal_len = len(wal)
        end = int(db._lsn)
        clock = None
        span_relid = -1  # sentinel: no open span
        span_payloads: list | None = None
        span_lsns: list | None = None
        span_room = 0
        count = 0
        idx = self._wal_index
        pos = self.pos_lsn
        pub = self.publication
        filters = db.row_filters
        # 73/85/68 = I/U/D — integer compare beats a bytes-slice + tuple
        # membership test on this per-event loop
        while idx < wal_len and count < max_n:
            lsn, payload, tid, row = wal[idx]
            idx += 1
            # START_REPLICATION is INCLUSIVE of the requested LSN (see
            # _next_buffered)
            if lsn < pos:
                continue
            tag = payload[0]
            if tag == 73 or tag == 85 or tag == 68:
                # pre-encoded WAL entries (bench producers) don't carry a
                # table_id column — fall back to the payload's relid
                rid = tid if tid is not None \
                    else int.from_bytes(payload[1:5], "big")
                if rid not in pub_tables:
                    continue
                if filters and not db.row_filter_allows(pub, tid, row):
                    continue
                count += 1
                if rid == span_relid and span_room > 0:
                    span_payloads.append(payload)
                    span_lsns.append(int(lsn))
                    span_room -= 1
                else:
                    span_payloads = [payload]
                    span_lsns = [int(lsn)]
                    span_relid = rid
                    span_room = SPAN_MAX_ROWS - 1
                    out.append(FrameSpan(rid, span_payloads, span_lsns,
                                         end))
                continue
            if not self._publication_allows(payload, pub_tables):
                continue
            if not db.row_filter_allows(pub, tid, row):
                continue
            count += 1
            span_relid = -1
            if clock is None:
                clock = _now_us()
            out.append(pgoutput.XLogData(
                start_lsn=Lsn(lsn), end_lsn=db.current_lsn,
                clock_us=clock, payload=payload))
        self._wal_index = idx
        return out

    async def _frames(self):
        db = self.db
        while not self._closed:
            if self.slot.invalidated:
                raise EtlError(ErrorKind.SLOT_INVALIDATED,
                               f"slot {self.slot.name} invalidated")
            frame = self._next_buffered()
            if frame is not None:
                yield frame
                continue
            # wait for more WAL or emit keepalive on timeout
            try:
                async with db._wal_cond:
                    await asyncio.wait_for(db._wal_cond.wait(),
                                           timeout=self._keepalive_interval)
            except asyncio.TimeoutError:
                yield pgoutput.PrimaryKeepalive(
                    end_lsn=db.current_lsn, clock_us=_now_us(),
                    reply_requested=True)

    def _publication_allows(self, payload: bytes,
                            pub_tables: set[TableId]) -> bool:
        tag = payload[0:1]
        if tag in (b"I", b"U", b"D", b"R"):
            rid = int.from_bytes(payload[1:5], "big")
            return rid in pub_tables
        if tag == b"T":
            # truncate lists relations; deliver if any is published
            n = int.from_bytes(payload[1:5], "big")
            rids = [int.from_bytes(payload[6 + 4 * i : 10 + 4 * i], "big")
                    for i in range(n)]
            return any(r in pub_tables for r in rids)
        return True  # begin/commit/message flow through

    async def send_status_update(self, written: Lsn, flushed: Lsn,
                                 applied: Lsn,
                                 reply_requested: bool = False) -> None:
        self.status_updates.append((written, flushed, applied))
        if flushed > self.slot.confirmed_flush:
            self.slot.confirmed_flush = flushed

    async def close(self) -> None:
        self._closed = True
        self.slot.active = False
        if self in self.db.active_streams:
            self.db.active_streams.remove(self)


class _FakeCopyStream(CopyStream):
    def __init__(self, rows: list[list[str | None]], chunk_rows: int = 512,
                 encoded: "list[bytes] | None" = None):
        self._rows = rows
        self._chunk_rows = chunk_rows
        self._encoded = encoded  # pre-rendered COPY lines, 1:1 with rows

    def __aiter__(self):
        return self._chunks()

    async def _chunks(self):
        enc = self._encoded
        for i in range(0, len(self._rows), self._chunk_rows):
            lines = enc[i : i + self._chunk_rows] if enc is not None else \
                [encode_copy_row(r) for r in self._rows[i : i + self._chunk_rows]]
            chunk = b"\n".join(lines)
            yield chunk + b"\n" if chunk else b""
            await asyncio.sleep(0)  # yield to the loop like real IO


class FakeSource(ReplicationSource):
    """One connection to a FakeDatabase."""

    def __init__(self, db: FakeDatabase,
                 keepalive_interval_s: float = 0.05):
        self.db = db
        self.connected = False
        self._keepalive_interval = keepalive_interval_s
        self.streams: list[_FakeReplicationStream] = []

    async def connect(self) -> None:
        self.connected = True

    async def close(self) -> None:
        self.connected = False
        for s in self.streams:
            await s.close()

    async def publication_exists(self, publication: str) -> bool:
        return publication in self.db.publications

    async def get_publication_table_ids(self, publication: str) -> list[TableId]:
        if publication not in self.db.publications:
            raise EtlError(ErrorKind.PUBLICATION_NOT_FOUND, publication)
        return list(self.db.publications[publication])

    async def get_table_schema(self, table_id: TableId, publication: str,
                               snapshot_id: str | None = None
                               ) -> ReplicatedTableSchema:
        t = self.db.tables.get(table_id)
        if t is None:
            raise EtlError(ErrorKind.PUBLICATION_TABLE_MISSING,
                           f"table {table_id}")
        schema = t.schema
        n = len(schema.columns)
        filt = self.db.column_filters.get((publication, table_id))
        repl_mask = (ColumnMask.from_column_names(schema, filt) if filt
                     else ColumnMask.all_set(n))
        identity = ColumnMask(c.is_primary_key for c in schema.columns)
        if identity.count() == 0:
            identity = ColumnMask.all_set(n) \
                if t.replica_identity == ord("f") else ColumnMask([False] * n)
        out = ReplicatedTableSchema(schema, repl_mask, identity)
        # leaf partitions inherit the published ROOT's row filter, same as
        # the column filters above (pg_publication_tables lists the root)
        sql = self.db.row_filter_sql.get(
            (publication, self.db.wal_relid(table_id)))
        if sql:
            from ..ops.predicate import RowFilterError, parse_row_filter

            try:
                out = out.with_row_predicate(parse_row_filter(sql))
            except RowFilterError:
                pass  # outside the client envelope; server-side only
        return out

    async def get_row_filters(self, publication: str) -> "dict[TableId, str]":
        return {tid: sql
                for (pub, tid), sql in self.db.row_filter_sql.items()
                if pub == publication}

    async def get_current_wal_lsn(self) -> Lsn:
        return self.db.current_lsn

    async def is_in_recovery(self) -> bool:
        return self.db.is_standby

    async def get_partition_leaves(
            self, table_id: TableId) -> list[tuple[TableId, int, int]]:
        t = self.db.tables.get(table_id)
        if t is None or not t.partition_leaves:
            return []
        out = []
        for leaf_id in t.partition_leaves:
            leaf = self.db.tables[leaf_id]
            n = len(leaf.rows)
            out.append((leaf_id, n, max(1, n // 64)))
        return out

    async def applied_source_migrations(self) -> list[str]:
        return list(self.db.applied_migrations)

    async def apply_source_migration(self, name: str, sql: str) -> None:
        # the fake models the migration's EFFECT: the DDL event trigger is
        # installed, so ALTER TABLE through FakeTransaction emits the
        # supabase_etl_ddl logical message (the installed path)
        self.db.ddl_trigger_installed = True
        self.db.applied_migrations.append(name)

    async def get_slot(self, name: str) -> SlotInfo | None:
        s = self.db.slots.get(name)
        if s is None:
            return None
        return SlotInfo(name=s.name, confirmed_flush_lsn=s.confirmed_flush,
                        active=s.active, invalidated=s.invalidated)

    async def create_slot(self, name: str) -> CreatedSlot:
        if name in self.db.slots:
            raise EtlError(ErrorKind.SLOT_ALREADY_EXISTS, name)
        # on a standby, logical slot creation blocks until the primary
        # logs a standby snapshot (PG16; FakeStandby.snapshot_gate)
        await self.db.wait_slot_creation_allowed()
        point = self.db.current_lsn
        sid = self.db.take_snapshot()
        self.db.slots[name] = _FakeSlot(
            name=name, consistent_point=point, confirmed_flush=point,
            snapshot_id=sid)
        return CreatedSlot(name=name, consistent_point=point, snapshot_id=sid)

    async def delete_slot(self, name: str) -> None:
        self.db.slots.pop(name, None)

    async def copy_table_stream(self, table_id: TableId, publication: str,
                                snapshot_id: str,
                                ctid_range: "tuple[int, int] | None" = None,
                                publication_table_id: "TableId | None" = None
                                ) -> CopyStream:
        snap = self.db.snapshots.get(snapshot_id)
        if snap is None:
            raise EtlError(ErrorKind.SNAPSHOT_EXPORT_FAILED, snapshot_id)
        rows, encoded = snap.get(table_id, ([], None))
        # a leaf partition inherits the published root's row/column filters
        pub_tid = self.db.wal_relid(table_id)
        pred = self.db.row_filters.get((publication, pub_tid)) \
            if self.db.server_row_filtering else None
        if pred is not None:
            rows = [r for r in rows if pred(r)]
            encoded = None  # filtered subset no longer aligns with the cache
        if ctid_range is not None:
            # fake pages: 64 rows per heap page
            lo, hi = ctid_range
            rows = rows[lo * 64 : hi * 64]
            if encoded is not None:
                encoded = encoded[lo * 64 : hi * 64]
        filt = self.db.column_filters.get((publication, pub_tid))
        if filt:
            schema = self.db.tables[table_id].schema
            idx = [schema.column_index(c) for c in filt]
            rows = [[r[i] for i in idx] for r in rows]
            encoded = None
        return _FakeCopyStream(rows, encoded=encoded)

    async def estimate_table_stats(self, table_id: TableId) -> tuple[int, int]:
        n = len(self.db.tables[table_id].rows)
        return n, max(1, n // 64)

    async def start_replication(self, slot_name: str, publication: str,
                                start_lsn: Lsn) -> ReplicationStream:
        slot = self.db.slots.get(slot_name)
        if slot is None:
            raise EtlError(ErrorKind.SLOT_NOT_FOUND, slot_name)
        if slot.invalidated:
            raise EtlError(ErrorKind.SLOT_INVALIDATED, slot_name)
        slot.active = True
        start = max(start_lsn, slot.confirmed_flush)
        stream = _FakeReplicationStream(self.db, slot, publication, start,
                                        self._keepalive_interval)
        self.streams.append(stream)
        return stream
