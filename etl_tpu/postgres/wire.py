"""Postgres frontend/backend wire protocol (v3) client.

The transport layer under PgReplicationClient (postgres/client.py):
startup + auth (trust / cleartext / md5 / SCRAM-SHA-256), simple queries,
COPY OUT streaming, and the replication sub-protocol (IDENTIFY_SYSTEM,
CREATE_REPLICATION_SLOT, START_REPLICATION with CopyBoth framing).

Reference parity: the forked tokio-postgres replication protocol support
the reference leans on (SURVEY §7 hard part 4 — "pgoutput/replication
protocol client in a non-Rust stack"); connection options mirror
client/raw.rs:237-270 (application_name, replication=database, TLS,
keepalives).

Written against the PostgreSQL protocol documentation; no Postgres client
library is used anywhere.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import hmac
import os
import ssl as ssl_mod
import struct
from dataclasses import dataclass, field
from typing import AsyncIterator

from ..models.errors import ErrorKind, EtlError

PROTOCOL_VERSION = 196608  # 3.0


@dataclass
class BackendMessage:
    tag: bytes
    payload: bytes


@dataclass
class PgServerError(EtlError):
    """ErrorResponse from the backend, with severity/code/message fields."""

    def __init__(self, fields: dict[str, str]):
        self.fields = fields
        code = fields.get("C", "")
        msg = fields.get("M", "server error")
        kind = ErrorKind.SOURCE_QUERY_FAILED
        if code.startswith("28"):
            kind = ErrorKind.SOURCE_AUTH_FAILED
        elif code == "42704":  # undefined_object (e.g. missing slot)
            kind = ErrorKind.SLOT_NOT_FOUND
        elif code == "42710":  # duplicate_object
            kind = ErrorKind.SLOT_ALREADY_EXISTS
        elif code == "55006":  # object_in_use
            kind = ErrorKind.SLOT_IN_USE
        super().__init__(kind, f"{code}: {msg}")


@dataclass
class RowDescription:
    names: list[str]
    type_oids: list[int]


@dataclass
class QueryResult:
    description: RowDescription | None
    rows: list[list[str | None]]
    command_tag: str = ""


class PgWireConnection:
    """One protocol-v3 connection (asyncio)."""

    def __init__(self, *, host: str, port: int, database: str, user: str,
                 password: str | None = None, application_name: str = "etl_tpu",
                 replication: bool = False, ssl_context: ssl_mod.SSLContext | None = None,
                 connect_timeout_s: float = 30.0):
        self.host = host
        self.port = port
        self.database = database
        self.user = user
        self.password = password
        self.application_name = application_name
        self.replication = replication
        self.ssl_context = ssl_context
        self.connect_timeout_s = connect_timeout_s
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self.parameters: dict[str, str] = {}
        self.backend_pid = 0

    # -- low-level IO --------------------------------------------------------

    async def _read_message(self) -> BackendMessage:
        assert self._reader is not None
        header = await self._reader.readexactly(5)
        tag = header[:1]
        (length,) = struct.unpack(">i", header[1:5])
        # corrupted stream defense: a flipped bit in the length field
        # must surface as a typed protocol error, not a readexactly()
        # that waits forever for gigabytes. Bound = PG's own 1GB
        # message cap (a smaller cap would reject a valid CopyData
        # carrying a near-1GB TOAST value and wedge the retry loop on
        # correct data)
        if length < 4 or length - 4 > 1 << 30:
            raise EtlError(ErrorKind.SOURCE_PROTOCOL_VIOLATION,
                           f"corrupt message length {length} "
                           f"(tag {tag!r})")
        payload = await self._reader.readexactly(length - 4)
        if tag == b"E":
            raise PgServerError(_parse_error_fields(payload))
        return BackendMessage(tag, payload)

    def _send(self, tag: bytes, payload: bytes) -> None:
        assert self._writer is not None
        self._writer.write(tag + struct.pack(">i", len(payload) + 4) + payload)

    async def _flush(self) -> None:
        assert self._writer is not None
        await self._writer.drain()

    # -- connect / auth ------------------------------------------------------

    async def connect(self) -> None:
        try:
            self._reader, self._writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port),
                self.connect_timeout_s)
        except (OSError, asyncio.TimeoutError) as e:
            raise EtlError(ErrorKind.SOURCE_CONNECTION_FAILED,
                           f"{self.host}:{self.port}: {e}")
        try:
            if self.ssl_context is not None:
                await self._start_tls()
            params = {
                "user": self.user,
                "database": self.database,
                "application_name": self.application_name,
                "client_encoding": "UTF8",
            }
            if self.replication:
                params["replication"] = "database"
            body = struct.pack(">i", PROTOCOL_VERSION)
            for k, v in params.items():
                body += k.encode() + b"\x00" + v.encode() + b"\x00"
            body += b"\x00"
            assert self._writer is not None
            self._writer.write(struct.pack(">i", len(body) + 4) + body)
            await self._flush()
            await self._authenticate()
            # consume until ReadyForQuery
            while True:
                msg = await self._read_message()
                if msg.tag == b"Z":
                    return
                if msg.tag == b"S":
                    k, _, v = msg.payload.partition(b"\x00")
                    self.parameters[k.decode()] = \
                        v.rstrip(b"\x00").decode()
                elif msg.tag == b"K":
                    self.backend_pid = struct.unpack(
                        ">i", msg.payload[:4])[0]
        except BaseException:
            # a failed TLS/auth/startup must not leak the socket
            self._writer.close()
            self._reader = self._writer = None
            raise

    async def _start_tls(self) -> None:
        assert self._writer is not None and self._reader is not None
        self._writer.write(struct.pack(">ii", 8, 80877103))  # SSLRequest
        await self._flush()
        resp = await self._reader.readexactly(1)
        if resp != b"S":
            raise EtlError(ErrorKind.SOURCE_TLS_FAILED,
                           "server refused TLS")
        transport = self._writer.transport
        loop = asyncio.get_event_loop()
        try:
            new_transport = await loop.start_tls(
                transport, self._writer.transport.get_protocol(),
                self.ssl_context, server_hostname=self.host)
        except (ssl_mod.SSLError, OSError) as e:
            # typed: cert verification / handshake failures are config
            # problems, not transient IO (reference sslmode=require errors)
            raise EtlError(ErrorKind.SOURCE_TLS_FAILED,
                           f"TLS handshake with {self.host}:{self.port} "
                           f"failed: {e}")
        if new_transport is None:
            # start_tls returns None when the peer drops as the handshake
            # settles (SSLProtocol nulls the app transport) — surface it
            # typed instead of poisoning the stream pair
            raise EtlError(ErrorKind.SOURCE_TLS_FAILED,
                           f"TLS handshake with {self.host}:{self.port} "
                           "failed: connection lost during handshake")
        self._writer._transport = new_transport  # type: ignore[attr-defined]
        self._reader._transport = new_transport  # type: ignore[attr-defined]

    async def _authenticate(self) -> None:
        while True:
            msg = await self._read_message()
            if msg.tag == b"N":  # NoticeResponse is legal at any time
                continue
            if msg.tag != b"R":
                raise EtlError(ErrorKind.SOURCE_PROTOCOL_VIOLATION,
                               f"expected auth, got {msg.tag!r}")
            (code,) = struct.unpack(">i", msg.payload[:4])
            if code == 0:  # AuthenticationOk
                return
            if code == 3:  # cleartext
                if self.password is None:
                    raise EtlError(ErrorKind.SOURCE_AUTH_FAILED,
                                   "password required")
                self._send(b"p", self.password.encode() + b"\x00")
                await self._flush()
            elif code == 5:  # md5
                if self.password is None:
                    raise EtlError(ErrorKind.SOURCE_AUTH_FAILED,
                                   "password required")
                salt = msg.payload[4:8]
                inner = hashlib.md5(
                    self.password.encode() + self.user.encode()).hexdigest()
                digest = hashlib.md5(inner.encode() + salt).hexdigest()
                self._send(b"p", b"md5" + digest.encode() + b"\x00")
                await self._flush()
            elif code == 10:  # SASL
                mechanisms = msg.payload[4:].split(b"\x00")
                if b"SCRAM-SHA-256" not in mechanisms:
                    raise EtlError(ErrorKind.SOURCE_AUTH_FAILED,
                                   f"unsupported SASL mechanisms {mechanisms}")
                await self._scram_auth()
            else:
                raise EtlError(ErrorKind.SOURCE_AUTH_FAILED,
                               f"unsupported auth method {code}")

    # injectable for golden-transcript tests (a pinned byte exchange needs
    # deterministic nonces); production keeps the 18-byte random default
    _scram_nonce_bytes = staticmethod(lambda: os.urandom(18))

    async def _scram_auth(self) -> None:
        """SCRAM-SHA-256 (RFC 5802/7677)."""
        if self.password is None:
            raise EtlError(ErrorKind.SOURCE_AUTH_FAILED, "password required")
        nonce = base64.b64encode(self._scram_nonce_bytes()).decode()
        first_bare = f"n=,r={nonce}"
        msg = b"SCRAM-SHA-256\x00" + struct.pack(
            ">i", len(first_bare) + 3) + b"n,," + first_bare.encode()
        self._send(b"p", msg)
        await self._flush()
        cont = await self._read_message()
        (code,) = struct.unpack(">i", cont.payload[:4])
        if code != 11:
            raise EtlError(ErrorKind.SOURCE_AUTH_FAILED,
                           f"expected SASLContinue, got {code}")
        server_first = cont.payload[4:].decode()
        attrs = dict(p.split("=", 1) for p in server_first.split(","))
        server_nonce = attrs["r"]
        salt = base64.b64decode(attrs["s"])
        iterations = int(attrs["i"])
        if not server_nonce.startswith(nonce):
            raise EtlError(ErrorKind.SOURCE_AUTH_FAILED,
                           "SCRAM nonce mismatch")
        salted = hashlib.pbkdf2_hmac("sha256", self.password.encode(), salt,
                                     iterations)
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored_key = hashlib.sha256(client_key).digest()
        without_proof = f"c=biws,r={server_nonce}"
        auth_message = ",".join([first_bare, server_first, without_proof])
        signature = hmac.new(stored_key, auth_message.encode(),
                             hashlib.sha256).digest()
        proof = bytes(a ^ b for a, b in zip(client_key, signature))
        final = f"{without_proof},p={base64.b64encode(proof).decode()}"
        self._send(b"p", final.encode())
        await self._flush()
        final_msg = await self._read_message()
        (code,) = struct.unpack(">i", final_msg.payload[:4])
        if code != 12:
            raise EtlError(ErrorKind.SOURCE_AUTH_FAILED,
                           f"expected SASLFinal, got {code}")
        server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        expected = hmac.new(server_key, auth_message.encode(),
                            hashlib.sha256).digest()
        got = dict(p.split("=", 1)
                   for p in final_msg.payload[4:].decode().split(","))
        if base64.b64decode(got.get("v", "")) != expected:
            raise EtlError(ErrorKind.SOURCE_AUTH_FAILED,
                           "SCRAM server signature mismatch")

    # -- simple query --------------------------------------------------------

    async def _read_query_response(self) -> QueryResult:
        """Collect RowDescription/DataRows/CommandComplete until
        ReadyForQuery; a captured ErrorResponse raises at the sync point
        (shared by the simple and extended query paths)."""
        desc: RowDescription | None = None
        rows: list[list[str | None]] = []
        tag = ""
        error: PgServerError | None = None
        while True:
            try:
                msg = await self._read_message()
            except PgServerError as e:
                error = e  # keep consuming until ReadyForQuery
                continue
            if msg.tag == b"T":
                desc = _parse_row_description(msg.payload)
            elif msg.tag == b"D":
                rows.append(_parse_data_row(msg.payload))
            elif msg.tag == b"C":
                tag = msg.payload.rstrip(b"\x00").decode()
            elif msg.tag == b"Z":
                if error is not None:
                    raise error
                return QueryResult(desc, rows, tag)
            # N (notice), S (parameter), 1/2/n/s acks: ignored

    async def query(self, sql: str) -> QueryResult:
        """Simple-query protocol; returns text-format rows."""
        self._send(b"Q", sql.encode() + b"\x00")
        await self._flush()
        return await self._read_query_response()

    # -- extended query ------------------------------------------------------

    async def query_params(self, sql: str,
                           params: "tuple | list" = ()) -> QueryResult:
        """Extended-protocol query with SERVER-side parameter binding
        ($1..$n placeholders): unnamed Parse → Bind (text-format params)
        → Describe → Execute → Sync. Removes any client-side quoting from
        the security/correctness path."""
        body = _cstr("") + _cstr(sql) + struct.pack(">h", 0)
        self._send(b"P", body)
        bind = bytearray(_cstr("") + _cstr(""))
        bind += struct.pack(">h", 0)  # all params text-format
        bind += struct.pack(">h", len(params))
        for v in params:
            if v is None:
                bind += struct.pack(">i", -1)
            else:
                b = str(v).encode()
                bind += struct.pack(">i", len(b)) + b
        bind += struct.pack(">h", 0)  # all results text-format
        self._send(b"B", bytes(bind))
        self._send(b"D", b"P" + _cstr(""))
        self._send(b"E", _cstr("") + struct.pack(">i", 0))
        self._send(b"S", b"")
        await self._flush()
        return await self._read_query_response()

    async def copy_out(self, sql: str) -> AsyncIterator[bytes]:
        """COPY ... TO STDOUT: yields raw CopyData payloads."""
        self._send(b"Q", sql.encode() + b"\x00")
        await self._flush()
        started = False
        error: PgServerError | None = None
        while True:
            try:
                msg = await self._read_message()
            except PgServerError as e:
                error = e
                continue
            if msg.tag == b"H":  # CopyOutResponse
                started = True
            elif msg.tag == b"d":
                yield msg.payload
            elif msg.tag == b"c":  # CopyDone
                pass
            elif msg.tag == b"C":
                pass
            elif msg.tag == b"Z":
                if error is not None:
                    raise error
                if not started:
                    raise EtlError(ErrorKind.SOURCE_QUERY_FAILED,
                                   f"not a COPY OUT statement: {sql!r}")
                return

    # -- replication sub-protocol ---------------------------------------------

    async def start_copy_both(self, sql: str) -> None:
        """Issue START_REPLICATION; leaves the connection in CopyBoth mode."""
        self._send(b"Q", sql.encode() + b"\x00")
        await self._flush()
        while True:
            msg = await self._read_message()
            if msg.tag == b"N":
                continue
            break
        if msg.tag != b"W":
            raise EtlError(ErrorKind.REPLICATION_STREAM_FAILED,
                           f"expected CopyBothResponse, got {msg.tag!r}")

    async def copy_both_read(self) -> bytes | None:
        """Next CopyData payload in CopyBoth mode; None when the server
        ends the stream."""
        while True:
            msg = await self._read_message()
            if msg.tag == b"d":
                return msg.payload
            if msg.tag in (b"c", b"C"):
                continue
            if msg.tag == b"Z":
                return None

    async def copy_both_send(self, payload: bytes) -> None:
        self._send(b"d", payload)
        await self._flush()

    async def close(self) -> None:
        if self._writer is not None:
            try:
                self._send(b"X", b"")
                await self._flush()
            except (ConnectionError, RuntimeError):
                pass
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, ssl_mod.SSLError):
                pass
            self._writer = None
            self._reader = None


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


def _parse_error_fields(payload: bytes) -> dict[str, str]:
    fields: dict[str, str] = {}
    for part in payload.split(b"\x00"):
        if part:
            fields[chr(part[0])] = part[1:].decode("utf-8", "replace")
    return fields


def _parse_row_description(payload: bytes) -> RowDescription:
    (n,) = struct.unpack(">h", payload[:2])
    pos = 2
    names, oids = [], []
    for _ in range(n):
        end = payload.index(b"\x00", pos)
        names.append(payload[pos:end].decode())
        pos = end + 1
        _table, _attr, oid, _size, _mod, _fmt = struct.unpack(
            ">ihihih", payload[pos : pos + 18])
        oids.append(oid)
        pos += 18
    return RowDescription(names, oids)


def _parse_data_row(payload: bytes) -> list[str | None]:
    (n,) = struct.unpack(">h", payload[:2])
    pos = 2
    out: list[str | None] = []
    for _ in range(n):
        (ln,) = struct.unpack(">i", payload[pos : pos + 4])
        pos += 4
        if ln < 0:
            out.append(None)
        else:
            out.append(payload[pos : pos + ln].decode())
            pos += ln
    return out
