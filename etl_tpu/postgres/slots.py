"""Replication slot naming.

Reference parity: crates/etl-postgres/src/slots.rs:16-18,49-120 —
`supabase_etl_apply_{pipeline}` and
`supabase_etl_table_sync_{pipeline}_{table}`, bounded by Postgres' 63-byte
identifier limit, with parsing helpers for cleanup sweeps.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..models.errors import ErrorKind, EtlError
from ..models.schema import TableId

SLOT_PREFIX = "supabase_etl"
MAX_SLOT_LEN = 63


def apply_slot_name(pipeline_id: int) -> str:
    name = f"{SLOT_PREFIX}_apply_{pipeline_id}"
    _check(name)
    return name


def table_sync_slot_name(pipeline_id: int, table_id: TableId) -> str:
    name = f"{SLOT_PREFIX}_table_sync_{pipeline_id}_{table_id}"
    _check(name)
    return name


def _check(name: str) -> None:
    if len(name.encode()) > MAX_SLOT_LEN:
        raise EtlError(ErrorKind.SLOT_NAME_TOO_LONG, name)


@dataclass(frozen=True)
class ParsedSlot:
    pipeline_id: int
    table_id: TableId | None  # None = apply slot

    @property
    def is_apply(self) -> bool:
        return self.table_id is None


def parse_slot_name(name: str) -> ParsedSlot | None:
    """Parse a framework slot name; None if it isn't ours."""
    if name.startswith(f"{SLOT_PREFIX}_apply_"):
        rest = name[len(f"{SLOT_PREFIX}_apply_"):]
        try:
            return ParsedSlot(int(rest), None)
        except ValueError:
            return None
    if name.startswith(f"{SLOT_PREFIX}_table_sync_"):
        rest = name[len(f"{SLOT_PREFIX}_table_sync_"):]
        parts = rest.split("_")
        if len(parts) != 2:
            return None
        try:
            return ParsedSlot(int(parts[0]), int(parts[1]))
        except ValueError:
            return None
    return None


def slots_for_pipeline(names: list[str], pipeline_id: int) -> list[str]:
    """Cleanup helper: all of a pipeline's slots among `names`."""
    out = []
    for n in names:
        p = parse_slot_name(n)
        if p is not None and p.pipeline_id == pipeline_id:
            out.append(n)
    return out
