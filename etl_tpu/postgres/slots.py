"""Replication slot naming.

Reference parity: crates/etl-postgres/src/slots.rs:16-18,49-120 —
`supabase_etl_apply_{pipeline}` and
`supabase_etl_table_sync_{pipeline}_{table}`, bounded by Postgres' 63-byte
identifier limit, with parsing helpers for cleanup sweeps.

Sharded extension (docs/sharding.md): when a publication is split across
K replicator pods, every slot carries an `_s{shard}` suffix —
`supabase_etl_apply_{pipeline}_s{shard}` and
`supabase_etl_table_sync_{pipeline}_{table}_s{shard}` — so each shard
owns its own replication stream and durable-progress keys, and a cleanup
sweep can enumerate one shard's slots without touching its siblings'.

Parsing is anchored from the RIGHT: the trailing `_s{shard}` (if any) is
stripped first, then the fixed-count integer fields; a name whose
trailing segments carry extra underscores is rejected instead of being
split ambiguously.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..models.errors import ErrorKind, EtlError
from ..models.schema import TableId

SLOT_PREFIX = "supabase_etl"
MAX_SLOT_LEN = 63


def _shard_suffix(shard: int | None) -> str:
    if shard is None:
        return ""
    if shard < 0:
        raise EtlError(ErrorKind.CONFIG_INVALID,
                       f"shard index must be >= 0, got {shard}")
    return f"_s{shard}"


def apply_slot_name(pipeline_id: int, shard: int | None = None) -> str:
    name = f"{SLOT_PREFIX}_apply_{pipeline_id}{_shard_suffix(shard)}"
    _check(name)
    return name


def table_sync_slot_name(pipeline_id: int, table_id: TableId,
                         shard: int | None = None) -> str:
    name = (f"{SLOT_PREFIX}_table_sync_{pipeline_id}_{table_id}"
            f"{_shard_suffix(shard)}")
    _check(name)
    return name


def _check(name: str) -> None:
    if len(name.encode()) > MAX_SLOT_LEN:
        raise EtlError(ErrorKind.SLOT_NAME_TOO_LONG, name)


@dataclass(frozen=True)
class ParsedSlot:
    pipeline_id: int
    table_id: TableId | None  # None = apply slot
    shard: int | None = None  # None = unsharded deployment

    @property
    def is_apply(self) -> bool:
        return self.table_id is None


def _parse_int(token: str) -> int | None:
    """Strict non-negative decimal: int() would also accept '+1', '_',
    and surrounding whitespace, all of which a real slot sweep should
    treat as foreign names, not ours."""
    return int(token) if token.isdigit() else None


def _split_shard(rest: str) -> tuple[str, int | None] | None:
    """Strip a trailing `_s{int}` shard suffix (parsed from the right).
    Returns (remainder, shard) or None when a malformed `_s` suffix is
    present (e.g. `_s` with no digits)."""
    head, sep, tail = rest.rpartition("_")
    if sep and tail.startswith("s"):
        shard = _parse_int(tail[1:])
        if shard is None:
            return None  # `_sXY`: claims the shard shape but isn't one
        return head, shard
    return rest, None


def parse_slot_name(name: str) -> ParsedSlot | None:
    """Parse a framework slot name; None if it isn't ours.

    Round-trip contract (property-tested): for every name produced by
    `apply_slot_name` / `table_sync_slot_name`, parsing returns exactly
    the ids that built it. Fields are consumed from the RIGHT — shard
    suffix, then table id, then pipeline id — so any leftover or extra
    `_`-separated material rejects the name instead of aliasing one
    field into another."""
    if name.startswith(f"{SLOT_PREFIX}_apply_"):
        rest = name[len(f"{SLOT_PREFIX}_apply_"):]
        split = _split_shard(rest)
        if split is None:
            return None
        rest, shard = split
        pid = _parse_int(rest)
        if pid is None:
            return None
        return ParsedSlot(pid, None, shard)
    if name.startswith(f"{SLOT_PREFIX}_table_sync_"):
        rest = name[len(f"{SLOT_PREFIX}_table_sync_"):]
        split = _split_shard(rest)
        if split is None:
            return None
        rest, shard = split
        head, sep, tail = rest.rpartition("_")
        if not sep:
            return None
        pid, tid = _parse_int(head), _parse_int(tail)
        if pid is None or tid is None:
            return None
        return ParsedSlot(pid, tid, shard)
    return None


def slots_for_pipeline(names: list[str], pipeline_id: int,
                       shard: int | None = None) -> list[str]:
    """Cleanup helper: all of a pipeline's slots among `names`. With
    `shard` given, only that shard's slots (an unsharded deployment's
    slots never match a shard filter and vice versa)."""
    out = []
    for n in names:
        p = parse_slot_name(n)
        if p is not None and p.pipeline_id == pipeline_id \
                and (shard is None or p.shard == shard):
            out.append(n)
    return out
