"""Postgres server version constants and gates.

Reference parity: crates/etl-postgres/src/version.rs. Version numbers use
Postgres's internal format `MAJOR * 10000 + MINOR` (e.g. 150004 for 15.4);
officially supported majors are 14 through 18. A version of 0 means
"unknown" and fails every gate — the conservative fallback the reference
gets from `meets_version(None, _) == false`.
"""

from __future__ import annotations

POSTGRES_14 = 140000
POSTGRES_15 = 150000
POSTGRES_16 = 160000
POSTGRES_17 = 170000
POSTGRES_18 = 180000


def meets_version(server_version: int, required: int) -> bool:
    """True when a KNOWN server version meets `required` (unknown = 0 never
    does)."""
    return server_version > 0 and server_version >= required


def parse_server_version(raw: str) -> int:
    """'15.4' → 150004; '16beta1 (Debian...)' → 160000; junk → 0."""
    import re

    m = re.match(r"(\d+)(?:\.(\d+))?", raw.split()[0] if raw else "")
    if not m:
        return 0
    return int(m.group(1)) * 10000 + int(m.group(2) or 0)
