"""ReplicationSource: the seam between the runtime and Postgres.

The apply loop, table-sync workers and pipeline consume this interface; the
wire-protocol client (postgres/client.py) implements it against a real
server, and FakeSource (postgres/fake.py) implements it in-memory with the
same semantics (slots with consistent points, MVCC snapshots at slot
creation, publication filtering) — the substitute for the reference's
real-Postgres integration harness (SURVEY §4.2) in an environment without a
Postgres server.

Reference parity: `PgReplicationClient` surface (crates/etl/src/postgres/
client/raw.rs:212 — slot CRUD with snapshot transactions, publication
queries, START_REPLICATION) and `PgReplicationTransaction` (transaction.rs:
727 — schema introspection, COPY streams, snapshot forking).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import AsyncIterator

from ..models.lsn import Lsn
from ..models.schema import ReplicatedTableSchema, TableId
from .codec.pgoutput import ReplicationFrame


@dataclass(frozen=True)
class SlotInfo:
    name: str
    confirmed_flush_lsn: Lsn
    active: bool = False
    invalidated: bool = False  # wal_status = lost


@dataclass(frozen=True)
class CreatedSlot:
    name: str
    consistent_point: Lsn  # WAL position at slot creation
    snapshot_id: str  # exported snapshot (fake: internal snapshot key)


#: row-message tags that may aggregate into a FrameSpan
_ROW_TAGS = (b"I", b"U", b"D")

#: span length cap: the apply loop's batch-budget check runs once per
#: span, so an unbounded span inside one giant transaction could blow
#: far past max_size_bytes before the next check (the split-at-budget
#: e2e pins the resulting behavior)
SPAN_MAX_ROWS = 1024


class FrameSpan:
    """A contiguous run of row messages (Insert/Update/Delete) for ONE
    table, drained in bulk.

    This is the CDC hot-path unit: the overwhelming majority of WAL
    traffic is runs of row changes for a single table, and handing the
    apply loop one span (relid + raw payloads + int LSNs) instead of
    per-row frame objects removes the per-event allocation and dispatch
    that otherwise caps end-to-end throughput (the reference's analogue
    is a compiled-Rust per-event loop, apply.rs:1280-1336; a Python
    runtime must amortize instead). Control frames (Begin/Commit/
    Relation/Truncate/keepalives) never enter a span — they bound it, so
    transaction state is constant within one."""

    __slots__ = ("relid", "payloads", "start_lsns", "end_lsn")

    def __init__(self, relid: int, payloads: list, start_lsns: list,
                 end_lsn: int):
        self.relid = relid
        self.payloads = payloads  # list[bytes], pgoutput row messages
        self.start_lsns = start_lsns  # list[int], one per payload
        self.end_lsn = end_lsn  # server WAL end at drain time

    def __len__(self) -> int:
        return len(self.payloads)


class ReplicationStream(abc.ABC):
    """The START_REPLICATION copy-both stream: frames down, status up."""

    @abc.abstractmethod
    def __aiter__(self) -> AsyncIterator[ReplicationFrame]: ...

    def drain_buffered(self, max_n: int) -> list:
        """Already-received frames, synchronously (no event-loop round
        trip). Default: none — the apply loop then falls back to one
        awaited frame per select. Implementations override this to lift
        the per-frame asyncio overhead off the CDC hot path."""
        return []

    def drain_spans(self, max_n: int) -> list:
        """Drain buffered traffic as a mixed list of `FrameSpan`s (bulk
        row runs) and individual non-row frames, in WAL order. Default:
        segment `drain_buffered` output host-side; implementations that
        can segment closer to the wire (or skip per-frame objects
        entirely, like the in-memory fake) override this."""
        from .codec.pgoutput import XLogData

        frames = self.drain_buffered(max_n)
        if not frames:
            return frames
        out: list = []
        i, n = 0, len(frames)
        while i < n:
            f = frames[i]
            if type(f) is not XLogData or f.payload[:1] not in _ROW_TAGS:
                out.append(f)
                i += 1
                continue
            relid = int.from_bytes(f.payload[1:5], "big")
            payloads = [f.payload]
            lsns = [int(f.start_lsn)]
            end = int(f.end_lsn)
            j, cap = i + 1, i + SPAN_MAX_ROWS
            while j < n and j < cap:
                g = frames[j]
                if type(g) is not XLogData:
                    break
                p = g.payload
                if p[:1] not in _ROW_TAGS \
                        or int.from_bytes(p[1:5], "big") != relid:
                    break
                payloads.append(p)
                lsns.append(int(g.start_lsn))
                end = int(g.end_lsn)
                j += 1
            out.append(FrameSpan(relid, payloads, lsns, end))
            i = j
        return out

    @abc.abstractmethod
    async def send_status_update(self, written: Lsn, flushed: Lsn,
                                 applied: Lsn,
                                 reply_requested: bool = False) -> None: ...

    @abc.abstractmethod
    async def close(self) -> None: ...


class CopyStream(abc.ABC):
    """COPY TO STDOUT: yields raw text-format chunks (newline-complete)."""

    @abc.abstractmethod
    def __aiter__(self) -> AsyncIterator[bytes]: ...


class ReplicationSource(abc.ABC):
    """One logical connection to the source database."""

    @abc.abstractmethod
    async def connect(self) -> None: ...

    @abc.abstractmethod
    async def close(self) -> None: ...

    # -- catalog -------------------------------------------------------------

    @abc.abstractmethod
    async def publication_exists(self, publication: str) -> bool: ...

    @abc.abstractmethod
    async def get_publication_table_ids(self,
                                        publication: str) -> list[TableId]: ...

    @abc.abstractmethod
    async def get_table_schema(
        self, table_id: TableId, publication: str,
        snapshot_id: str | None = None) -> ReplicatedTableSchema:
        """Schema + replica identity + publication column filters, read in
        the slot snapshot when given (reference transaction.rs:750-768)."""

    async def get_row_filters(self, publication: str) -> "dict[TableId, str]":
        """Publication row-filter SQL per published table (PG15+
        `pg_publication_tables.rowfilter`). The pipeline compiles these
        into the fused decode programs (ops/predicate.py) so filtering
        runs client-side on device — required when the walsender does not
        filter (PG14, or the filter-offload deployment), idempotent when
        it does. Default: none (pre-15 sources)."""
        return {}

    @abc.abstractmethod
    async def get_current_wal_lsn(self) -> Lsn: ...

    # -- source migrations (reference postgres/migrations.rs) ---------------

    @abc.abstractmethod
    async def is_in_recovery(self) -> bool:
        """True on a standby/read replica (pg_is_in_recovery())."""

    @abc.abstractmethod
    async def applied_source_migrations(self) -> "list[str]":
        """Names recorded in etl.source_migrations ([] if absent)."""

    @abc.abstractmethod
    async def apply_source_migration(self, name: str, sql: str) -> None:
        """Run one migration script and record its name."""


    # -- slots ---------------------------------------------------------------

    @abc.abstractmethod
    async def get_slot(self, name: str) -> SlotInfo | None: ...

    @abc.abstractmethod
    async def create_slot(self, name: str) -> CreatedSlot:
        """CREATE_REPLICATION_SLOT ... USE_SNAPSHOT inside a transaction —
        the returned snapshot_id fences table copies against the slot's
        consistent point (reference raw.rs:419-529)."""

    @abc.abstractmethod
    async def delete_slot(self, name: str) -> None:
        """Drop if exists; no error when absent."""

    # -- data ----------------------------------------------------------------

    @abc.abstractmethod
    async def copy_table_stream(self, table_id: TableId, publication: str,
                                snapshot_id: str,
                                ctid_range: "tuple[int, int] | None" = None,
                                publication_table_id: "TableId | None" = None
                                ) -> CopyStream:
        """COPY text stream of the table as of the snapshot; optional CTID
        page range for partitioned parallel copy (transaction.rs:780,868).
        `publication_table_id`: the published relation when it differs from
        the physical one (leaf partitions under
        publish_via_partition_root inherit the root's filters)."""

    @abc.abstractmethod
    async def estimate_table_stats(self, table_id: TableId) -> tuple[int, int]:
        """(estimated_rows, heap_pages) from pg_class for copy planning."""

    async def get_partition_leaves(
            self, table_id: TableId) -> "list[tuple[TableId, int, int]]":
        """Leaf partitions of a partitioned table as (leaf_id, est_rows,
        heap_pages); empty for regular tables. Copy planning weights CTID
        ranges per leaf (reference transaction.rs:808-825,
        copy.rs:457-547)."""
        return []

    @abc.abstractmethod
    async def start_replication(self, slot_name: str, publication: str,
                                start_lsn: Lsn) -> ReplicationStream: ...
