"""PgReplicationClient: ReplicationSource over the wire protocol.

Reference parity: `PgReplicationClient` (crates/etl/src/postgres/client/
raw.rs:212) + `PgReplicationTransaction` (transaction.rs:727):
replication-protocol connections with per-worker application names
(raw.rs:237-270), slot CRUD with exported snapshots (raw.rs:419-529),
publication queries (raw.rs:531-622), schema introspection with replica
identity and PG15 publication column lists (transaction.rs:750-768),
CTID-bounded COPY streams (transaction.rs:780,868), START_REPLICATION with
pgoutput options (raw.rs:623), server version detection (raw.rs:308).
"""

from __future__ import annotations

import logging
import ssl as ssl_mod
import time
from typing import AsyncIterator

from ..config.pipeline import PgConnectionConfig
from ..models.errors import ErrorKind, EtlError
from ..models.lsn import Lsn
from ..models.schema import (ColumnMask, ColumnSchema, ReplicatedTableSchema,
                             TableId, TableName, TableSchema)
from .codec import pgoutput
from .version import POSTGRES_15, meets_version, parse_server_version
from .source import (CopyStream, CreatedSlot, ReplicationSource,
                     ReplicationStream, SlotInfo)
from .wire import PgServerError, PgWireConnection

logger = logging.getLogger("etl_tpu.postgres.client")


def _quote_literal(s: str) -> str:
    return "'" + s.replace("'", "''") + "'"


def wire_connection_from_config(config: PgConnectionConfig, *,
                                application_name: str,
                                replication: bool = False
                                ) -> PgWireConnection:
    """THE connection builder shared by the replication client and the
    PostgresStore: TLS context from config.tls, secret-wrapper password
    unwrapping via .expose() — divergence here means the store and the
    client authenticate differently against the same config."""
    ssl_context = None
    if config.tls.enabled:
        ssl_context = ssl_mod.create_default_context()
        if config.tls.trusted_root_certs:
            ssl_context.load_verify_locations(
                cadata=config.tls.trusted_root_certs)
    password = config.password
    expose = getattr(password, "expose", None)
    return PgWireConnection(
        host=config.host, port=config.port, database=config.name,
        user=config.username, password=expose() if expose else password,
        application_name=application_name, replication=replication,
        ssl_context=ssl_context, connect_timeout_s=config.connect_timeout_s)


class _WireReplicationStream(ReplicationStream):
    def __init__(self, conn: PgWireConnection):
        self._conn = conn
        self._closed = False
        self._pending_error: Exception | None = None

    def __aiter__(self) -> AsyncIterator[pgoutput.ReplicationFrame]:
        return self._frames()

    async def _frames(self):
        while not self._closed:
            if self._pending_error is not None:
                err, self._pending_error = self._pending_error, None
                raise err
            payload = await self._conn.copy_both_read()
            if payload is None:
                return
            yield pgoutput.decode_replication_frame(payload)

    def drain_buffered(self, max_n: int) -> list:
        """Parse CopyData frames already sitting in the stream reader's
        buffer without awaiting — under a WAL burst the socket delivers
        many frames per event-loop wakeup and paying a select() per frame
        caps CDC throughput (CPython StreamReader internals; degrades to
        the awaited path when unavailable)."""
        out: list = []
        if self._pending_error is not None:
            err, self._pending_error = self._pending_error, None
            raise err
        reader = getattr(self._conn, "_reader", None)
        buf = getattr(reader, "_buffer", None)
        if buf is None or self._closed:
            return out
        while len(out) < max_n and len(buf) >= 5:
            length = int.from_bytes(buf[1:5], "big")
            if len(buf) < 1 + length:
                break
            tag = buf[0:1]
            payload = bytes(buf[5 : 1 + length])
            del buf[: 1 + length]
            if tag == b"d":
                out.append(pgoutput.decode_replication_frame(payload))
            elif tag == b"E":
                # do NOT raise here: frames already parsed in this pass
                # were deleted from the reader buffer and would be lost,
                # forcing a restart-from-durable re-delivery. Hand the
                # caller what it has; the stored error surfaces on the
                # next drain/iteration.
                from .wire import PgServerError, _parse_error_fields

                self._pending_error = PgServerError(
                    _parse_error_fields(payload))
                break
            elif tag == b"Z":
                self._closed = True
                break
            # 'c'/'C' and other tags: skip, same as copy_both_read
        getattr(reader, "_maybe_resume_transport", lambda: None)()
        return out

    async def send_status_update(self, written: Lsn, flushed: Lsn,
                                 applied: Lsn,
                                 reply_requested: bool = False) -> None:
        await self._conn.copy_both_send(pgoutput.encode_standby_status_update(
            int(written), int(flushed), int(applied),
            int(time.time() * 1e6), reply_requested))

    async def close(self) -> None:
        self._closed = True
        await self._conn.close()


class _WireCopyStream(CopyStream):
    """Owns its connection; closes it when the COPY ends (or fails)."""

    def __init__(self, conn: PgWireConnection, sql: str):
        self._conn = conn
        self._sql = sql

    def __aiter__(self):
        return self._chunks()

    async def _chunks(self):
        try:
            async for chunk in self._conn.copy_out(self._sql):
                yield chunk
        finally:
            await self._conn.close()


class PgReplicationClient(ReplicationSource):
    """One replication-protocol connection to a real Postgres."""

    def __init__(self, config: PgConnectionConfig, *,
                 application_name: str = "etl_tpu"):
        self.config = config
        self.application_name = application_name
        self._conn: PgWireConnection | None = None
        self.server_version: int = 0  # e.g. 150004

    def _new_conn(self, replication: bool) -> PgWireConnection:
        return wire_connection_from_config(
            self.config, application_name=self.application_name,
            replication=replication)

    @property
    def conn(self) -> PgWireConnection:
        if self._conn is None:
            raise EtlError(ErrorKind.SOURCE_CONNECTION_FAILED,
                           "not connected")
        return self._conn

    async def connect(self) -> None:
        self._conn = self._new_conn(replication=True)
        await self._conn.connect()
        ver = self._conn.parameters.get("server_version", "0")
        self.server_version = parse_server_version(ver)

    async def close(self) -> None:
        if self._conn is not None:
            await self._conn.close()
            self._conn = None

    # -- catalog ----------------------------------------------------------------

    async def publication_exists(self, publication: str) -> bool:
        r = await self.conn.query(
            f"SELECT 1 FROM pg_publication WHERE pubname = "
            f"{_quote_literal(publication)}")
        return bool(r.rows)

    async def get_publication_table_ids(self,
                                        publication: str) -> list[TableId]:
        r = await self.conn.query(
            "SELECT c.oid FROM pg_publication_tables pt "
            "JOIN pg_namespace n ON n.nspname = pt.schemaname "
            "JOIN pg_class c ON c.relnamespace = n.oid "
            "AND c.relname = pt.tablename "
            f"WHERE pt.pubname = {_quote_literal(publication)} "
            "ORDER BY c.oid")
        return [int(row[0]) for row in r.rows]

    async def get_table_schema(self, table_id: TableId, publication: str,
                               snapshot_id: str | None = None
                               ) -> ReplicatedTableSchema:
        # schema + replica identity (reference transaction.rs:750-767)
        r = await self.conn.query(
            "SELECT n.nspname, c.relname, c.relreplident "
            "FROM pg_class c JOIN pg_namespace n ON n.oid = c.relnamespace "
            f"WHERE c.oid = {int(table_id)}")
        if not r.rows:
            raise EtlError(ErrorKind.PUBLICATION_TABLE_MISSING,
                           f"table {table_id}")
        nspname, relname, replident = r.rows[0]
        cols = await self.conn.query(
            "SELECT a.attname, a.atttypid, a.atttypmod, a.attnotnull, "
            "COALESCE(ikey.ord, 0), pg_get_expr(d.adbin, d.adrelid) "
            "FROM pg_attribute a "
            "LEFT JOIN pg_attrdef d ON d.adrelid = a.attrelid "
            "AND d.adnum = a.attnum "
            "LEFT JOIN (SELECT x.attnum_ord AS ord, x.attnum FROM ("
            "  SELECT generate_subscripts(i.indkey, 1) + 1 AS attnum_ord, "
            "         unnest(i.indkey) AS attnum FROM pg_index i "
            f"  WHERE i.indrelid = {int(table_id)} AND i.indisprimary"
            ") x) ikey ON ikey.attnum = a.attnum "
            f"WHERE a.attrelid = {int(table_id)} AND a.attnum > 0 "
            "AND NOT a.attisdropped ORDER BY a.attnum")
        columns = tuple(
            ColumnSchema(
                name=row[0], type_oid=int(row[1]), modifier=int(row[2]),
                nullable=row[3] == "f",
                primary_key_ordinal=int(row[4]) or None,
                default_expression=row[5])
            for row in cols.rows)
        schema = TableSchema(id=table_id,
                             name=TableName(nspname, relname),
                             columns=columns)
        n = len(columns)
        # publication column lists exist only on PG15+ (version gate per
        # reference transaction.rs:268 — pg_publication_tables.attnames is
        # not even a column on 14, the query would error); pre-15 every
        # column replicates
        repl_mask = ColumnMask.all_set(n)
        rowfilter_sql = None
        if meets_version(self.server_version, POSTGRES_15):
            filt = await self.conn.query(
                "SELECT pt.attnames, pt.rowfilter "
                "FROM pg_publication_tables pt "
                "JOIN pg_namespace ns ON ns.nspname = pt.schemaname "
                "JOIN pg_class pc ON pc.relnamespace = ns.oid "
                "AND pc.relname = pt.tablename "
                f"WHERE pt.pubname = {_quote_literal(publication)} "
                f"AND pc.oid = {int(table_id)}")
            if filt.rows and filt.rows[0][0] is not None:
                names = _parse_name_array(filt.rows[0][0])
                if names:
                    repl_mask = ColumnMask.from_column_names(schema, names)
            if filt.rows and len(filt.rows[0]) > 1:
                rowfilter_sql = filt.rows[0][1]
        identity = ColumnMask(c.is_primary_key for c in columns)
        if identity.count() == 0 and replident == "f":
            identity = ColumnMask.all_set(n)
        out = ReplicatedTableSchema(schema, repl_mask, identity)
        if rowfilter_sql:
            # fused decode filtering (ops/predicate.py): the publication's
            # WHERE clause rides the schema so the decoder compiles it
            # into the device program. Unsupported expressions stay
            # server-side only — the walsender filters them on PG15+.
            from ..ops.predicate import RowFilterError, parse_row_filter

            try:
                out = out.with_row_predicate(parse_row_filter(rowfilter_sql))
            except RowFilterError:
                logger.info("row filter %r on table %s is outside the "
                            "client-side envelope; relying on the "
                            "walsender", rowfilter_sql, table_id)
        return out

    async def get_row_filters(self, publication: str) -> "dict[TableId, str]":
        if not meets_version(self.server_version, POSTGRES_15):
            return {}  # row filters were added in Postgres 15
        r = await self.conn.query(
            "SELECT pc.oid, pt.rowfilter FROM pg_publication_tables pt "
            "JOIN pg_namespace ns ON ns.nspname = pt.schemaname "
            "JOIN pg_class pc ON pc.relnamespace = ns.oid "
            "AND pc.relname = pt.tablename "
            f"WHERE pt.pubname = {_quote_literal(publication)}")
        return {int(row[0]): row[1] for row in r.rows
                if len(row) > 1 and row[1]}

    async def get_current_wal_lsn(self) -> Lsn:
        r = await self.conn.query("SELECT pg_current_wal_lsn()")
        return Lsn(r.rows[0][0])

    # -- source migrations (reference postgres/migrations.rs:102-122) --------

    async def is_in_recovery(self) -> bool:
        r = await self.conn.query("SELECT pg_is_in_recovery()")
        return r.rows[0][0] == "t"

    async def applied_source_migrations(self) -> list[str]:
        from .wire import PgServerError

        try:
            r = await self.conn.query(
                "SELECT name FROM etl.source_migrations ORDER BY name")
        except PgServerError as e:
            # only 'relation/schema does not exist' means not-installed;
            # permission or transient errors must NOT trigger a re-run of
            # the migration script (it would fail or double-apply)
            if e.fields.get("C") in ("42P01", "3F000"):
                return []
            raise
        return [row[0] for row in r.rows]

    async def apply_source_migration(self, name: str, sql: str) -> None:
        await self.conn.query(sql)
        await self.conn.query(
            "INSERT INTO etl.source_migrations (name) VALUES "
            f"({_quote_literal(name)}) ON CONFLICT (name) DO NOTHING")

    # -- slots ------------------------------------------------------------------

    async def get_slot(self, name: str) -> SlotInfo | None:
        r = await self.conn.query(
            "SELECT confirmed_flush_lsn, active, "
            "COALESCE(wal_status, 'reserved') FROM pg_replication_slots "
            f"WHERE slot_name = {_quote_literal(name)}")
        if not r.rows:
            return None
        flush, active, wal_status = r.rows[0]
        return SlotInfo(
            name=name,
            confirmed_flush_lsn=Lsn(flush) if flush else Lsn.ZERO,
            active=active == "t",
            invalidated=wal_status == "lost")

    async def create_slot(self, name: str) -> CreatedSlot:
        """CREATE_REPLICATION_SLOT ... EXPORT_SNAPSHOT: the returned
        snapshot name fences copies via SET TRANSACTION SNAPSHOT on child
        connections (reference raw.rs:419-529, transaction.rs:794,827)."""
        r = await self.conn.query(
            f'CREATE_REPLICATION_SLOT "{name}" LOGICAL pgoutput '
            "(SNAPSHOT 'export')")
        row = r.rows[0]
        return CreatedSlot(name=row[0], consistent_point=Lsn(row[1]),
                           snapshot_id=row[2] or "")

    async def delete_slot(self, name: str) -> None:
        try:
            await self.conn.query(f'DROP_REPLICATION_SLOT "{name}" WAIT')
        except PgServerError as e:
            if e.kind is not ErrorKind.SLOT_NOT_FOUND:
                raise

    # -- data -------------------------------------------------------------------

    async def copy_table_stream(self, table_id: TableId, publication: str,
                                snapshot_id: str,
                                ctid_range: "tuple[int, int] | None" = None,
                                publication_table_id: "TableId | None" = None
                                ) -> CopyStream:
        """COPY in a REPEATABLE READ transaction pinned to the exported
        snapshot; fresh connection per stream (copy workers fork children,
        reference copy.rs:346-363). `publication_table_id` names the
        PUBLISHED relation when it differs from the physical one — a leaf
        partition under publish_via_partition_root inherits the root's
        column list and row filter (pg_publication_tables lists only the
        root)."""
        conn = self._new_conn(replication=False)
        await conn.connect()
        try:
            qualified, names, rowfilter = await self._table_and_columns(
                conn, table_id, publication,
                publication_table_id=publication_table_id)
            cols = ", ".join(f'"{c}"' for c in names)
            conds = []
            if ctid_range is not None:
                lo, hi = ctid_range
                conds.append(f"ctid >= '({lo},0)' AND ctid < '({hi},0)'")
            if rowfilter:
                # PG15 publication row filter: the snapshot COPY must apply
                # the same predicate the walsender applies to CDC, or the
                # initial copy includes rows the publication excludes
                # (reference transaction.rs:868)
                conds.append(f"({rowfilter})")
            where = f" WHERE {' AND '.join(conds)}" if conds else ""
            await conn.query(
                "BEGIN ISOLATION LEVEL REPEATABLE READ READ ONLY")
            if snapshot_id:
                await conn.query(
                    f"SET TRANSACTION SNAPSHOT {_quote_literal(snapshot_id)}")
        except BaseException:
            await conn.close()  # don't leak the socket / open transaction
            raise
        sql = f"COPY (SELECT {cols} FROM {qualified}{where}) TO STDOUT"
        return _WireCopyStream(conn, sql)

    async def _table_and_columns(self, conn: PgWireConnection,
                                 table_id: TableId,
                                 publication: str, *,
                                 publication_table_id: "TableId | None" = None
                                 ) -> tuple[str, list[str], "str | None"]:
        r = await conn.query(
            "SELECT n.nspname, c.relname FROM pg_class c "
            "JOIN pg_namespace n ON n.oid = c.relnamespace "
            f"WHERE c.oid = {int(table_id)}")
        if not r.rows:
            raise EtlError(ErrorKind.PUBLICATION_TABLE_MISSING,
                           f"table {table_id}")
        qualified = TableName(r.rows[0][0], r.rows[0][1]).quoted()
        pub_oid = int(publication_table_id
                      if publication_table_id is not None else table_id)
        # attnames/rowfilter are PG15+ columns; on 14 the COPY takes every
        # column and no predicate exists (reference transaction.rs:661:
        # "Row filters on publications were added in Postgres 15")
        ver = parse_server_version(
            conn.parameters.get("server_version", "0"))
        rowfilter = None
        names: list[str] = []
        if meets_version(ver, POSTGRES_15):
            filt = await conn.query(
                "SELECT pt.attnames, pt.rowfilter "
                "FROM pg_publication_tables pt "
                "JOIN pg_namespace ns ON ns.nspname = pt.schemaname "
                "JOIN pg_class pc ON pc.relnamespace = ns.oid "
                "AND pc.relname = pt.tablename "
                f"WHERE pt.pubname = {_quote_literal(publication)} "
                f"AND pc.oid = {pub_oid}")
            rowfilter = filt.rows[0][1] \
                if filt.rows and len(filt.rows[0]) > 1 else None
            if filt.rows and filt.rows[0][0]:
                names = _parse_name_array(filt.rows[0][0])
        if not names:
            cols = await conn.query(
                f"SELECT a.attname FROM pg_attribute a WHERE a.attrelid = "
                f"{int(table_id)} AND a.attnum > 0 AND NOT a.attisdropped "
                "ORDER BY a.attnum")
            names = [row[0] for row in cols.rows]
        return qualified, names, rowfilter

    async def estimate_table_stats(self, table_id: TableId) -> tuple[int, int]:
        r = await self.conn.query(
            "SELECT GREATEST(reltuples::bigint, 0), "
            "GREATEST(relpages::bigint, 1) "
            f"FROM pg_class WHERE oid = {int(table_id)}")
        if not r.rows:
            return 0, 1
        return int(r.rows[0][0]), int(r.rows[0][1])

    async def get_partition_leaves(
            self, table_id: TableId) -> list[tuple[TableId, int, int]]:
        """Leaf partitions with stats for per-leaf copy planning
        (reference transaction.rs:808-825)."""
        r = await self.conn.query(
            "SELECT c.oid, GREATEST(c.reltuples::bigint, 0), "
            "GREATEST(c.relpages::bigint, 1) "
            f"FROM pg_partition_tree({int(table_id)}) pt "
            "JOIN pg_class c ON c.oid = pt.relid "
            "WHERE pt.isleaf AND pt.level > 0 ORDER BY c.oid")
        return [(int(a), int(b), int(c)) for a, b, c in r.rows]

    async def start_replication(self, slot_name: str, publication: str,
                                start_lsn: Lsn) -> ReplicationStream:
        conn = self._new_conn(replication=True)
        await conn.connect()
        try:
            opts = (f"proto_version '2', publication_names "
                    f"{_quote_literal(publication)}, messages 'true'")
            await conn.start_copy_both(
                f'START_REPLICATION SLOT "{slot_name}" LOGICAL '
                f"{start_lsn} ({opts})")
        except BaseException:
            await conn.close()
            raise
        return _WireReplicationStream(conn)


def _parse_name_array(raw) -> list[str]:
    """Parse a pg name[] text literal like '{id,name}'."""
    if isinstance(raw, list):
        return raw
    raw = raw.strip()
    if raw.startswith("{") and raw.endswith("}"):
        inner = raw[1:-1]
        return [p.strip().strip('"') for p in inner.split(",") if p.strip()]
    return []
