"""The workload profile catalog.

A `WorkloadProfile` is a small frozen data object describing one traffic
shape: the column mix of its tables, the seed-row count, the op mix per
transaction (insert/update/delete weights, pk-rekey and TOAST-unchanged
rates), the transaction granularity (many tiny vs one giant), and the
structural stressors (truncate storms, ALTER TABLE churn, partitioned
roots). `generator.WorkloadGenerator` turns a profile + a seed into a
deterministic stream of FakeTransaction commits.

Adding a profile: add an entry to `PROFILES` (and, if it needs a new
column mix, a builder in `COLUMN_MIXES`). Every registered profile is
automatically covered by the determinism and decode round-trip tests in
tests/test_workloads.py — no further wiring needed for `bench.py
--workload <name>`, `python -m etl_tpu.chaos --workload <name>`, or
`devtools serve-source --workload <name>`.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..models.pgtypes import Oid
from ..models.schema import ColumnSchema


def _basic_mix() -> tuple[ColumnSchema, ...]:
    """The pgbench-CDC shape every legacy bench/chaos run used."""
    return (ColumnSchema("id", Oid.INT8, nullable=False,
                         primary_key_ordinal=1),
            ColumnSchema("v", Oid.INT4),
            ColumnSchema("note", Oid.TEXT))


def _wide_mix() -> tuple[ColumnSchema, ...]:
    """120 columns of cycling types (the BASELINE wide-row shape, but
    driven through the full pipeline rather than decode isolation)."""
    kinds = (Oid.INT4, Oid.INT8, Oid.FLOAT8, Oid.TEXT, Oid.BOOL,
             Oid.NUMERIC, Oid.DATE, Oid.TIMESTAMP, Oid.TIMESTAMPTZ,
             Oid.UUID)
    cols = [ColumnSchema("id", Oid.INT8, nullable=False,
                         primary_key_ordinal=1)]
    cols += [ColumnSchema(f"c{i:03d}", kinds[i % len(kinds)])
             for i in range(119)]
    return tuple(cols)


def _numeric_ts_mix() -> tuple[ColumnSchema, ...]:
    """NUMERIC / timestamp dense: the column kinds whose decode is
    heaviest on the host-combine path."""
    cols = [ColumnSchema("id", Oid.INT8, nullable=False,
                         primary_key_ordinal=1)]
    for i in range(6):
        cols.append(ColumnSchema(f"amount{i}", Oid.NUMERIC))
    for i in range(3):
        cols.append(ColumnSchema(f"at{i}", Oid.TIMESTAMPTZ))
    cols.append(ColumnSchema("day", Oid.DATE))
    cols.append(ColumnSchema("ts", Oid.TIMESTAMP))
    return tuple(cols)


def _toast_mix() -> tuple[ColumnSchema, ...]:
    """A fat TEXT column (the TOAST candidate) plus narrow companions."""
    return (ColumnSchema("id", Oid.INT8, nullable=False,
                         primary_key_ordinal=1),
            ColumnSchema("payload", Oid.TEXT),  # the TOASTed column
            ColumnSchema("v", Oid.INT4),
            ColumnSchema("tag", Oid.TEXT))


COLUMN_MIXES = {
    "basic": _basic_mix,
    "wide": _wide_mix,
    "numeric_ts": _numeric_ts_mix,
    "toast": _toast_mix,
}


@dataclass(frozen=True)
class WorkloadProfile:
    """One named traffic shape. All randomness is drawn by the generator
    from its seeded RNG; the profile itself is pure configuration."""

    name: str
    description: str
    column_mix: str = "basic"
    tables: int = 1
    rows_per_table: int = 4  # seed rows copied before CDC starts
    rows_per_tx: int = 4  # row ops per transaction
    txs_per_step: int = 1  # transactions committed per generator step
    # op mix (normalized weights; delete/update apply only while enough
    # rows exist)
    insert_weight: float = 1.0
    update_weight: float = 0.0
    delete_weight: float = 0.0
    # 'd' (default: PK) or 'f' (full) — ALTER TABLE ... REPLICA IDENTITY
    replica_identity: str = "d"
    # fraction of updates that change the PRIMARY KEY (forces the 'K'
    # old-key tuple under default identity and the delete+upsert split
    # at key-aware destinations)
    rekey_rate: float = 0.0
    # fraction of updates that leave the TOAST candidate column unchanged
    # (the walsender then sends the 'u' unchanged-TOAST marker)
    toast_unchanged_rate: float = 0.0
    # every Nth step begins with TRUNCATE of every table, inside the same
    # transaction as the step's inserts (the storm interleaving)
    truncate_every: int | None = None
    # every Nth step runs ALTER TABLE (add/drop a column, alternating)
    # followed by a same-transaction backfill UPDATE of every live row
    ddl_every: int | None = None
    # partitioned root: each table becomes a 2-leaf partitioned table
    # published via the root (publish_via_partition_root)
    partitioned: bool = False
    # deletes never shrink a table below this many rows
    min_rows: int = 2
    # seeded poison-pill rate (docs/dead-letter.md): this fraction of
    # CDC-inserted rows (never seed/copy rows — isolation is a streaming
    # boundary) carry a `POISON-…` marker value in their last TEXT
    # column; the PoisonRejectingDestination refuses any write containing
    # one with DESTINATION_REJECTED, driving the bisection + DLQ path.
    # Only the first `poison_tables` tables are poisoned so survivor
    # tables prove delivery isolation during quarantine.
    poison_rate: float = 0.0
    poison_tables: int = 1
    # publication row filter SQL (PG15 WHERE clause, ops/predicate.py
    # subset) — evaluated CLIENT-SIDE: the generator sets the fake's
    # server_row_filtering=False (the filter-offload deployment), so the
    # walsender ships every row and only the fused decode filter stands
    # between excluded rows and the destination. End-state verification
    # then proves the device-side filter. Filtered profiles must stay
    # insert-only: UPDATE/DELETE row-filter transforms are walsender
    # semantics the client does not re-implement.
    row_filter: str | None = None

    def columns(self):
        return COLUMN_MIXES[self.column_mix]()


PROFILES: dict[str, WorkloadProfile] = {p.name: p for p in (
    WorkloadProfile(
        name="insert_heavy",
        description="pgbench-style insert CDC — the legacy baseline shape",
        insert_weight=1.0, rows_per_tx=8),
    WorkloadProfile(
        name="update_heavy_default",
        description="70% updates under REPLICA IDENTITY DEFAULT; 10% of "
                    "updates re-key the PK (the 'K' old-tuple path)",
        insert_weight=0.2, update_weight=0.7, delete_weight=0.1,
        rekey_rate=0.1, rows_per_table=8, rows_per_tx=6),
    WorkloadProfile(
        name="update_heavy_full",
        description="70% updates under REPLICA IDENTITY FULL (every "
                    "update ships the 'O' full old image)",
        insert_weight=0.2, update_weight=0.7, delete_weight=0.1,
        replica_identity="f", rekey_rate=0.1, rows_per_table=8,
        rows_per_tx=6),
    WorkloadProfile(
        name="delete_heavy_default",
        description="45% deletes under REPLICA IDENTITY DEFAULT (key-only "
                    "'K' delete tuples)",
        insert_weight=0.45, update_weight=0.1, delete_weight=0.45,
        rows_per_table=10, rows_per_tx=6),
    WorkloadProfile(
        name="delete_heavy_full",
        description="45% deletes under REPLICA IDENTITY FULL ('O' full "
                    "old rows on delete)",
        insert_weight=0.45, update_weight=0.1, delete_weight=0.45,
        replica_identity="f", rows_per_table=10, rows_per_tx=6),
    WorkloadProfile(
        name="wide_rows",
        description="120-column mixed-type rows through the full pipeline",
        column_mix="wide", insert_weight=0.6, update_weight=0.35,
        delete_weight=0.05, rows_per_table=4, rows_per_tx=4),
    WorkloadProfile(
        name="toast_heavy_full",
        description="update-heavy with 60% unchanged-TOAST markers under "
                    "REPLICA IDENTITY FULL (old image back-fills)",
        column_mix="toast", insert_weight=0.25, update_weight=0.7,
        delete_weight=0.05, replica_identity="f",
        toast_unchanged_rate=0.6, rows_per_table=6, rows_per_tx=5),
    WorkloadProfile(
        name="toast_heavy_default",
        description="unchanged-TOAST under REPLICA IDENTITY DEFAULT — no "
                    "old image, the column-wise PATCH path",
        column_mix="toast", insert_weight=0.25, update_weight=0.7,
        delete_weight=0.05, toast_unchanged_rate=0.6, rows_per_table=6,
        rows_per_tx=5),
    WorkloadProfile(
        name="numeric_timestamp_dense",
        description="NUMERIC/timestamp-dense columns (host-combine-heavy "
                    "decode mix)",
        column_mix="numeric_ts", insert_weight=0.5, update_weight=0.45,
        delete_weight=0.05, rows_per_table=6, rows_per_tx=5),
    WorkloadProfile(
        name="tiny_txs",
        description="many single-row transactions per step (commit-"
                    "boundary pressure: durable progress per row)",
        insert_weight=0.5, update_weight=0.4, delete_weight=0.1,
        rows_per_table=6, rows_per_tx=1, txs_per_step=8),
    WorkloadProfile(
        name="giant_tx",
        description="one giant transaction per step (run sealing + "
                    "mid-transaction flush splitting)",
        insert_weight=0.6, update_weight=0.3, delete_weight=0.1,
        rows_per_table=8, rows_per_tx=512),
    WorkloadProfile(
        name="truncate_storm",
        description="TRUNCATE interleaved with inserts in the same "
                    "transaction every 3rd step (the barrier ordering "
                    "stress across coalesced columnar batches)",
        insert_weight=0.8, update_weight=0.2, rows_per_table=5,
        rows_per_tx=6, truncate_every=3),
    WorkloadProfile(
        name="ddl_churn",
        description="ALTER TABLE add/drop column every 4th step with a "
                    "same-transaction backfill (mid-stream schema change)",
        insert_weight=0.55, update_weight=0.4, delete_weight=0.05,
        rows_per_table=5, rows_per_tx=4, ddl_every=4),
    # filter-selective family (ROADMAP item 4): the publication predicate
    # drops 90/50/10% of rows ("v" is uniform in [-10^6, 10^6)); the name
    # carries the KEEP percentage. Insert-only by the row_filter contract
    # above; byte-identical (profile, seed) replay holds like every other
    # profile — the filter changes what is DELIVERED, not what is
    # generated.
    WorkloadProfile(
        name="filter_selective_10",
        description="publication row filter keeps ~10% of rows (drops "
                    "90%) — the fused decode filter's best case",
        insert_weight=1.0, rows_per_tx=8, row_filter="v < -800000"),
    WorkloadProfile(
        name="filter_selective_50",
        description="publication row filter keeps ~50% of rows",
        insert_weight=1.0, rows_per_tx=8, row_filter="v < 0"),
    WorkloadProfile(
        name="filter_selective_90",
        description="publication row filter keeps ~90% of rows (drops "
                    "10%) — near-passthrough selectivity",
        insert_weight=1.0, rows_per_tx=8, row_filter="v < 800000"),
    WorkloadProfile(
        name="poison_rows",
        description="insert CDC where a seeded ~0.1% of rows carry a "
                    "POISON marker value the destination rejects "
                    "(DESTINATION_REJECTED) — drives batch bisection, "
                    "the dead-letter store, and per-table quarantine; "
                    "tables beyond the first stay clean as the "
                    "delivery-isolation control group",
        insert_weight=1.0, rows_per_tx=8, tables=3, rows_per_table=4,
        poison_rate=0.001, poison_tables=1),
    WorkloadProfile(
        name="partitioned_root",
        description="2-leaf partitioned tables published via the root "
                    "(publish_via_partition_root leaf→root mapping)",
        insert_weight=0.6, update_weight=0.3, delete_weight=0.1,
        rows_per_table=6, rows_per_tx=5, partitioned=True),
)}


def get_profile(name: str) -> WorkloadProfile:
    try:
        return PROFILES[name]
    except KeyError:
        raise KeyError(f"unknown workload profile {name!r}; known: "
                       f"{', '.join(sorted(PROFILES))}") from None


def profile_names() -> list[str]:
    return sorted(PROFILES)
