"""Seeded adversarial workload generator (ROADMAP item 3).

Every benchmark and chaos scenario used to drive ONE workload shape:
pgbench-style insert CDC. This package generates the traffic real
replication streams are made of — update/delete-heavy under both replica
identities, wide rows, TOAST-heavy, numeric/timestamp-dense, tiny vs
giant transactions, truncate storms, DDL churn, partitioned roots —
through the same `FakeDatabase`/`FakeTransaction` walsender the rest of
the test stack uses.

Determinism contract: one `(profile, seed)` pair replays a byte-identical
WAL payload stream (the generator pins the fake's commit clock and is the
only consumer of its RNG). See docs/workloads.md.
"""

from .generator import (WorkloadGenerator, make_chaos_workload,
                        wal_payloads)
from .profiles import (PROFILES, WorkloadProfile, get_profile,
                       profile_names)

__all__ = [
    "PROFILES",
    "WorkloadGenerator",
    "WorkloadProfile",
    "get_profile",
    "make_chaos_workload",
    "profile_names",
    "wal_payloads",
]
