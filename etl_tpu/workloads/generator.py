"""Deterministic workload generation over the fake walsender.

`WorkloadGenerator(profile, seed=N)` owns every source of randomness for
one run: it seeds `random.Random`, pins the FakeDatabase commit clock,
and draws all row values, op choices, and table choices from that one
stream — so one `(profile, seed)` pair replays a byte-identical WAL
payload sequence (asserted in tests/test_workloads.py).

The generator tracks the committed source truth as it goes (`expected`:
{table_id: {pk: tuple(decoded values)}}, mirroring the fake's storage but
in decoded-cell form), which is exactly what the chaos invariant checker
consumes — so the same object drives `bench.py --workload`, the chaos
corpus × profile matrix, and `devtools serve-source --workload`.
"""

from __future__ import annotations

import random
import uuid as uuid_mod

from ..models.pgtypes import Oid
from ..models.schema import TableName, TableSchema
from ..postgres.codec.text import parse_cell_text
from ..postgres.fake import TOAST_UNCHANGED_VALUE, FakeDatabase
from .profiles import WorkloadProfile, get_profile

BASE_TABLE_ID = 16384
#: leaf partition OIDs live in their own range so a matrix run never
#: collides them with root ids
LEAF_TABLE_BASE = 18000

#: epoch for the pinned commit clock (any fixed value works; this one
#: keeps timestamps in a plausible 2023 range for humans reading traces)
FIXED_CLOCK_US = 1_700_000_000_000_000


def wal_payloads(db: FakeDatabase) -> list[bytes]:
    """The raw pgoutput payload sequence of a fake database's WAL — the
    unit of the byte-identical determinism contract."""
    return [payload for (_, payload, _, _) in db.wal]


class WorkloadGenerator:
    """Incremental workload driver with chaos-runner-compatible shape:
    `build_db()`, `run_tx(db)`, `table_ids`, `expected`, `tx_index`,
    `delivered(dest)` — the same interface the chaos runner's default
    workload exposes."""

    def __init__(self, profile: WorkloadProfile | str, seed: int | None = None,
                 rng: random.Random | None = None):
        if isinstance(profile, str):
            profile = get_profile(profile)
        self.profile = profile
        if rng is None:
            rng = random.Random(f"workload:{profile.name}:{seed}")
        self.rng = rng
        self.table_ids = [BASE_TABLE_ID + i for i in range(profile.tables)]
        # publication row filter (filter_selective_* profiles): the
        # committed-truth filter below and the fake walsender's catalog
        # SQL both derive from ONE parsed IR, so the generator's
        # `expected`, the server's (disabled) WHERE evaluation, and the
        # decoder's fused device filter can never disagree on a verdict
        self.row_filter = None
        self._row_pred = None
        if profile.row_filter:
            from ..ops.predicate import parse_row_filter

            if profile.update_weight or profile.delete_weight \
                    or profile.ddl_every or profile.truncate_every:
                # the client-filter envelope is insert-only (UPDATE/DELETE
                # row-filter transforms are walsender semantics): a
                # filtered profile with mutating traffic would deliver
                # unfiltered U/D batches and silently diverge from
                # `expected` — refuse at construction, not mid-run
                raise ValueError(
                    f"profile {profile.name!r}: row_filter requires an "
                    f"insert-only op mix (docs/workloads.md)")
            self.row_filter = parse_row_filter(profile.row_filter)
        # committed source truth, decoded-cell form (invariant checker's
        # `expected` input)
        self.expected: dict[int, dict[int, tuple]] = \
            {tid: {} for tid in self.table_ids}
        # the same rows in wire-text form (update/backfill ops re-send
        # unchanged columns as text)
        self._text: dict[int, dict[int, list[str | None]]] = \
            {tid: {} for tid in self.table_ids}
        self._schemas: dict[int, TableSchema] = {}
        self._leaves: dict[int, list[int]] = {}  # root -> leaf tids
        self._leaf_of: dict[int, dict[int, int]] = \
            {tid: {} for tid in self.table_ids}  # root -> pk -> leaf
        self._next_pk: dict[int, int] = {tid: 1 for tid in self.table_ids}
        self._ddl_step: dict[int, int] = {tid: 0 for tid in self.table_ids}
        # poison-pill seeding (docs/dead-letter.md): CDC inserts into the
        # first `poison_tables` tables carry a POISON marker at
        # `poison_rate`; seed/copy rows never do (the isolation boundary
        # is streaming CDC). The extra RNG draw happens ONLY for
        # poisoned profiles, so every other profile's byte-identical
        # replay contract is untouched.
        self._poison_tids = set(
            self.table_ids[:profile.poison_tables]) \
            if profile.poison_rate > 0 else set()
        self._seeding = False
        self.poison_pks: dict[int, set[int]] = \
            {tid: set() for tid in self.table_ids}
        self.tx_index = 0  # generator steps completed
        self.row_ops = 0  # Insert/Update/Delete ops committed (bench rate)

    # -- setup ----------------------------------------------------------------

    def build_db(self) -> FakeDatabase:
        p = self.profile
        self._seeding = True  # seed/copy rows are never poisoned
        db = FakeDatabase()
        db.clock_us = FIXED_CLOCK_US
        if p.ddl_every:
            # the DDL event trigger is part of this profile's contract;
            # installing it here (rather than waiting for the pipeline's
            # source migrations) keeps generator-only runs byte-identical
            # to in-pipeline runs
            db.ddl_trigger_installed = True
        for i, tid in enumerate(self.table_ids):
            schema = TableSchema(
                tid, TableName("public", f"wl_{p.name}_{i}"), p.columns())
            self._schemas[tid] = schema
            if self.row_filter is not None and self._row_pred is None:
                # every table shares the profile's column mix, so one
                # compiled text evaluator serves them all
                self._row_pred = self.row_filter.compile_texts(schema)
            seed_rows = []
            for _ in range(p.rows_per_table):
                pk, texts = self._new_row(tid, schema)
                seed_rows.append(texts)
                self._record_row(tid, schema, pk, texts)
            if p.partitioned:
                leaf_ids = [LEAF_TABLE_BASE + 2 * i, LEAF_TABLE_BASE + 2 * i + 1]
                self._leaves[tid] = leaf_ids
                leaves = {}
                for j, leaf in enumerate(leaf_ids):
                    rows = [r for r in seed_rows if int(r[0]) % 2 == j]
                    leaves[leaf] = (f"wl_{p.name}_{i}_p{j}", rows)
                db.create_partitioned_table(schema, leaves)
                for r in seed_rows:
                    pk = int(r[0])
                    self._leaf_of[tid][pk] = leaf_ids[pk % 2]
            else:
                db.create_table(schema, rows=seed_rows)
            if p.replica_identity == "f":
                db.set_replica_identity(tid, "f")
                if p.partitioned:
                    for leaf in self._leaves[tid]:
                        db.set_replica_identity(leaf, "f")
        if self.row_filter is None:
            db.create_publication("pub", list(self.table_ids))
        else:
            # filter-offload deployment: the catalog surfaces the WHERE
            # clause (so the pipeline compiles it into the fused decode
            # program) but the walsender ships EVERY row — delivery can
            # only match `expected` if the client-side filter works
            db.create_publication(
                "pub", list(self.table_ids),
                row_filters={
                    tid: (self.row_filter.sql,
                          self.row_filter.compile_texts(self._schemas[tid]))
                    for tid in self.table_ids})
            db.server_row_filtering = False
        self._seeding = False
        return db

    # -- value generation ------------------------------------------------------

    def _text_for(self, oid: int) -> str:
        rng = self.rng
        if oid in (Oid.INT8, Oid.INT4):
            return str(rng.randrange(-10**6, 10**6))
        if oid == Oid.FLOAT8:
            # dyadic fractions only: every correct parser (host codec,
            # device decode) lands on the identical float64, so the
            # invariant checker's value comparison is exact
            return f"{rng.randrange(-10**6, 10**6)}.{rng.choice(('0', '25', '5', '75'))}"
        if oid == Oid.BOOL:
            return rng.choice(("t", "f"))
        if oid == Oid.NUMERIC:
            return f"{rng.randrange(0, 10**9)}.{rng.randrange(0, 100):02d}"
        if oid == Oid.DATE:
            return f"2024-{rng.randrange(1, 13):02d}-{rng.randrange(1, 29):02d}"
        if oid == Oid.TIMESTAMP:
            return (f"2024-05-{rng.randrange(1, 29):02d} "
                    f"{rng.randrange(0, 24):02d}:{rng.randrange(0, 60):02d}"
                    f":{rng.randrange(0, 60):02d}.{rng.randrange(0, 10**6):06d}")
        if oid == Oid.TIMESTAMPTZ:
            return (f"2024-06-{rng.randrange(1, 29):02d} "
                    f"{rng.randrange(0, 24):02d}:{rng.randrange(0, 60):02d}"
                    f":{rng.randrange(0, 60):02d}.{rng.randrange(0, 10**6):06d}+00")
        if oid == Oid.UUID:
            return str(uuid_mod.UUID(int=rng.getrandbits(128)))
        return f"t-{rng.randrange(10**9)}"  # TEXT and friends

    def _new_row(self, tid: int, schema: TableSchema) -> tuple[int, list]:
        pk = self._next_pk[tid]
        self._next_pk[tid] += 1
        texts: list[str | None] = []
        for c in schema.columns:
            if c.is_primary_key:
                texts.append(str(pk))
            elif c.nullable and self.rng.random() < 0.05:
                texts.append(None)
            else:
                texts.append(self._text_for(c.type_oid))
        if self._poison_tids and not self._seeding \
                and tid in self._poison_tids \
                and self.rng.random() < self.profile.poison_rate:
            for i in range(len(schema.columns) - 1, -1, -1):
                c = schema.columns[i]
                if c.type_oid == Oid.TEXT and not c.is_primary_key:
                    texts[i] = f"POISON-{self.rng.randrange(10**6)}"
                    self.poison_pks[tid].add(pk)
                    break
        return pk, texts

    def _record_row(self, tid: int, schema: TableSchema, pk: int,
                    texts: list) -> None:
        self._text[tid][pk] = list(texts)
        if self._row_pred is not None and not self._row_pred(texts):
            # the publication's row filter excludes this row: the SOURCE
            # stores it (self._text keeps tracking it for update/delete
            # targeting) but it must never be DELIVERED
            self.expected[tid].pop(pk, None)
            return
        self.expected[tid][pk] = tuple(
            parse_cell_text(t, c.type_oid)
            for t, c in zip(texts, schema.columns))

    def _drop_row(self, tid: int, pk: int) -> None:
        del self._text[tid][pk]
        self.expected[tid].pop(pk, None)
        self._leaf_of[tid].pop(pk, None)

    # -- op targets ------------------------------------------------------------

    def _op_table(self, tid: int, pk: int | None) -> int:
        """The physical relation an op targets: the leaf holding `pk` for
        partitioned roots (new pks route by pk % leaves), else the root."""
        leaves = self._leaves.get(tid)
        if not leaves:
            return tid
        if pk is None:
            return tid
        leaf = self._leaf_of[tid].get(pk)
        if leaf is None:
            leaf = leaves[pk % len(leaves)]
            self._leaf_of[tid][pk] = leaf
        return leaf

    def _key_for(self, schema: TableSchema, pk: int) -> list:
        return [str(pk) if c.is_primary_key else None
                for c in schema.columns]

    # -- one step --------------------------------------------------------------

    async def run_tx(self, db: FakeDatabase) -> None:
        """One generator step: `txs_per_step` committed transactions of
        profile-shaped traffic (plus the step's structural stressor —
        truncate storm or DDL churn — when due)."""
        p = self.profile
        step = self.tx_index
        for n in range(p.txs_per_step):
            tid = self.table_ids[self.rng.randrange(len(self.table_ids))]
            schema = self._schemas[tid]
            async with db.transaction() as tx:
                # the structural stressors are PER STEP, not per
                # transaction — only the step's first transaction carries
                # them (a txs_per_step>1 profile would otherwise truncate
                # or ALTER once per transaction)
                if n == 0 and p.truncate_every and step > 0 \
                        and step % p.truncate_every == 0:
                    # truncate THEN insert inside one transaction: the
                    # destination must order the barrier between the
                    # preceding and following coalesced batches
                    tx.truncate(list(self.table_ids))
                    for t2 in self.table_ids:
                        self._text[t2].clear()
                        self.expected[t2].clear()
                        self._leaf_of[t2].clear()
                if n == 0 and p.ddl_every and step > 0 \
                        and step % p.ddl_every == 0:
                    schema = self._run_ddl(tx, tid, schema)
                for _ in range(p.rows_per_tx):
                    self._one_op(tx, tid, schema)
        self.tx_index += 1

    def _run_ddl(self, tx, tid: int, schema: TableSchema) -> TableSchema:
        """ALTER TABLE (add a TEXT column, or drop the last added one,
        alternating) + a same-transaction backfill UPDATE of every live
        row — the add-column-and-backfill migration shape. The backfill
        keeps every row's delivered image at the post-ALTER width, so the
        committed truth stays comparable whether or not a chaos recopy
        lands after the DDL."""
        n = self._ddl_step[tid]
        self._ddl_step[tid] += 1
        base = tuple(schema.columns)
        if n % 2 == 0:
            from ..models.schema import ColumnSchema

            new_schema = TableSchema(
                schema.id, schema.name,
                base + (ColumnSchema(f"x{n // 2}", Oid.TEXT),))
        else:
            # drop the column the previous DDL step added
            new_schema = TableSchema(schema.id, schema.name, base[:-1])
        tx.alter_table(tid, new_schema)
        self._schemas[tid] = new_schema
        old_names = [c.name for c in schema.columns]
        new_cols = new_schema.columns
        for pk in sorted(self._text[tid]):
            old_texts = self._text[tid][pk]
            by_name = dict(zip(old_names, old_texts))
            texts = []
            for c in new_cols:
                if c.name in by_name:
                    texts.append(by_name[c.name])
                else:
                    texts.append(self._text_for(c.type_oid))
            tx.update(self._op_table(tid, pk),
                      self._key_for(new_schema, pk), texts)
            self._record_row(tid, new_schema, pk, texts)
            self.row_ops += 1
        return new_schema

    def _one_op(self, tx, tid: int, schema: TableSchema) -> None:
        p = self.profile
        rng = self.rng
        exp = self._text[tid]
        live = sorted(exp)
        total = p.insert_weight + p.update_weight + p.delete_weight
        roll = rng.random() * total
        if roll < p.delete_weight and len(live) > p.min_rows:
            pk = live[rng.randrange(len(live))]
            tx.delete(self._op_table(tid, pk), self._key_for(schema, pk))
            self._drop_row(tid, pk)
        elif roll < p.delete_weight + p.update_weight and live:
            self._one_update(tx, tid, schema, live)
        else:
            pk, texts = self._new_row(tid, schema)
            tx.insert(self._op_table(tid, pk), texts)
            self._record_row(tid, schema, pk, texts)
        self.row_ops += 1

    def _one_update(self, tx, tid: int, schema: TableSchema,
                    live: list[int]) -> None:
        p = self.profile
        rng = self.rng
        pk = live[rng.randrange(len(live))]
        old_texts = self._text[tid][pk]
        new_pk = pk
        if p.rekey_rate and rng.random() < p.rekey_rate:
            new_pk = self._next_pk[tid]
            self._next_pk[tid] += 1
        toast_cols: set[int] = set()
        if p.toast_unchanged_rate and rng.random() < p.toast_unchanged_rate:
            # leave the TOAST candidate column (the fat TEXT one, index 1
            # in the toast mix) unchanged — the walsender sends 'u'
            toast_cols.add(1)
        values: list = []
        expected_texts: list[str | None] = []
        for i, c in enumerate(schema.columns):
            if c.is_primary_key:
                values.append(str(new_pk))
                expected_texts.append(str(new_pk))
            elif i in toast_cols:
                values.append(TOAST_UNCHANGED_VALUE)
                expected_texts.append(old_texts[i])  # storage keeps it
            else:
                t = self._text_for(c.type_oid)
                values.append(t)
                expected_texts.append(t)
        tx.update(self._op_table(tid, pk), self._key_for(schema, pk),
                  values)
        if new_pk != pk:
            leaf = self._leaf_of[tid].get(pk)
            self._drop_row(tid, pk)
            if leaf is not None:
                # the row object stays in its original leaf (the fake
                # updates rows in place); track the new pk there
                self._leaf_of[tid][new_pk] = leaf
        self._record_row(tid, schema, new_pk, expected_texts)

    # -- verification ----------------------------------------------------------

    def delivered(self, dest) -> bool:
        """True when the destination's reconstructed final view equals the
        committed source truth (same collapse rules as the chaos
        invariant checker)."""
        from ..chaos.invariants import view_matches

        return view_matches(dest, self.table_ids, self.expected)

    def describe(self) -> dict:
        p = self.profile
        return {
            "profile": p.name,
            "column_mix": p.column_mix,
            "tables": p.tables,
            "replica_identity": p.replica_identity,
            "partitioned": p.partitioned,
            "tx_index": self.tx_index,
            "row_ops": self.row_ops,
        }


def make_chaos_workload(profile_name: str,
                        rng: random.Random) -> WorkloadGenerator:
    """The chaos runner's entry point: a generator drawing from the
    scenario's own seeded RNG, so one (scenario, profile, seed) triple
    replays the identical workload and injection interleaving."""
    return WorkloadGenerator(get_profile(profile_name), rng=rng)
