"""Dev automation: `python -m etl_tpu.devtools <command>`.

The xtask analogue (reference crates/xtask: docker Postgres clusters,
chaos injection, pg-fill-table, benchmark orchestration) for an
environment with no docker/k8s: the cluster is the socket-level fake
server, and chaos is driven through its connection-severing hooks.

Commands:
  serve-source   start a fake PG server with N generated rows (the
                 pg-fill-table + `cargo x postgres start` analogue);
                 prints the port and streams CDC traffic if requested
  chaos          run a pipeline over real TCP against the fake server
                 while repeatedly severing every replication stream
                 (NetworkChaos partition analogue), then verify exactly-
                 once delivery to the destination
  fuzz           seeded parser fuzzing (etl_tpu.testing.fuzz)
  bench-compare  diff two benchmark JSON reports (etl_tpu.benchmarks)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def _make_filled_db(n_rows: int, n_tables: int = 1):
    from .models import ColumnSchema, Oid, TableName, TableSchema
    from .postgres.fake import FakeDatabase

    db = FakeDatabase()
    tids = []
    for t in range(n_tables):
        tid = 20000 + t
        db.create_table(TableSchema(
            tid, TableName("public", f"filled_{t}"),
            (ColumnSchema("id", Oid.INT8, nullable=False,
                          primary_key_ordinal=1),
             ColumnSchema("bucket", Oid.INT4),
             ColumnSchema("payload", Oid.TEXT))),
            rows=[[str(i + 1), str(i % 97), f"payload-{t}-{i}" + "x" * 40]
                  for i in range(n_rows)])
        tids.append(tid)
    db.create_publication("pub", tids)
    return db, tids


async def serve_source(args) -> int:
    from .testing.fake_pg_server import FakePgServer

    db, tids = _make_filled_db(args.rows, args.tables)
    server = FakePgServer(db)
    await server.start()
    print(json.dumps({"port": server.port, "publication": "pub",
                      "tables": tids, "rows_per_table": args.rows}))
    if args.cdc_rate > 0:
        i = args.rows
        while True:
            remaining = args.cdc_rate  # full requested rows/second
            while remaining > 0:
                tx = db.transaction()
                for _ in range(min(remaining, 500)):
                    i += 1
                    tx.insert(tids[i % len(tids)],
                              [str(i + 1), str(i % 97), f"cdc-{i}"])
                remaining -= 500
                await tx.commit()
            await asyncio.sleep(1.0)
    await asyncio.Event().wait()
    return 0


async def chaos(args) -> int:
    """Partition chaos: sever every live replication stream every
    `--interval` seconds while CDC flows; at the end, assert the
    destination saw every row exactly once (at-least-once + idempotent
    delivery must collapse to exactly-once in the memory destination's
    event log given slot/progress resume)."""
    from .config import (BatchConfig, BatchEngine, PgConnectionConfig,
                         PipelineConfig, RetryConfig)
    from .destinations import MemoryDestination
    from .models import InsertEvent
    from .postgres.client import PgReplicationClient
    from .runtime import Pipeline, TableStateType
    from .store import NotifyingStore
    from .testing.fake_pg_server import FakePgServer

    db, tids = _make_filled_db(args.rows)
    tid = tids[0]
    server = FakePgServer(db)
    await server.start()
    cfg = PgConnectionConfig(host="127.0.0.1", port=server.port,
                             name="postgres", username="etl")
    store = NotifyingStore()
    dest = MemoryDestination()
    pipeline = Pipeline(
        config=PipelineConfig(
            pipeline_id=1, publication_name="pub", pg_connection=cfg,
            batch=BatchConfig(max_fill_ms=40,
                              batch_engine=BatchEngine(args.engine)),
            apply_retry=RetryConfig(max_attempts=100, initial_delay_ms=50,
                                    max_delay_ms=200)),
        store=store, destination=dest,
        source_factory=lambda: PgReplicationClient(cfg))
    await pipeline.start()
    await asyncio.wait_for(store.notify_on(tid, TableStateType.READY), 60)

    n_cdc = 0
    severs = 0
    deadline = asyncio.get_event_loop().time() + args.seconds
    while asyncio.get_event_loop().time() < deadline:
        tx = db.transaction()
        for _ in range(50):
            n_cdc += 1
            tx.insert(tid, [str(10**6 + n_cdc), "0", f"chaos-{n_cdc}"])
        await tx.commit()
        await asyncio.sleep(args.interval / 2)
        await db.sever_streams()  # the NetworkChaos partition
        severs += 1
        await asyncio.sleep(args.interval / 2)

    def delivered():
        return {e.row.values[0] for e in dest.events
                if isinstance(e, InsertEvent)}

    expected = {10**6 + i for i in range(1, n_cdc + 1)}
    for _ in range(600):
        if delivered() >= expected:
            break
        await asyncio.sleep(0.1)
    got = delivered()
    missing = expected - got
    await pipeline.shutdown_and_wait()
    await server.stop()
    dup_count = sum(
        1 for e in dest.events if isinstance(e, InsertEvent)) - len(got)
    report = {"severs": severs, "cdc_rows": n_cdc,
              "delivered": len(got & expected), "missing": sorted(missing),
              "duplicate_events": dup_count,
              "copied_rows": len(dest.table_rows[tid])}
    print(json.dumps(report))
    if missing or dup_count > 0 or report["copied_rows"] != args.rows:
        print("CHAOS FAILED", file=sys.stderr)
        return 1
    print("chaos OK: no loss across stream partitions", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="etl_tpu.devtools")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("serve-source",
                        help="fake PG server with generated data")
    sp.add_argument("--rows", type=int, default=10_000)
    sp.add_argument("--tables", type=int, default=1)
    sp.add_argument("--cdc-rate", type=int, default=0,
                    help="rows/second of continuous CDC traffic")

    cp = sub.add_parser("chaos", help="stream-partition chaos scenario")
    cp.add_argument("--rows", type=int, default=2_000)
    cp.add_argument("--seconds", type=float, default=10.0)
    cp.add_argument("--interval", type=float, default=1.0)
    cp.add_argument("--engine", default="tpu", choices=["tpu", "cpu"])

    fp = sub.add_parser("fuzz", help="seeded parser fuzzing")
    fp.add_argument("--target", default=None)
    fp.add_argument("--seconds", type=float, default=10.0)
    fp.add_argument("--seed", type=int, default=None)

    bp = sub.add_parser("bench-compare", help="diff two bench reports")
    bp.add_argument("a")
    bp.add_argument("b")
    bp.add_argument("--fail-pct", type=float, default=None)

    args = p.parse_args(argv)
    if args.cmd == "serve-source":
        return asyncio.run(serve_source(args))
    if args.cmd == "chaos":
        return asyncio.run(chaos(args))
    if args.cmd == "fuzz":
        from .testing.fuzz import main as fuzz_main

        fuzz_args = []
        if args.target:
            fuzz_args += ["--target", args.target]
        fuzz_args += ["--seconds", str(args.seconds)]
        if args.seed is not None:
            fuzz_args += ["--seed", str(args.seed)]
        return fuzz_main(fuzz_args)
    if args.cmd == "bench-compare":
        from .benchmarks.compare import main as cmp_main

        cmp_args = [args.a, args.b]
        if args.fail_pct is not None:
            cmp_args += ["--fail-pct", str(args.fail_pct)]
        return cmp_main(cmp_args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
