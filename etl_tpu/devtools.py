"""Dev automation: `python -m etl_tpu.devtools <command>`.

The xtask analogue (reference crates/xtask: docker Postgres clusters,
chaos injection, pg-fill-table, benchmark orchestration) for an
environment with no docker/k8s: the cluster is the socket-level fake
server, and chaos is driven through its connection-severing hooks.

Commands:
  serve-source   start a fake PG server with N generated rows (the
                 pg-fill-table + `cargo x postgres start` analogue);
                 prints the port and streams CDC traffic if requested.
                 `--workload <profile>` serves a named adversarial
                 profile from etl_tpu/workloads instead (update/delete/
                 TOAST/truncate/DDL/partitioned traffic, deterministic
                 per (profile, --seed))
  chaos          run a pipeline over real TCP against the fake server
                 while repeatedly severing every replication stream
                 (NetworkChaos partition analogue), then verify exactly-
                 once delivery to the destination
  fuzz           seeded parser fuzzing (etl_tpu.testing.fuzz)
  bench-compare  diff two benchmark JSON reports (etl_tpu.benchmarks)
  fill-table     bulk-load a table over the wire client — parallel
                 connections, multi-row batches (xtask pg-fill-table)
  rotate-encryption-key  re-encrypt stored control-plane configs under a
                 new AES-GCM key (xtask rotate-encryption-key)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def _make_filled_db(n_rows: int, n_tables: int = 1):
    from .models import ColumnSchema, Oid, TableName, TableSchema
    from .postgres.fake import FakeDatabase

    db = FakeDatabase()
    tids = []
    for t in range(n_tables):
        tid = 20000 + t
        db.create_table(TableSchema(
            tid, TableName("public", f"filled_{t}"),
            (ColumnSchema("id", Oid.INT8, nullable=False,
                          primary_key_ordinal=1),
             ColumnSchema("bucket", Oid.INT4),
             ColumnSchema("payload", Oid.TEXT))),
            rows=[[str(i + 1), str(i % 97), f"payload-{t}-{i}" + "x" * 40]
                  for i in range(n_rows)])
        tids.append(tid)
    db.create_publication("pub", tids)
    return db, tids


async def serve_source(args) -> int:
    from .testing.fake_pg_server import FakePgServer

    gen = None
    if args.workload:
        from .workloads import WorkloadGenerator

        gen = WorkloadGenerator(args.workload, seed=args.seed)
        db, tids = gen.build_db(), gen.table_ids
    else:
        db, tids = _make_filled_db(args.rows, args.tables)
    server = FakePgServer(db)
    await server.start()
    info = {"port": server.port, "publication": "pub"}
    if gen is not None:
        info.update(gen.describe())
        info["seed"] = args.seed
    else:
        info["rows_per_table"] = args.rows
    info["tables"] = tids  # the published table OIDs (roots when partitioned)
    print(json.dumps(info))
    if args.cdc_rate > 0 and gen is not None:
        # profile-shaped CDC: generator steps until ~cdc_rate row ops
        # landed this second (a step's op count varies by profile — a
        # giant_tx step alone is 512 ops)
        while True:
            ops0 = gen.row_ops
            while gen.row_ops - ops0 < args.cdc_rate:
                await gen.run_tx(db)
            await asyncio.sleep(1.0)
    if args.cdc_rate > 0:
        i = args.rows
        while True:
            remaining = args.cdc_rate  # full requested rows/second
            while remaining > 0:
                tx = db.transaction()
                for _ in range(min(remaining, 500)):
                    i += 1
                    tx.insert(tids[i % len(tids)],
                              [str(i + 1), str(i % 97), f"cdc-{i}"])
                remaining -= 500
                await tx.commit()
            await asyncio.sleep(1.0)
    await asyncio.Event().wait()
    return 0


async def _chaos_scenario(args, scenario: str) -> tuple[dict, bool]:
    """One chaos scenario over a live pipeline on real TCP (the Chaos
    Mesh matrix analogue, xtask chaos/scenario.rs: PacketLoss /
    Partition / Latency). Scenarios:

      partition    sever every replication stream each interval
                   (NetworkChaos Partition) — no loss, NO duplicate
                   events;
      latency      route all wire traffic through a TCP proxy adding
                   delay±jitter per chunk (NetworkChaos Latency / tc
                   netem delay) — no loss, no duplicates, just slower;
      corruption   the proxy flips a byte in every Nth server→client
                   chunk (tc netem corrupt): the wire client must
                   surface typed protocol errors and reconnect —
                   no loss, no duplicates;
      copy         partitions injected DURING the initial table copy
                   (sever until the table reaches READY): the copy's
                   crash-marker/fencing must land exactly the source
                   row set, then CDC flows;
      destination  scripted destination faults (reject before apply +
                   fail AFTER apply) — no loss; duplicates are the
                   at-least-once redeliveries idempotent destinations
                   collapse, bounded by the injected fail-after-apply
                   count;
      slot         invalidate the apply slot mid-stream (max_slot_wal_
                   keep_size eviction) with recreate_and_resync — the
                   pipeline must resync and converge with no loss.
    """
    from .config import (BatchConfig, BatchEngine, InvalidatedSlotBehavior,
                         PgConnectionConfig, PipelineConfig, RetryConfig)
    from .destinations import MemoryDestination
    from .destinations.memory import (FaultAction, FaultInjectingDestination,
                                      FaultKind)
    from .models import InsertEvent
    from .postgres.client import PgReplicationClient
    from .runtime import Pipeline, TableStateType
    from .store import NotifyingStore
    from .testing.fake_pg_server import FakePgServer

    from .testing.chaos_proxy import ChaosProxy

    db, tids = _make_filled_db(args.rows)
    tid = tids[0]
    server = FakePgServer(db)
    await server.start()
    proxy: ChaosProxy | None = None
    port = server.port
    if scenario == "latency":
        proxy = ChaosProxy("127.0.0.1", server.port,
                           delay_ms=args.latency_ms,
                           jitter_ms=args.latency_ms / 4)
    elif scenario == "corruption":
        # armed AFTER the initial copy reaches READY (corrupting the
        # copy stream is the `copy` scenario's territory; corrupting
        # every 6th copy chunk would just starve convergence)
        proxy = ChaosProxy("127.0.0.1", server.port)
    elif scenario == "copy":
        proxy = ChaosProxy("127.0.0.1", server.port)
    if proxy is not None:
        await proxy.start()
        port = proxy.port
    cfg = PgConnectionConfig(host="127.0.0.1", port=port,
                             name="postgres", username="etl")
    store = NotifyingStore()
    memory = MemoryDestination()
    dest = memory
    fail_after_applies = 0
    if scenario == "destination":
        dest = FaultInjectingDestination(memory)
    pipeline = Pipeline(
        config=PipelineConfig(
            pipeline_id=1, publication_name="pub", pg_connection=cfg,
            batch=BatchConfig(max_fill_ms=40,
                              batch_engine=BatchEngine(args.engine)),
            apply_retry=RetryConfig(max_attempts=100, initial_delay_ms=50,
                                    max_delay_ms=200),
            invalidated_slot_behavior=
                InvalidatedSlotBehavior.RECREATE_AND_RESYNC),
        store=store, destination=dest,
        source_factory=lambda: PgReplicationClient(cfg))
    ready = store.notify_on(tid, TableStateType.READY)
    await pipeline.start()
    copy_severs = 0
    if scenario == "copy":
        # partition the wire REPEATEDLY while the initial copy runs;
        # stop as soon as the table reaches READY so the run converges
        # tight cadence: the copy has to be HIT while in flight, so
        # sever early and often rather than on the CDC interval
        for _ in range(args.copy_severs):
            if ready.done():
                break
            await asyncio.sleep(0.05)
            if ready.done():
                # READY landed during the sleep: a sever now would hit
                # the CDC stream, not the copy — counting it would
                # false-green the copy_severs > 0 gate
                break
            proxy.sever()
            copy_severs += 1
    await asyncio.wait_for(ready, 120)
    if scenario == "corruption":
        proxy.corrupt_every = 6

    n_cdc = 0
    disruptions = 0
    deadline = asyncio.get_event_loop().time() + args.seconds
    while asyncio.get_event_loop().time() < deadline:
        tx = db.transaction()
        for _ in range(50):
            n_cdc += 1
            tx.insert(tid, [str(10**6 + n_cdc), "0", f"chaos-{n_cdc}"])
        await tx.commit()
        await asyncio.sleep(args.interval / 2)
        disruptions += 1
        if scenario == "partition":
            await db.sever_streams()  # the NetworkChaos partition
        elif scenario in ("latency", "corruption", "copy"):
            # latency/corruption chaos is CONTINUOUS (every forwarded
            # chunk); copy's partitions already happened pre-READY —
            # the loop only produces CDC traffic to converge on
            disruptions -= 1
        elif scenario == "destination":
            # both failure sides of a write: before apply (clean retry)
            # and AFTER apply (forces redelivery of applied events)
            dest.script("write_events", FaultAction(FaultKind.REJECT))
            dest.script("write_events",
                        FaultAction(FaultKind.FAIL_AFTER_APPLY))
            fail_after_applies += 1
        elif scenario == "slot" and disruptions == 2:
            # one mid-stream eviction is the scenario; repeated
            # invalidations would just repeat the same resync
            from .postgres.slots import apply_slot_name

            db.invalidate_slot(apply_slot_name(1))
            await db.sever_streams()
        await asyncio.sleep(args.interval / 2)

    def delivered():
        return {e.row.values[0] for e in memory.events
                if isinstance(e, InsertEvent)}

    def resynced():
        # a slot resync re-copies rows instead of re-streaming them
        return {r.values[0] for r in (memory.table_rows.get(tid) or [])}

    expected = {10**6 + i for i in range(1, n_cdc + 1)}
    for _ in range(600):
        if delivered() | resynced() >= expected:
            break
        await asyncio.sleep(0.1)
    got = delivered() | resynced()
    missing = expected - got
    await pipeline.shutdown_and_wait()
    await server.stop()
    if proxy is not None:
        await proxy.stop()
    dup_count = sum(
        1 for e in memory.events if isinstance(e, InsertEvent)) \
        - len(delivered())
    copied = [r.values[0] for r in (memory.table_rows.get(tid) or [])]
    report = {"scenario": scenario, "disruptions": disruptions,
              "cdc_rows": n_cdc, "delivered": len(got & expected),
              "missing": sorted(missing)[:20],
              "duplicate_events": dup_count}
    if scenario == "partition":
        ok = (not missing and dup_count == 0
              and len(memory.table_rows[tid]) >= args.rows)
    elif scenario == "latency":
        report["delay_ms"] = args.latency_ms
        ok = not missing and dup_count == 0
    elif scenario == "corruption":
        # the proxy must actually have flipped bytes for this run to
        # mean anything; recovery must be loss- and duplicate-free
        report["corrupted_chunks"] = proxy.corrupted
        ok = not missing and dup_count == 0 and proxy.corrupted > 0
    elif scenario == "copy":
        # chaos DURING the copy: partitions were injected pre-READY and
        # the destination's table rows must be EXACTLY the source set —
        # a lost CTID range shows as missing, a refetched one as dupes
        src = set(range(1, args.rows + 1))  # the pre-CDC table content
        report["copy_severs"] = copy_severs
        report["copy_rows"] = len(copied)
        report["copy_dupes"] = len(copied) - len(set(copied))
        ok = (not missing and copy_severs > 0
              and set(copied) == src and len(copied) == args.rows)
    elif scenario == "destination":
        # duplicates are EXPECTED here (fail-after-apply forces
        # redelivery) but must be bounded by the injected faults x batch
        ok = not missing and dup_count <= fail_after_applies * 64
    else:  # slot
        ok = not missing and bool(memory.dropped_tables)
    return report, ok


async def chaos(args) -> int:
    scenarios = (["partition", "latency", "corruption", "copy",
                  "destination", "slot"]
                 if args.scenario == "all" else [args.scenario])
    failed = []
    for sc in scenarios:
        report, ok = await _chaos_scenario(args, sc)
        print(json.dumps(report))
        if not ok:
            failed.append(sc)
    if failed:
        print(f"CHAOS FAILED: {failed}", file=sys.stderr)
        return 1
    print(f"chaos OK: {', '.join(scenarios)} — no loss",
          file=sys.stderr)
    return 0


async def fill_table(args) -> int:
    """Bulk-load a table over the wire client (reference xtask
    pg-fill-table): N parallel connections issuing multi-row INSERT
    literals (the loader owns every value — ids are sequential ints, the
    payload is a fixed [a-z0-9] filler — so literal SQL is the fastest
    correct shape, like the reference's psql COPY feed), until --rows
    rows of --row-bytes payload landed. Prints one JSON line with
    sustained rows/s and bytes/s."""
    import os
    import random
    import time

    from .config.pipeline import PgConnectionConfig
    from .postgres.client import wire_connection_from_config

    cfg = PgConnectionConfig(
        host=args.host, port=args.port, name=args.database,
        username=args.username,
        password=args.password or os.environ.get("POSTGRES_PASSWORD", ""))
    setup = wire_connection_from_config(cfg, application_name="etl_fill")
    await setup.connect()
    await setup.query(
        f"CREATE TABLE IF NOT EXISTS {args.table} ("
        f"id BIGINT PRIMARY KEY, bucket INT, payload TEXT)")
    await setup.close()

    counter = {"rows": 0, "bytes": 0}
    rng = random.Random(11)
    alphabet = "abcdefghijklmnopqrstuvwxyz0123456789"
    filler = "".join(rng.choice(alphabet) for _ in range(args.row_bytes))

    async def worker(wid: int, base: int, n: int) -> None:
        conn = wire_connection_from_config(
            cfg, application_name=f"etl_fill_{wid}")
        await conn.connect()
        done = 0
        while done < n:
            chunk = min(args.batch_rows, n - done)
            values = ", ".join(
                f"({base + done + k + 1}, {(done + k) % 97}, "
                f"'{filler}')" for k in range(chunk))
            await conn.query(
                f"INSERT INTO {args.table} (id, bucket, payload) "
                f"VALUES {values}")
            done += chunk
            counter["rows"] += chunk
            counter["bytes"] += chunk * (args.row_bytes + 16)
        await conn.close()

    per = -(-args.rows // args.parallelism)
    t0 = time.perf_counter()
    await asyncio.gather(*(worker(i, i * per,
                                  min(per, args.rows - i * per))
                           for i in range(args.parallelism)
                           if args.rows - i * per > 0))
    dt = time.perf_counter() - t0
    print(json.dumps({
        "table": args.table, "rows": counter["rows"],
        "bytes": counter["bytes"], "seconds": round(dt, 3),
        "rows_per_sec": round(counter["rows"] / max(dt, 1e-9)),
        "parallelism": args.parallelism}))
    return 0


def rotate_encryption_key(args) -> int:
    """Re-encrypt every stored source/destination config under a new
    primary key (reference xtask rotate-encryption-key). Keys are
    '<id>:<base64-32-bytes>'; rows already on the new key id are left
    untouched, so the command is idempotent and restartable."""
    import sqlite3

    from .api.crypto import ConfigCipher, EncryptionKey

    def parse_key(s: str) -> EncryptionKey:
        kid, _, b64 = s.partition(":")
        return EncryptionKey.from_base64(int(kid), b64)

    new = parse_key(args.new_key)
    olds = [parse_key(s) for s in args.old_key]
    cipher = ConfigCipher(new, olds)
    db = sqlite3.connect(args.db)
    rotated = skipped = 0
    try:
        for table in ("api_sources", "api_destinations"):
            for row_id, enc in db.execute(
                    f"SELECT id, config_enc FROM {table}").fetchall():
                if json.loads(enc).get("key_id") == new.key_id:
                    skipped += 1
                    continue
                db.execute(f"UPDATE {table} SET config_enc = ? WHERE "
                           f"id = ?", (cipher.rotate(enc), row_id))
                rotated += 1
        db.commit()
    finally:
        db.close()
    print(json.dumps({"rotated": rotated, "already_current": skipped,
                      "new_key_id": new.key_id}))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="etl_tpu.devtools")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("serve-source",
                        help="fake PG server with generated data")
    sp.add_argument("--rows", type=int, default=10_000)
    sp.add_argument("--tables", type=int, default=1)
    sp.add_argument("--cdc-rate", type=int, default=0,
                    help="rows/second of continuous CDC traffic (with "
                         "--workload: row OPS/second of profile-shaped "
                         "traffic)")
    sp.add_argument("--workload", default=None, metavar="PROFILE",
                    help="serve a named workload profile from "
                         "etl_tpu/workloads (update/delete/TOAST/"
                         "truncate/DDL/partitioned shapes; see "
                         "docs/workloads.md) instead of generated "
                         "filler rows; --rows/--tables are then owned "
                         "by the profile. Deterministic per "
                         "(profile, --seed)")
    sp.add_argument("--seed", type=int, default=7,
                    help="workload generator seed (with --workload)")

    cp = sub.add_parser("chaos", help="chaos scenario matrix")
    cp.add_argument("--rows", type=int, default=2_000)
    cp.add_argument("--seconds", type=float, default=10.0)
    cp.add_argument("--interval", type=float, default=1.0)
    cp.add_argument("--engine", default="tpu", choices=["tpu", "cpu"])
    cp.add_argument("--scenario", default="partition",
                    choices=["partition", "latency", "corruption",
                             "copy", "destination", "slot", "all"])
    cp.add_argument("--latency-ms", type=float, default=40.0,
                    help="per-chunk proxy delay for --scenario latency")
    cp.add_argument("--copy-severs", type=int, default=3,
                    help="max partitions injected during initial copy")

    fp = sub.add_parser("fuzz", help="seeded parser fuzzing")
    fp.add_argument("--target", default=None)
    fp.add_argument("--seconds", type=float, default=10.0)
    fp.add_argument("--seed", type=int, default=None)

    bp = sub.add_parser("bench-compare", help="diff two bench reports")
    bp.add_argument("a")
    bp.add_argument("b")
    bp.add_argument("--fail-pct", type=float, default=None)

    ft = sub.add_parser("fill-table",
                        help="bulk-load a table over the wire client "
                             "(xtask pg-fill-table)")
    ft.add_argument("--host", default="localhost")
    ft.add_argument("--port", type=int, default=5432)
    ft.add_argument("--database", default="postgres")
    ft.add_argument("--username", default="postgres")
    ft.add_argument("--password", default=None,
                    help="falls back to $POSTGRES_PASSWORD")
    ft.add_argument("--table", required=True)
    ft.add_argument("--rows", type=int, default=100_000)
    ft.add_argument("--row-bytes", type=int, default=256)
    ft.add_argument("--batch-rows", type=int, default=500)
    ft.add_argument("--parallelism", type=int, default=4)

    rk = sub.add_parser("rotate-encryption-key",
                        help="re-encrypt stored configs under a new key")
    rk.add_argument("--db", required=True,
                    help="path to the control-plane sqlite database")
    rk.add_argument("--new-key", required=True,
                    help="'<id>:<base64 32-byte key>' — the new primary")
    rk.add_argument("--old-key", action="append", default=[],
                    help="'<id>:<base64>' decrypt-only key (repeatable)")

    args = p.parse_args(argv)
    if args.cmd == "serve-source":
        return asyncio.run(serve_source(args))
    if args.cmd == "chaos":
        return asyncio.run(chaos(args))
    if args.cmd == "fuzz":
        from .testing.fuzz import main as fuzz_main

        fuzz_args = []
        if args.target:
            fuzz_args += ["--target", args.target]
        fuzz_args += ["--seconds", str(args.seconds)]
        if args.seed is not None:
            fuzz_args += ["--seed", str(args.seed)]
        return fuzz_main(fuzz_args)
    if args.cmd == "bench-compare":
        from .benchmarks.compare import main as cmp_main

        cmp_args = [args.a, args.b]
        if args.fail_pct is not None:
            cmp_args += ["--fail-pct", str(args.fail_pct)]
        return cmp_main(cmp_args)
    if args.cmd == "fill-table":
        return asyncio.run(fill_table(args))
    if args.cmd == "rotate-encryption-key":
        return rotate_encryption_key(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
