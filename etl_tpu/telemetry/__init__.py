"""Telemetry: metrics registry, tracing init, egress accounting."""

from .egress import record_egress
from .metrics import MetricsRegistry, registry
from .tracing import init_tracing, set_error_hook
