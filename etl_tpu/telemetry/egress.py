"""Egress/billing accounting.

Reference parity: `etl_processed_bytes` structured log on destination ack
(crates/etl/src/egress.rs:1-20) with payload accounting via
StreamingPayloadMetadata/TableCopyPayloadMetadata
(source_payload_metadata.rs). Emits both a metric counter and a structured
log record so billing pipelines can consume either."""

from __future__ import annotations

import logging

from .metrics import (ETL_PROCESSED_BYTES_TOTAL, LABEL_DESTINATION,
                      LABEL_PIPELINE_ID, registry)

logger = logging.getLogger("etl_tpu.egress")


def record_egress(*, pipeline_id: int, destination: str, bytes_processed: int,
                  kind: str) -> None:
    """kind: 'table_copy' | 'streaming'. Called on durable destination acks."""
    registry.counter_inc(ETL_PROCESSED_BYTES_TOTAL, bytes_processed, {
        LABEL_PIPELINE_ID: str(pipeline_id),
        LABEL_DESTINATION: destination,
    })
    logger.info("etl_processed_bytes", extra={"fields": {
        "pipeline_id": pipeline_id, "destination": destination,
        "bytes": bytes_processed, "kind": kind}})
