"""Structured logging / tracing initialization.

Reference parity: `init_tracing` (crates/etl-telemetry/src/tracing.rs:272)
— JSON logs in production, pretty in development, with global
project-ref/pipeline-id fields on every record (tracing.rs:95-117). Sentry
capture is represented by an optional error-callback hook (no egress in
this environment).
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Callable


class JsonFormatter(logging.Formatter):
    def __init__(self, static_fields: dict[str, str]):
        super().__init__()
        self.static_fields = static_fields

    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S",
                                time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
            **self.static_fields,
        }
        if record.exc_info and record.exc_info[0] is not None:
            doc["exception"] = self.formatException(record.exc_info)
        extra = getattr(record, "fields", None)
        if isinstance(extra, dict):
            doc.update(extra)
        return json.dumps(doc)


class PrettyFormatter(logging.Formatter):
    def __init__(self, static_fields: dict[str, str]):
        suffix = " ".join(f"{k}={v}" for k, v in static_fields.items())
        fmt = "%(asctime)s %(levelname)-5s %(name)s: %(message)s"
        if suffix:
            fmt += f"  [{suffix}]"
        super().__init__(fmt)


_error_hook: Callable[[logging.LogRecord], None] | None = None


class _HookHandler(logging.Handler):
    def emit(self, record: logging.LogRecord) -> None:
        if _error_hook is not None and record.levelno >= logging.ERROR:
            _error_hook(record)


def set_error_hook(hook: Callable[[logging.LogRecord], None]) -> None:
    """Error capture hook (the Sentry-layer analogue). Self-installing:
    attaches the dispatch handler to the root logger if init_tracing has
    not run yet."""
    global _error_hook
    _error_hook = hook
    root = logging.getLogger()
    if not any(isinstance(h, _HookHandler) for h in root.handlers):
        root.addHandler(_HookHandler())


def init_tracing(*, environment: str = "dev", project_ref: str = "",
                 pipeline_id: int | None = None,
                 level: int = logging.INFO) -> None:
    static: dict[str, str] = {}
    if project_ref:
        static["project"] = project_ref
    if pipeline_id is not None:
        static["pipeline_id"] = str(pipeline_id)
    handler = logging.StreamHandler(sys.stderr)
    if environment in ("prod", "staging"):
        handler.setFormatter(JsonFormatter(static))
    else:
        handler.setFormatter(PrettyFormatter(static))
    root = logging.getLogger()
    root.handlers = [handler, _HookHandler()]
    root.setLevel(level)
