"""Error-notification webhooks.

Reference parity: etl-replicator error notification webhooks
(crates/etl-replicator/src/error_notification.rs) — ERROR-level records
POST a JSON payload to a configured webhook URL, rate-limited, fired
through the tracing error hook so every component participates."""

from __future__ import annotations

import asyncio
import json
import logging
import time

from .tracing import set_error_hook

logger = logging.getLogger("etl_tpu.notify")


class WebhookErrorNotifier:
    def __init__(self, url: str, *, pipeline_id: int | None = None,
                 min_interval_s: float = 30.0):
        self.url = url
        self.pipeline_id = pipeline_id
        self.min_interval_s = min_interval_s
        self._last_sent: float | None = None  # None = never sent
        self._session = None
        self._tasks: set[asyncio.Task] = set()
        self._closed = False

    def install(self) -> None:
        set_error_hook(self._on_error)

    def _on_error(self, record: logging.LogRecord) -> None:
        if record.name.startswith("etl_tpu.notify"):
            return  # never recurse on our own failures
        if self._closed:
            return
        now = time.monotonic()
        if self._last_sent is not None \
                and now - self._last_sent < self.min_interval_s:
            return
        self._last_sent = now
        payload = {
            "pipeline_id": self.pipeline_id,
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
            "ts": time.time(),
        }
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop (e.g. during interpreter shutdown)
        # strong reference: loops hold tasks weakly, and close() must be
        # able to await in-flight posts (the LAST error is the one that
        # matters most)
        task = loop.create_task(self._post(payload))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _post(self, payload: dict) -> None:
        import aiohttp

        try:
            if self._closed:
                return
            if self._session is None:
                self._session = aiohttp.ClientSession()
            async with self._session.post(
                    self.url, json=payload,
                    timeout=aiohttp.ClientTimeout(total=10)) as resp:
                await resp.read()
        except Exception as e:
            logger.warning("error webhook failed: %r", e)

    async def flush(self) -> None:
        """Wait for in-flight notifications (call before teardown)."""
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)

    async def close(self) -> None:
        await self.flush()
        self._closed = True
        if self._session is not None:
            await self._session.close()
            self._session = None
