"""Metrics: registry + Prometheus text exposition.

Reference parity: the `metrics` facade + Prometheus recorder
(crates/etl-telemetry/src/metrics.rs:23-62) and the metric-name constants
(crates/etl/src/observability.rs:7-72). Implemented dependency-free:
counters/gauges/histograms in-process, rendered in Prometheus text format
for the API `/metrics` route and the replicator's endpoint.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from dataclasses import dataclass, field

# --- metric names (reference observability.rs) ------------------------------

ETL_TABLE_COPY_ROWS_TOTAL = "etl_table_copy_rows_total"
# TableRow/PartialTableRow constructions (models/table_row keeps the hot
# counter; publish_table_rows_constructed() mirrors it here). Zero over a
# streamed-CDC window = the egress path stayed columnar fetch-to-wire —
# bench.py --smoke gates on exactly that.
ETL_TABLE_ROWS_CONSTRUCTED_TOTAL = "etl_table_rows_constructed_total"
ETL_TABLE_COPY_BYTES_TOTAL = "etl_table_copy_bytes_total"
ETL_TABLE_COPY_DURATION_SECONDS = "etl_table_copy_duration_seconds"
ETL_TABLE_COPY_END_TO_END_LAG_BYTES = "etl_table_copy_end_to_end_lag_bytes"
ETL_APPLY_LOOP_EVENTS_TOTAL = "etl_apply_loop_events_total"
ETL_APPLY_LOOP_BATCHES_TOTAL = "etl_apply_loop_batches_total"
ETL_APPLY_LOOP_RECEIVED_LAG_BYTES = "etl_apply_loop_received_lag_bytes"
ETL_APPLY_LOOP_FLUSH_LAG_BYTES = "etl_apply_loop_flush_lag_bytes"
ETL_APPLY_LOOP_EFFECTIVE_FLUSH_LAG_BYTES = \
    "etl_apply_loop_effective_flush_lag_bytes"
ETL_APPLY_LOOP_END_TO_END_LAG_BYTES = "etl_apply_loop_end_to_end_lag_bytes"
ETL_TRANSACTION_SIZE_BYTES = "etl_transaction_size_bytes"
ETL_TRANSACTIONS_TOTAL = "etl_transactions_total"
ETL_MEMORY_BACKPRESSURE_ACTIVATIONS_TOTAL = \
    "etl_memory_backpressure_activations_total"
ETL_MEMORY_BACKPRESSURE_ACTIVE = "etl_memory_backpressure_active"
ETL_WORKER_ERRORS_TOTAL = "etl_worker_errors_total"
ETL_SLOT_INVALIDATIONS_TOTAL = "etl_slot_invalidations_total"
ETL_TABLES_TOTAL = "etl_tables_total"
ETL_TABLES_READY = "etl_tables_ready"
ETL_TABLES_ERRORED = "etl_tables_errored"
ETL_DEVICE_DECODE_ROWS_TOTAL = "etl_device_decode_rows_total"
ETL_DEVICE_DECODE_FALLBACK_ROWS_TOTAL = \
    "etl_device_decode_fallback_rows_total"
ETL_DEVICE_DECODE_SECONDS = "etl_device_decode_seconds"
# fused publication row filtering (ops/predicate.py + the fused decode
# program): rows the predicate compacted out of decode output, the bytes
# the packed-result fetch actually moved over the device→host link
# (filtered dispatches fetch a survivor-count-sized slice, so this
# counter — not an assumption — is the evidence that fetched bytes scale
# with selectivity), and the last-batch selectivity (survivors / staged
# rows) of filter-bearing decoders
ETL_DECODE_ROWS_FILTERED_TOTAL = "etl_decode_rows_filtered_total"
ETL_DECODE_FETCHED_BYTES_TOTAL = "etl_decode_fetched_bytes_total"
ETL_DECODE_FILTER_SELECTIVITY = "etl_decode_filter_selectivity"
# decode routing by path (device / host-XLA / per-row oracle): the
# device share is the headline honesty metric for "decode on TPU" —
# benches report it so a host-only steady state can't hide
ETL_DECODE_ROUTED_DEVICE_ROWS_TOTAL = "etl_decode_routed_device_rows_total"
ETL_DECODE_ROUTED_HOST_ROWS_TOTAL = "etl_decode_routed_host_rows_total"
ETL_DECODE_ROUTED_ORACLE_ROWS_TOTAL = "etl_decode_routed_oracle_rows_total"
ETL_PROCESSED_BYTES_TOTAL = "etl_processed_bytes_total"
# decode pipeline stage timings (ops/pipeline.py): pack = host gather into
# the staging arena, dispatch = jit call (device work starts), fetch =
# result wait + unpack/combine. Overlap = seconds of pack time that ran
# while another batch was in flight on the device — the whole point of the
# three-stage scheduler; the ratio gauge is overlap/pack cumulatively.
ETL_DECODE_PACK_SECONDS = "etl_decode_pack_seconds"
ETL_DECODE_DISPATCH_SECONDS = "etl_decode_dispatch_seconds"
ETL_DECODE_FETCH_SECONDS = "etl_decode_fetch_seconds"
ETL_DECODE_PIPELINE_PACK_SECONDS_TOTAL = \
    "etl_decode_pipeline_pack_seconds_total"
ETL_DECODE_PIPELINE_OVERLAP_SECONDS_TOTAL = \
    "etl_decode_pipeline_overlap_seconds_total"
ETL_DECODE_PIPELINE_OVERLAP_RATIO = "etl_decode_pipeline_overlap_ratio"
ETL_DECODE_PIPELINE_IN_FLIGHT = "etl_decode_pipeline_in_flight"
# staging-arena pool (ops/staging.py): hit = a preallocated buffer was
# reused, miss = a fresh allocation (labels: {"result": "hit"|"miss"})
ETL_STAGING_ARENA_REQUESTS_TOTAL = "etl_staging_arena_requests_total"
# mesh-sharded decode (ops/engine.py mesh path): shard count of the last
# sharded dispatch, batches/rows routed through the mesh program, padding
# rows appended by pad_to_multiple so odd buckets shard (the waste-ratio
# gauge is cumulative padded/uploaded — upload bytes are the binding
# resource, so sustained waste above a few percent means the row buckets
# and the mesh size disagree), and the device-reduced per-shard
# fallback-candidate counts (total + a per-shard last-batch gauge; skew
# across shards points at a sick device, not bad data)
ETL_DECODE_MESH_SHARDS = "etl_decode_mesh_shards"
ETL_DECODE_MESH_BATCHES_TOTAL = "etl_decode_mesh_batches_total"
ETL_DECODE_MESH_ROWS_TOTAL = "etl_decode_mesh_rows_total"
ETL_DECODE_MESH_PADDED_ROWS_TOTAL = "etl_decode_mesh_padded_rows_total"
ETL_DECODE_MESH_PAD_WASTE_RATIO = "etl_decode_mesh_pad_waste_ratio"
ETL_DECODE_MESH_FALLBACK_CANDIDATE_ROWS_TOTAL = \
    "etl_decode_mesh_fallback_candidate_rows_total"
ETL_DECODE_MESH_SHARD_FALLBACK_CANDIDATES = \
    "etl_decode_mesh_shard_fallback_candidates"
# fair batch-admission scheduler (ops/pipeline.AdmissionScheduler): N
# decode pipelines sharing one device set. Wait histogram + grant
# counters are labeled per pipeline tenant; starvation grants count the
# aging valve overriding the lag-weighted pick (a tenant waited past the
# starvation deadline); bypass grants count the liveness valve
# (consumer blocked on an undispatched batch, or close) overshooting the
# capacity instead of deadlocking
ETL_DECODE_ADMISSION_WAIT_SECONDS = "etl_decode_admission_wait_seconds"
ETL_DECODE_ADMISSION_GRANTS_TOTAL = "etl_decode_admission_grants_total"
ETL_DECODE_ADMISSION_STARVATION_GRANTS_TOTAL = \
    "etl_decode_admission_starvation_grants_total"
ETL_DECODE_ADMISSION_BYPASS_GRANTS_TOTAL = \
    "etl_decode_admission_bypass_grants_total"
ETL_DECODE_ADMISSION_WAITERS = "etl_decode_admission_waiters"
ETL_DECODE_ADMISSION_IN_FLIGHT = "etl_decode_admission_in_flight"
ETL_DECODE_ADMISSION_TENANTS = "etl_decode_admission_tenants"
# pending catalog-inlined bytes per lake table (reference
# ETL_DUCKLAKE_TABLE_ACTIVE_INLINED_DATA_BYTES, ducklake/inline_size.rs)
ETL_LAKE_INLINED_DATA_BYTES = "etl_lake_inlined_data_bytes"
# Snowpipe channel reopened after a stale continuation token (reference
# ETL_SNOWFLAKE_CHANNEL_RECOVERIES_TOTAL, snowflake/metrics.rs)
ETL_SNOWPIPE_CHANNEL_RECOVERIES_TOTAL = \
    "etl_snowpipe_channel_recoveries_total"
# horizontal scale-out (etl_tpu/sharding): the authoritative topology
# (shard count + epoch), tables-per-shard (labeled per shard — skew means
# the HRW map and the table population disagree), rebalance timings +
# moved-table counts from the two-phase coordinator, and write refusals
# from the shard fence (labeled by reason: not_owned = a routing bug or a
# racing rebalance, epoch_stale = a pod outliving its topology — both
# should be zero in steady state and NONZERO refusals are the fence
# doing its job during a rollout)
ETL_SHARD_COUNT = "etl_shard_count"
ETL_SHARD_EPOCH = "etl_shard_epoch"
ETL_SHARD_TABLES = "etl_shard_tables"
ETL_SHARD_REBALANCE_DURATION_SECONDS = \
    "etl_shard_rebalance_duration_seconds"
ETL_SHARD_REBALANCE_MOVED_TABLES_TOTAL = \
    "etl_shard_rebalance_moved_tables_total"
ETL_SHARD_WRITE_REFUSALS_TOTAL = "etl_shard_write_refusals_total"
# exactly-once delivery (destinations/base.py transactional seam +
# runtime recovery): rows a transactional sink dropped as coordinate
# duplicates of a blind re-stream (label mode=stream|replay), restart
# recoveries that successfully read the sink's high-water mark vs fell
# back to the legacy blind re-stream (the loud-warning degradation,
# labeled by reason: error = typed sink failure after retries, timeout =
# the op bound cut it off), and the high coordinate of the last acked
# transactional commit range — the operator-visible high-water mark
ETL_EXACTLY_ONCE_DEDUP_ROWS_TOTAL = "etl_exactly_once_dedup_rows_total"
ETL_EXACTLY_ONCE_RECOVERIES_TOTAL = "etl_exactly_once_recoveries_total"
ETL_EXACTLY_ONCE_RECOVERY_FALLBACKS_TOTAL = \
    "etl_exactly_once_recovery_fallbacks_total"
ETL_EXACTLY_ONCE_HIGH_WATER_LSN = "etl_exactly_once_high_water_lsn"
# chaos subsystem (etl_tpu/chaos): fault firings per site, per-scenario
# pass/fail, and how long crash→restart recovery took until the workload
# fully re-delivered
ETL_CHAOS_INJECTED_FAULTS_TOTAL = "etl_chaos_injected_faults_total"
ETL_CHAOS_SCENARIOS_TOTAL = "etl_chaos_scenarios_total"
ETL_CHAOS_RECOVERY_DURATION_SECONDS = "etl_chaos_recovery_duration_seconds"
# decode pipeline degraded a batch to the host oracle after a (simulated
# or real) device allocation failure — the OOM-resilience path
ETL_DECODE_DEVICE_OOM_FALLBACKS_TOTAL = \
    "etl_decode_device_oom_fallbacks_total"
# a nonblocking decoder found its host-path program uncompiled and kicked
# the compile to a background thread, decoding the triggering batches on
# the oracle meanwhile (wide schemas compile for tens of seconds — inline
# that would wedge the apply loop into a stall-restart cycle)
ETL_DECODE_BACKGROUND_COMPILES_TOTAL = \
    "etl_decode_background_compiles_total"
# device-resident wire egress (ops/egress.py): batches whose dispatch
# attached device-rendered wire buffers, and destination writes that
# consumed them via the fast assembly path vs fell back to the host
# columnar encoders (label path=device|host)
ETL_EGRESS_DEVICE_BATCHES_TOTAL = "etl_egress_device_batches_total"
ETL_EGRESS_WRITES_TOTAL = "etl_egress_writes_total"
# program store (ops/program_store.py): cache hits by layer (memory =
# the in-process _SHARED_FN_CACHE, disk = a deserialized AOT
# executable), misses by reason (absent = never compiled on this
# version tag, invalid = corrupt/stale file deleted and rebuilt), disk
# load latency, and ACTUAL XLA program builds — the counter the
# warm-restart gates pin at zero (bench.py --coldstart, the chaos
# crash_restart_warm_programs scenario). The canonical-layout gauge is
# the number of distinct padded layouts live in this process: its ratio
# to tables-seen is the compile sharing canonicalization buys.
ETL_COMPILE_CACHE_HITS_TOTAL = "etl_compile_cache_hits_total"
ETL_COMPILE_CACHE_MISSES_TOTAL = "etl_compile_cache_misses_total"
ETL_COMPILE_CACHE_LOAD_SECONDS = "etl_compile_cache_load_seconds"
ETL_PROGRAMS_COMPILED_TOTAL = "etl_programs_compiled_total"
ETL_DECODE_CANONICAL_LAYOUTS = "etl_decode_canonical_layouts"
# closed-loop autoscaling (etl_tpu/autoscale): per-shard replication lag
# as a FIRST-CLASS gauge, sampled on the apply loop's existing
# status-update cadence — the same received−durable number the admission
# weight reads, so the autoscale collector and a human operator stare at
# the identical series (no ad-hoc lag.py query drift). The decision
# metrics mirror the policy's outputs: the last raw rate-model target,
# the aggregate backlog and estimated per-shard drain capacity it was
# computed from, applied decisions by direction (up/down), holds by
# reason (cooldown/band/in_flight/unhealthy), and whether an actuation
# (two-phase rebalance + orchestrator roll) is currently in flight.
ETL_SLOT_LAG_BYTES = "etl_slot_lag_bytes"
ETL_SHARD_DELIVERED_EVENTS = "etl_shard_delivered_events"
ETL_AUTOSCALE_TARGET_SHARDS = "etl_autoscale_target_shards"
ETL_AUTOSCALE_BACKLOG_BYTES = "etl_autoscale_backlog_bytes"
ETL_AUTOSCALE_CAPACITY_BYTES_PER_S = "etl_autoscale_capacity_bytes_per_s"
ETL_AUTOSCALE_DECISIONS_TOTAL = "etl_autoscale_decisions_total"
ETL_AUTOSCALE_HOLDS_TOTAL = "etl_autoscale_holds_total"
ETL_AUTOSCALE_DECISION_IN_FLIGHT = "etl_autoscale_decision_in_flight"
ETL_AUTOSCALE_RESUMES_TOTAL = "etl_autoscale_resumes_total"
# fleet reconciler (etl_tpu/fleet): desired-vs-observed pipeline counts
# and total desired shards per tick, the spec version currently being
# reconciled, applied actuations by verb (create/resize/delete), ticks
# that held a pipeline because a pending journal record was in flight,
# successor resumes by mode (settle = actuation had landed, journal-only;
# redrive = crash before actuation, verb re-driven; abort = spec moved
# on), and a 0/1 converged flag the /fleet endpoint surfaces
ETL_FLEET_PIPELINES_DESIRED = "etl_fleet_pipelines_desired"
ETL_FLEET_PIPELINES_OBSERVED = "etl_fleet_pipelines_observed"
ETL_FLEET_SHARDS_DESIRED = "etl_fleet_shards_desired"
ETL_FLEET_SPEC_VERSION = "etl_fleet_spec_version"
ETL_FLEET_RECONCILE_ACTIONS_TOTAL = "etl_fleet_reconcile_actions_total"
ETL_FLEET_RECONCILE_HOLDS_TOTAL = "etl_fleet_reconcile_holds_total"
ETL_FLEET_RESUMES_TOTAL = "etl_fleet_resumes_total"
ETL_FLEET_CONVERGED = "etl_fleet_converged"
# supervision subsystem (etl_tpu/supervision): watchdog detections by
# kind+component, cancel-and-restart escalations, the pipeline health
# state (0 healthy / 1 degraded / 2 faulted), the oldest heartbeat age
# observed in the last sweep, per-destination breaker state (0 closed /
# 1 half-open / 2 open) + open transitions, and destination calls the
# per-op timeout bound had to cut off
# windowed destination-ack pipeline (runtime/ack_window.py): destination
# writes in flight right now (labeled {"path": "apply"|"copy"} — the
# apply loop's bounded write window vs the per-partition copy window),
# dispatch→durable latency per ack, and the overlap evidence: busy =
# seconds with ≥1 write in flight, overlap = seconds with ≥2 (the time
# the window actually hid ack latency behind later writes). The ratio
# gauge is overlap/busy cumulatively — 0 at window=1 by construction,
# approaching (K-1)/K when a K-deep window stays saturated.
ETL_DESTINATION_ACK_IN_FLIGHT = "etl_destination_ack_in_flight"
ETL_DESTINATION_ACK_LATENCY_SECONDS = "etl_destination_ack_latency_seconds"
ETL_DESTINATION_ACK_BUSY_SECONDS_TOTAL = \
    "etl_destination_ack_busy_seconds_total"
ETL_DESTINATION_ACK_OVERLAP_SECONDS_TOTAL = \
    "etl_destination_ack_overlap_seconds_total"
ETL_DESTINATION_ACK_OVERLAP_RATIO = "etl_destination_ack_overlap_ratio"
# poison-pill isolation + dead-letter store (runtime/poison.py,
# docs/dead-letter.md): isolations run (one per poisoned flush),
# bisection probe writes (the O(log batch) isolation cost — bounded by
# the chaos invariant), rows appended to the DLQ by reason (poison =
# bisected to a poison row; quarantine = parked because the table is
# quarantined), events parked, replay/discard operator actions, and the
# live quarantined-table count
ETL_POISON_ISOLATIONS_TOTAL = "etl_poison_isolations_total"
ETL_POISON_BISECTION_WRITES_TOTAL = "etl_poison_bisection_writes_total"
ETL_DLQ_ENTRIES_TOTAL = "etl_dlq_entries_total"
ETL_DLQ_REPLAYED_TOTAL = "etl_dlq_replayed_total"
ETL_DLQ_DISCARDED_TOTAL = "etl_dlq_discarded_total"
ETL_QUARANTINED_TABLES = "etl_quarantined_tables"
ETL_QUARANTINE_PARKED_EVENTS_TOTAL = "etl_quarantine_parked_events_total"
ETL_SUPERVISION_EVENTS_TOTAL = "etl_supervision_events_total"
ETL_SUPERVISION_RESTARTS_TOTAL = "etl_supervision_restarts_total"
ETL_PIPELINE_HEALTH_STATE = "etl_pipeline_health_state"
ETL_HEARTBEAT_MAX_AGE_SECONDS = "etl_heartbeat_max_age_seconds"
ETL_DESTINATION_BREAKER_STATE = "etl_destination_breaker_state"
ETL_DESTINATION_BREAKER_OPENS_TOTAL = "etl_destination_breaker_opens_total"
ETL_DESTINATION_OP_TIMEOUTS_TOTAL = "etl_destination_op_timeouts_total"

# label keys
LABEL_PIPELINE_ID = "pipeline_id"
LABEL_TABLE = "table"
LABEL_WORKER_TYPE = "worker_type"
LABEL_DESTINATION = "destination"

_HISTOGRAM_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                      30.0, 60.0)

# byte-scale series use byte-scale buckets (the default set is seconds)
_BYTE_BUCKETS = (1 << 10, 16 << 10, 64 << 10, 256 << 10, 1 << 20, 4 << 20,
                 16 << 20, 64 << 20, 256 << 20, 1 << 30)
# decode stages run sub-millisecond on warm paths; the default second-scale
# buckets would collapse every observation into the first bucket
_FINE_TIME_BUCKETS = (0.0002, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                      0.05, 0.1, 0.25, 1.0, 5.0)
_BUCKETS_BY_NAME = {
    "etl_transaction_size_bytes": _BYTE_BUCKETS,
    ETL_DECODE_PACK_SECONDS: _FINE_TIME_BUCKETS,
    ETL_DECODE_DISPATCH_SECONDS: _FINE_TIME_BUCKETS,
    ETL_DECODE_FETCH_SECONDS: _FINE_TIME_BUCKETS,
    # admission waits are sub-millisecond when uncontended and only reach
    # the coarse buckets under real multi-tenant contention
    ETL_DECODE_ADMISSION_WAIT_SECONDS: _FINE_TIME_BUCKETS,
}

LabelSet = tuple[tuple[str, str], ...]


def _labels(labels: dict[str, str] | None) -> LabelSet:
    return tuple(sorted((labels or {}).items()))


@dataclass
class _Histogram:
    bounds: tuple = _HISTOGRAM_BUCKETS
    buckets: list[int] = None  # type: ignore[assignment]
    total: float = 0.0
    count: int = 0

    def __post_init__(self):
        if self.buckets is None:
            self.buckets = [0] * (len(self.bounds) + 1)


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, dict[LabelSet, float]] = defaultdict(dict)
        self._gauges: dict[str, dict[LabelSet, float]] = defaultdict(dict)
        self._histograms: dict[str, dict[LabelSet, _Histogram]] = \
            defaultdict(dict)

    def counter_inc(self, name: str, value: float = 1.0,
                    labels: dict[str, str] | None = None) -> None:
        key = _labels(labels)
        with self._lock:
            self._counters[name][key] = \
                self._counters[name].get(key, 0.0) + value

    def gauge_set(self, name: str, value: float,
                  labels: dict[str, str] | None = None) -> None:
        with self._lock:
            self._gauges[name][_labels(labels)] = value

    def histogram_observe(self, name: str, value: float,
                          labels: dict[str, str] | None = None) -> None:
        key = _labels(labels)
        with self._lock:
            h = self._histograms[name].setdefault(
                key, _Histogram(bounds=_BUCKETS_BY_NAME.get(
                    name, _HISTOGRAM_BUCKETS)))
            h.total += value
            h.count += 1
            for i, b in enumerate(h.bounds):
                if value <= b:
                    h.buckets[i] += 1
                    return
            h.buckets[-1] += 1

    def get_counter(self, name: str,
                    labels: dict[str, str] | None = None) -> float:
        return self._counters.get(name, {}).get(_labels(labels), 0.0)

    def get_gauge(self, name: str,
                  labels: dict[str, str] | None = None) -> float | None:
        return self._gauges.get(name, {}).get(_labels(labels))

    def get_histogram(self, name: str,
                      labels: dict[str, str] | None = None
                      ) -> tuple[int, float]:
        """(count, sum) of one histogram series; (0, 0.0) when unseen —
        benches and tests read stage totals without parsing exposition."""
        h = self._histograms.get(name, {}).get(_labels(labels))
        return (h.count, h.total) if h is not None else (0, 0.0)

    def sum_counter(self, name: str) -> float:
        """Sum of a counter over EVERY label set (per-tenant admission
        counters roll up to a fleet total without the caller enumerating
        tenant names)."""
        with self._lock:
            return sum(self._counters.get(name, {}).values())

    def sum_histogram(self, name: str) -> tuple[int, float]:
        """(count, sum) of a histogram summed over every label set."""
        count, total = 0, 0.0
        with self._lock:
            for h in self._histograms.get(name, {}).values():
                count += h.count
                total += h.total
        return count, total

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out: list[str] = []

        def fmt_labels(key: LabelSet, extra: str = "") -> str:
            parts = [f'{k}="{v}"' for k, v in key]
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}" if parts else ""

        with self._lock:
            for name in sorted(self._counters):
                out.append(f"# TYPE {name} counter")
                for key, v in sorted(self._counters[name].items()):
                    out.append(f"{name}{fmt_labels(key)} {v:g}")
            for name in sorted(self._gauges):
                out.append(f"# TYPE {name} gauge")
                for key, v in sorted(self._gauges[name].items()):
                    out.append(f"{name}{fmt_labels(key)} {v:g}")
            for name in sorted(self._histograms):
                out.append(f"# TYPE {name} histogram")
                for key, h in sorted(self._histograms[name].items()):
                    cum = 0
                    for i, b in enumerate(h.bounds):
                        cum += h.buckets[i]
                        le = f'le="{b:g}"'
                        out.append(
                            f"{name}_bucket{fmt_labels(key, le)} {cum}")
                    cum += h.buckets[-1]
                    inf = 'le="+Inf"'
                    out.append(
                        f"{name}_bucket{fmt_labels(key, inf)} {cum}")
                    out.append(f"{name}_sum{fmt_labels(key)} {h.total:g}")
                    out.append(f"{name}_count{fmt_labels(key)} {h.count}")
        return "\n".join(out) + "\n"


# process-global registry (reference: once-only Prometheus recorder)
registry = MetricsRegistry()


def publish_table_rows_constructed() -> int:
    """Mirror the models/table_row construction counter into the registry
    (the hot path pays a bare list-index increment, not a registry lock;
    scrapes and the bench gates read through here) and return it."""
    from ..models.table_row import rows_constructed

    n = rows_constructed()
    registry.gauge_set(ETL_TABLE_ROWS_CONSTRUCTED_TOTAL, n)
    return n
