"""The unified retry policy: one backoff + classification shape for the
apply worker, table-sync workers, and destination writers.

Before this module each layer carried its own ad-hoc loop:
`RetryConfig.delay_ms` (worker restarts), `DestinationRetryPolicy.delay`
(HTTP writers), and hand-rolled retryable() lambdas per destination.
`RetryPolicy` folds them together:

  - exponential backoff with a multiplier, a delay cap, and bounded
    multiplicative jitter (decorrelates retry herds across workers);
  - per-`ErrorKind` transient/permanent classification. Two granularities
    exist on purpose:
      * `WORKER_TRANSIENT_KINDS` (= models.errors._TIMED_KINDS) — what a
        WORKER may retry by re-streaming from durable progress; includes
        DESTINATION_FAILED because a re-streamed window may succeed
        against a recovered destination;
      * `DESTINATION_TRANSIENT_KINDS` — what a WRITER may retry in place
        (same payload, same call): throttles, connection drops, timeouts.
        DESTINATION_FAILED is deliberately NOT here: an in-place retry of
        the identical request against a destination that REJECTED it
        (4xx-class, schema errors) cannot succeed — that failure
        escalates to the worker loop instead.
  - an `execute()` runner destinations use directly (`with_retries` in
    destinations/util.py delegates here).

ClickHouse (and any HTTP writer) classifies its errors by raising
EtlError kinds mapped from HTTP status; the policy decides
transient/permanent — no per-destination retryable() lambdas.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from typing import Awaitable, Callable, TypeVar

from .models.errors import (ErrorKind, EtlError, RetryKind, _TIMED_KINDS,
                            retry_directive)

T = TypeVar("T")

#: what a worker may retry by re-streaming from durable progress
WORKER_TRANSIENT_KINDS: frozenset[ErrorKind] = _TIMED_KINDS

#: what a destination writer may retry IN PLACE (same request): transient
#: transport/capacity conditions only — rejected payloads escalate
DESTINATION_TRANSIENT_KINDS: frozenset[ErrorKind] = frozenset({
    ErrorKind.DESTINATION_THROTTLED,
    ErrorKind.DESTINATION_CONNECTION_FAILED,
    ErrorKind.TIMEOUT,
})


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + jitter + per-ErrorKind classification."""

    max_attempts: int = 5
    initial_delay_s: float = 0.2
    max_delay_s: float = 10.0
    multiplier: float = 2.0
    jitter: float = 0.2  # multiplicative: delay × (1 + U[0, jitter])
    transient_kinds: frozenset = field(
        default=DESTINATION_TRANSIENT_KINDS)

    @classmethod
    def from_config(cls, rc, *, transient_kinds: frozenset | None = None
                    ) -> "RetryPolicy":
        """Build from a config.RetryConfig (worker retry loops)."""
        return cls(max_attempts=rc.max_attempts,
                   initial_delay_s=rc.initial_delay_ms / 1000,
                   max_delay_s=rc.max_delay_ms / 1000,
                   multiplier=rc.backoff_factor,
                   transient_kinds=transient_kinds
                   if transient_kinds is not None else WORKER_TRANSIENT_KINDS)

    def base_delay(self, attempt: int) -> float:
        """Deterministic backoff for attempt N (0-based), no jitter."""
        return min(self.initial_delay_s * self.multiplier ** attempt,
                   self.max_delay_s)

    def delay(self, attempt: int,
              rng: "random.Random | None" = None) -> float:
        d = self.base_delay(attempt)
        r = rng.random() if rng is not None else random.random()
        return d * (1 + r * self.jitter)

    # -- classification ------------------------------------------------------

    def classify(self, exc: BaseException) -> RetryKind:
        """TIMED = retryable under this policy. EtlErrors start from the
        error-policy directive (models/errors.py); a TIMED directive is
        then narrowed by this policy's transient scope — worker-scoped
        policies keep the directive's full view, writer-scoped ones
        accept only in-place-retryable kinds."""
        if isinstance(exc, EtlError):
            directive = retry_directive(exc)
            if directive.kind is not RetryKind.TIMED:
                return directive.kind
            if self.transient_kinds == WORKER_TRANSIENT_KINDS \
                    or set(exc.kinds()) & self.transient_kinds:
                return RetryKind.TIMED
            return RetryKind.MANUAL
        if isinstance(exc, asyncio.CancelledError):
            return RetryKind.NO_RETRY
        if isinstance(exc, (ConnectionError, OSError, TimeoutError)):
            return RetryKind.TIMED
        # aiohttp client errors without importing aiohttp here
        if type(exc).__module__.startswith("aiohttp"):
            return RetryKind.TIMED
        return RetryKind.MANUAL

    def is_transient(self, exc: BaseException) -> bool:
        return self.classify(exc) is RetryKind.TIMED

    # -- runner --------------------------------------------------------------

    async def execute(self, op: Callable[[], Awaitable[T]],
                      retryable: "Callable[[BaseException], bool] | None"
                      = None) -> T:
        """Classify-and-backoff loop (reference retry.rs:classify). The
        default retryable predicate is `is_transient`; a custom one
        overrides classification but keeps the backoff schedule."""
        should_retry = retryable if retryable is not None \
            else self.is_transient
        last: BaseException | None = None
        for attempt in range(self.max_attempts):
            try:
                return await op()
            except asyncio.CancelledError:
                raise
            except BaseException as e:
                if not should_retry(e) \
                        or attempt + 1 >= self.max_attempts:
                    raise
                last = e
                await asyncio.sleep(self.delay(attempt))
        raise last  # pragma: no cover
