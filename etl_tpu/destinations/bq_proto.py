"""BigQuery Storage Write API wire format, from scratch.

Hand-rolled protobuf wire codec for the surface the reference drives
through gcp_bigquery_client + prost (crates/etl-destinations/src/bigquery/
encoding.rs, client.rs): `AppendRowsRequest` carrying a self-describing
`ProtoSchema` (DescriptorProto) plus per-row serialized proto messages
whose field tags are column ordinals (+1), with the CDC columns
`_CHANGE_TYPE` / `_CHANGE_SEQUENCE_NUMBER` appended after the data
columns — and `AppendRowsResponse` with `google.rpc.Status` errors whose
details may embed `google.cloud.bigquery.storage.v1.StorageError`.

Scalar encodings mirror encoding.rs:120-186 exactly: bool→varint,
i16/i32→int32 varint, i64→int64 varint, u32→uint32 varint, f32→fixed32,
f64→fixed64, timestamptz→int64 micros, and everything date/time/numeric/
uuid/json/interval renders to its Postgres text and encodes as a string.
Arrays use packed encoding for numeric kinds and repeated for strings
(encoding.rs:189-260); NULL array elements are rejected up front, the
validate-then-encode stance of validation.rs.

Transport note: the reference speaks gRPC; this environment has no gRPC
stack, so the client POSTs the SAME serialized AppendRowsRequest bytes as
`application/x-protobuf` and receives serialized AppendRowsResponse bytes.
Framing, descriptors, row bytes, status codes, and error details are the
real wire format — the tests' recording fake decodes and validates them.
"""

from __future__ import annotations

import datetime as dt
import struct
from dataclasses import dataclass, field

from ..models.cell import (JSON_NULL, PgInterval, PgNumeric, PgSpecialDate,
                           PgSpecialTimestamp, PgTimeTz, TOAST_UNCHANGED)
from ..models.errors import ErrorKind, EtlError
from ..models.pgtypes import CellKind, array_element
from ..models.schema import ColumnSchema, ReplicatedTableSchema

# -- protobuf primitives -----------------------------------------------------

_WIRE_VARINT = 0
_WIRE_FIXED64 = 1
_WIRE_LEN = 2
_WIRE_FIXED32 = 5


def _varint(n: int) -> bytes:
    """Unsigned LEB128. Negative int32/int64 values must be passed already
    masked to 64 bits (protobuf sign-extends them to 10 bytes)."""
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _signed(n: int) -> int:
    """Two's-complement 64-bit mask for int32/int64 varint encoding."""
    return n & 0xFFFFFFFFFFFFFFFF


def _key(field_no: int, wire: int) -> bytes:
    return _varint((field_no << 3) | wire)


def f_varint(field_no: int, value: int) -> bytes:
    return _key(field_no, _WIRE_VARINT) + _varint(value)


def f_int(field_no: int, value: int) -> bytes:
    return _key(field_no, _WIRE_VARINT) + _varint(_signed(value))


def f_bytes(field_no: int, data: bytes) -> bytes:
    return _key(field_no, _WIRE_LEN) + _varint(len(data)) + data


def f_string(field_no: int, s: str) -> bytes:
    return f_bytes(field_no, s.encode("utf-8"))


def f_double(field_no: int, v: float) -> bytes:
    return _key(field_no, _WIRE_FIXED64) + struct.pack("<d", v)


def f_float(field_no: int, v: float) -> bytes:
    return _key(field_no, _WIRE_FIXED32) + struct.pack("<f", v)


def parse_message(data: bytes) -> dict[int, list[tuple[int, object]]]:
    """Generic TLV parse: field_no → [(wire_type, value)]. LEN fields give
    bytes; varints give ints; fixed32/64 give raw 4/8 bytes."""
    out: dict[int, list[tuple[int, object]]] = {}
    i, n = 0, len(data)
    while i < n:
        tag = 0
        shift = 0
        while True:
            b = data[i]
            i += 1
            tag |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        field_no, wire = tag >> 3, tag & 7
        if wire == _WIRE_VARINT:
            v = 0
            shift = 0
            while True:
                b = data[i]
                i += 1
                v |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            value: object = v
        elif wire == _WIRE_LEN:
            ln = 0
            shift = 0
            while True:
                b = data[i]
                i += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            value = data[i : i + ln]
            i += ln
        elif wire == _WIRE_FIXED64:
            value = data[i : i + 8]
            i += 8
        elif wire == _WIRE_FIXED32:
            value = data[i : i + 4]
            i += 4
        else:
            raise EtlError(ErrorKind.SERIALIZATION_FAILED,
                           f"unsupported protobuf wire type {wire}")
        out.setdefault(field_no, []).append((wire, value))
    return out


def _first_bytes(msg: dict, field_no: int, default: bytes = b"") -> bytes:
    vals = msg.get(field_no)
    return vals[0][1] if vals else default  # type: ignore[return-value]


def _first_int(msg: dict, field_no: int, default: int = 0) -> int:
    vals = msg.get(field_no)
    return vals[0][1] if vals else default  # type: ignore[return-value]


def _to_i64(v: int) -> int:
    """Undo 64-bit two's complement from a decoded varint."""
    return v - (1 << 64) if v >= (1 << 63) else v


# -- descriptor (ProtoSchema) ------------------------------------------------

# FieldDescriptorProto.Type values
_T_DOUBLE, _T_FLOAT, _T_INT64, _T_INT32 = 1, 2, 3, 5
_T_BOOL, _T_STRING, _T_BYTES, _T_UINT32 = 8, 9, 12, 13
_L_OPTIONAL, _L_REPEATED = 1, 3

CHANGE_TYPE_FIELD = "_CHANGE_TYPE"
CHANGE_SEQUENCE_FIELD = "_CHANGE_SEQUENCE_NUMBER"

# reference schema.rs:246-267 (ColumnType per Postgres type): ints widen to
# int32/int64, floats stay native, timestamptz is instant micros (int64),
# every civil/textual kind is a string, bytea stays bytes
_PROTO_TYPE: dict[CellKind, int] = {
    CellKind.BOOL: _T_BOOL,
    CellKind.I16: _T_INT32, CellKind.I32: _T_INT32,
    CellKind.U32: _T_UINT32, CellKind.I64: _T_INT64,
    CellKind.F32: _T_FLOAT, CellKind.F64: _T_DOUBLE,
    CellKind.TIMESTAMPTZ: _T_INT64,
    CellKind.BYTES: _T_BYTES,
}


def _field_descriptor(name: str, number: int, ftype: int,
                      label: int = _L_OPTIONAL) -> bytes:
    # FieldDescriptorProto: name=1, number=3, label=4, type=5
    return (f_string(1, name) + f_int(3, number) + f_varint(4, label)
            + f_varint(5, ftype))


def row_descriptor(schema: ReplicatedTableSchema,
                   msg_name: str = "TableRow") -> bytes:
    """Serialized DescriptorProto for one table's append rows: data columns
    at ordinal+1, then the two CDC pseudo-columns."""
    fields = []
    for i, col in enumerate(schema.replicated_columns):
        if col.kind is CellKind.ARRAY:
            elem = array_element(col.type_oid)
            etype = _PROTO_TYPE.get(elem[1], _T_STRING) if elem else _T_STRING
            fields.append(_field_descriptor(col.name, i + 1, etype,
                                            _L_REPEATED))
        else:
            fields.append(_field_descriptor(
                col.name, i + 1, _PROTO_TYPE.get(col.kind, _T_STRING)))
    n = len(schema.replicated_columns)
    fields.append(_field_descriptor(CHANGE_TYPE_FIELD, n + 1, _T_STRING))
    fields.append(_field_descriptor(CHANGE_SEQUENCE_FIELD, n + 2, _T_STRING))
    # DescriptorProto: name=1, field=2 (repeated)
    return f_string(1, msg_name) + b"".join(f_bytes(2, f) for f in fields)


# -- row encoding ------------------------------------------------------------


def _text(v) -> str:
    """Postgres text rendering for string-typed proto fields (mirrors the
    Cell::to-string forms of encoding.rs)."""
    if v is JSON_NULL:
        return "null"
    if isinstance(v, (PgNumeric, PgTimeTz, PgInterval, PgSpecialDate,
                      PgSpecialTimestamp)):
        return v.pg_text()
    if isinstance(v, dt.datetime):
        return v.isoformat(sep=" ")
    if isinstance(v, (dt.date, dt.time)):
        return v.isoformat()
    if isinstance(v, (dict, list)):
        import json as _json

        return _json.dumps(v)
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


_EPOCH_UTC = dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc)
_US = dt.timedelta(microseconds=1)


def _tstz_micros(v) -> int:
    """Instant micros for a TIMESTAMPTZ proto field (declared TYPE_INT64 in
    the descriptor). Values with no instant representation — 'infinity' /
    '-infinity' specials — fail fast with a typed error, the reference's
    validate-then-encode stance (validation.rs): emitting a string here
    would violate the carried writer schema.

    Integer arithmetic, not `timestamp()*1e6`: float64 seconds resolve to
    ~0.2 µs at the 2024 epoch, so the float round-trip can flip the last
    microsecond — and the columnar encoder emits the decode engine's EXACT
    stored micros, which the row path must match bit-for-bit."""
    if isinstance(v, dt.datetime):
        if v.tzinfo is None:  # decode always attaches a zone; be safe
            v = v.replace(tzinfo=dt.timezone.utc)
        return (v - _EPOCH_UTC) // _US
    raise EtlError(
        ErrorKind.ROW_CONVERSION_FAILED,
        f"timestamptz value {v!r} has no instant representation for "
        "BigQuery TIMESTAMP (int64 micros)")


def _encode_scalar(tag: int, kind: CellKind, v, out: bytearray) -> None:
    if kind is CellKind.BOOL:
        out += f_varint(tag, 1 if v else 0)
    elif kind in (CellKind.I16, CellKind.I32, CellKind.I64):
        out += f_int(tag, int(v))
    elif kind is CellKind.U32:
        out += f_varint(tag, int(v))
    elif kind is CellKind.F32:
        out += f_float(tag, float(v))
    elif kind is CellKind.F64:
        out += f_double(tag, float(v))
    elif kind is CellKind.TIMESTAMPTZ:
        out += f_int(tag, _tstz_micros(v))
    elif kind is CellKind.BYTES:
        out += f_bytes(tag, bytes(v))
    else:
        out += f_string(tag, _text(v))


_PACKED_KINDS = frozenset({CellKind.BOOL, CellKind.I16, CellKind.I32,
                           CellKind.U32, CellKind.I64, CellKind.F32,
                           CellKind.F64, CellKind.TIMESTAMPTZ})


def _encode_array(tag: int, elem_kind: CellKind, values, out: bytearray,
                  col_name: str) -> None:
    for v in values:
        if v is None:
            raise EtlError(
                ErrorKind.ROW_CONVERSION_FAILED,
                f"array column {col_name} contains a NULL element: "
                "BigQuery REPEATED fields cannot hold NULLs")
    if elem_kind in _PACKED_KINDS and elem_kind not in (CellKind.F32,
                                                        CellKind.F64):
        payload = bytearray()
        for v in values:
            if elem_kind is CellKind.BOOL:
                payload += _varint(1 if v else 0)
            elif elem_kind is CellKind.TIMESTAMPTZ:
                payload += _varint(_signed(_tstz_micros(v)))
            elif elem_kind is CellKind.U32:
                payload += _varint(int(v))
            else:
                payload += _varint(_signed(int(v)))
        out += f_bytes(tag, bytes(payload))
    elif elem_kind is CellKind.F64:
        out += f_bytes(tag, b"".join(struct.pack("<d", float(v))
                                     for v in values))
    elif elem_kind is CellKind.F32:
        out += f_bytes(tag, b"".join(struct.pack("<f", float(v))
                                     for v in values))
    else:  # strings are repeated, never packed
        for v in values:
            out += f_string(tag, _text(v))


def encode_row(schema: ReplicatedTableSchema, values,
               change_type: str, change_sequence: str) -> bytes:
    """One append row: proto message bytes, NULLs omitted (proto3 absence),
    CDC columns last (core.rs:980-996)."""
    out = bytearray()
    cols = schema.replicated_columns
    for i, (col, v) in enumerate(zip(cols, values)):
        if v is None or v is TOAST_UNCHANGED:
            continue
        if col.kind is CellKind.ARRAY:
            elem = array_element(col.type_oid)
            _encode_array(i + 1, elem[1] if elem else CellKind.STRING,
                          v, out, col.name)
        else:
            _encode_scalar(i + 1, col.kind, v, out)
    n = len(cols)
    out += f_string(n + 1, change_type)
    out += f_string(n + 2, change_sequence)
    return bytes(out)


# -- columnar batch encoding (egress hot path) --------------------------------
#
# encode_row materializes a Python value per cell (Column.value boxes dense
# numpy scalars into datetimes/ints) and re-dispatches on CellKind per cell.
# encode_batch serializes column-at-a-time: one kind dispatch per COLUMN,
# dense numpy data encoded straight from the array (ints via tolist —
# already Python ints, no _from_dense boxing; floats sliced out of one
# astype().tobytes() blob; Arrow string columns sliced out of their value
# buffer without creating str objects). Output is byte-identical to
# encode_row over the expanded rows — asserted by the parity suite.

# dense timestamptz sentinels/bounds — the SAME objects _from_dense
# decodes with, so detection can never drift from Column.value()
from ..models.table_row import (DATE_INFINITY_DAYS as _DATE_INF,
                                DATE_NEG_INFINITY_DAYS as _DATE_NEG_INF,
                                MAX_DATE_DAYS as _MAX_DATE_DAYS,
                                MAX_TS_US as _MAX_TS_US,
                                MIN_DATE_DAYS as _MIN_DATE_DAYS,
                                MIN_TS_US as _MIN_TS_US,
                                TS_INFINITY_US as _TS_INF,
                                TS_NEG_INFINITY_US as _TS_NEG_INF)


from ..analysis.annotations import hot_loop


@hot_loop
def _column_cells(col, tag: int, dev=None, untrusted=None) -> list:
    """Encoded proto field bytes per row for one column (None = absent:
    NULL / TOAST-unchanged cells are omitted, proto3 absence). `dev` is
    the column's device-rendered text buffer (ops/egress.py DeviceEgress
    field) when one rode the decoded batch — consumed for the
    string-typed DATE field below, ignored for binary wire types.
    @hot_loop: runs per column per CDC flush — row materialization here
    would undo the columnar egress win (etl-lint rule 13)."""
    import numpy as np

    n = len(col)
    kind = col.schema.kind
    valid = col.validity
    if col.toast_unchanged is not None:
        valid = valid & ~col.toast_unchanged
    cells: list = [None] * n
    present = np.flatnonzero(valid)
    if present.size == 0:
        return cells
    if dev is not None and kind is CellKind.DATE and col.is_dense:
        # device-rendered ISO dates → f_string cells; specials /
        # out-of-range rows (never device-rendered, see egress module
        # docstring) drop to the generic per-value path below
        data = col.data
        ok = ((data != _DATE_INF) & (data != _DATE_NEG_INF)
              & (data >= _MIN_DATE_DAYS) & (data <= _MAX_DATE_DAYS))
        if untrusted is not None and untrusted.size:
            ok = ok.copy()
            ok[untrusted] = False  # fixed up after the device render
        key = _key(tag, _WIRE_LEN)
        buf, lens = dev
        blob = bytes(np.ascontiguousarray(buf).reshape(-1))
        width = buf.shape[1]
        for i in present.tolist():
            if ok[i]:
                ln = int(lens[i])
                cells[i] = key + _varint(ln) \
                    + blob[i * width:i * width + ln]
            else:
                out = bytearray()
                _encode_scalar(tag, kind, col.value(i), out)
                cells[i] = bytes(out)
        return cells
    if col.is_dense and kind is CellKind.BOOL:
        t1 = _key(tag, _WIRE_VARINT) + b"\x01"
        t0 = _key(tag, _WIRE_VARINT) + b"\x00"
        data = col.data
        for i in present.tolist():
            cells[i] = t1 if data[i] else t0
        return cells
    if col.is_dense and kind in (CellKind.I16, CellKind.I32, CellKind.I64):
        prefix = _key(tag, _WIRE_VARINT)
        data = col.data.tolist()
        for i in present.tolist():
            cells[i] = prefix + _varint(data[i] & 0xFFFFFFFFFFFFFFFF)
        return cells
    if col.is_dense and kind is CellKind.U32:
        prefix = _key(tag, _WIRE_VARINT)
        data = col.data.tolist()
        for i in present.tolist():
            cells[i] = prefix + _varint(data[i])
        return cells
    if col.is_dense and kind is CellKind.F64:
        prefix = _key(tag, _WIRE_FIXED64)
        blob = col.data.astype("<f8", copy=False).tobytes()
        for i in present.tolist():
            cells[i] = prefix + blob[8 * i : 8 * i + 8]
        return cells
    if col.is_dense and kind is CellKind.F32:
        prefix = _key(tag, _WIRE_FIXED32)
        blob = col.data.astype("<f4", copy=False).tobytes()
        for i in present.tolist():
            cells[i] = prefix + blob[4 * i : 4 * i + 4]
        return cells
    if col.is_dense and kind is CellKind.TIMESTAMPTZ:
        data = col.data
        sel = data[present]
        bad = ((sel == _TS_INF) | (sel == _TS_NEG_INF)
               | (sel < _MIN_TS_US) | (sel > _MAX_TS_US))
        if bad.any():
            i = int(present[np.flatnonzero(bad)[0]])
            _tstz_micros(col.value(i))  # raises the typed error
        prefix = _key(tag, _WIRE_VARINT)
        vals = data.tolist()
        for i in present.tolist():
            cells[i] = prefix + _varint(vals[i] & 0xFFFFFFFFFFFFFFFF)
        return cells
    if col.is_arrow and kind is CellKind.STRING and col.lazy_text_oid is None:
        cells_from_arrow = _arrow_string_cells(col.data, tag, n)
        if cells_from_arrow is not None:
            for i in present.tolist():
                cells[i] = cells_from_arrow[i]
            return cells
    # generic fallback: box the value and reuse the row-path encoders
    # (NUMERIC/DATE/TIME/TIMESTAMP/JSON/ARRAY/lazy-text columns — exotic
    # kinds keep exact row-path semantics)
    elem = array_element(col.schema.type_oid) if kind is CellKind.ARRAY \
        else None
    for i in present.tolist():
        v = col.value(i)
        if v is None or v is TOAST_UNCHANGED:
            continue
        out = bytearray()
        if kind is CellKind.ARRAY:
            _encode_array(tag, elem[1] if elem else CellKind.STRING, v, out,
                          col.schema.name)
        else:
            _encode_scalar(tag, kind, v, out)
        cells[i] = bytes(out)
    return cells


def _arrow_string_cells(arr, tag: int, n: int):
    """Encoded f_string cells straight from an Arrow StringArray's value
    buffer (no per-row str objects). None when the array layout isn't the
    simple offset-0 form (sliced arrays fall back to the generic path)."""
    import numpy as np

    if arr.offset != 0 or len(arr) != n:
        return None
    bufs = arr.buffers()
    if len(bufs) < 3 or bufs[1] is None or bufs[2] is None:
        return None
    offsets = np.frombuffer(bufs[1], dtype=np.int32, count=n + 1)
    data = bytes(bufs[2])
    cells = [None] * n
    key = _key(tag, _WIRE_LEN)
    o = offsets.tolist()
    for i in range(n):
        lo, hi = o[i], o[i + 1]
        cells[i] = key + _varint(hi - lo) + data[lo:hi]
    return cells


@hot_loop
def encode_batch(schema: ReplicatedTableSchema, batch,
                 change_types: list, change_sequences: list,
                 egress=None) -> list[bytes]:
    """Columnar AppendRows encoding: one serialized proto row per batch
    row, fields in column order then the two CDC pseudo-columns —
    byte-identical to per-row `encode_row` over the same values.
    `change_types` / `change_sequences` are per-row ASCII bytes (see
    util.change_type_batch / util.sequence_number_batch).
    @hot_loop: the BigQuery egress hot path (etl-lint rule 13 guards the
    row path out of it)."""
    n = batch.num_rows
    cols = schema.replicated_columns
    bufs = [bytearray() for _ in range(n)]
    for j, col in enumerate(batch.columns):
        dev = egress.field(j) if egress is not None else None
        cells = _column_cells(col, j + 1, dev,
                              egress.untrusted if egress is not None
                              else None)
        for i, cell in enumerate(cells):
            if cell is not None:
                bufs[i] += cell
    nc = len(cols)
    ct_key = _key(nc + 1, _WIRE_LEN)
    seq_key = _key(nc + 2, _WIRE_LEN)
    out = []
    for i in range(n):
        b = bufs[i]
        ct = change_types[i]
        seq = change_sequences[i]
        b += ct_key + _varint(len(ct)) + ct
        b += seq_key + _varint(len(seq)) + seq
        out.append(bytes(b))
    return out


# -- AppendRows request/response ---------------------------------------------

STORAGE_ERROR_TYPE_URL = (
    "type.googleapis.com/google.cloud.bigquery.storage.v1.StorageError")

# google.cloud.bigquery.storage.v1.StorageError.StorageErrorCode
STORAGE_ERROR_TABLE_NOT_FOUND = 1
STORAGE_ERROR_SCHEMA_MISMATCH_EXTRA_FIELDS = 7

# gRPC status codes (google.rpc.Code)
GRPC_OK = 0
GRPC_CANCELLED = 1
GRPC_INVALID_ARGUMENT = 3
GRPC_DEADLINE_EXCEEDED = 4
GRPC_NOT_FOUND = 5
GRPC_PERMISSION_DENIED = 7
GRPC_RESOURCE_EXHAUSTED = 8
GRPC_FAILED_PRECONDITION = 9
GRPC_ABORTED = 10
GRPC_INTERNAL = 13
GRPC_UNAVAILABLE = 14
GRPC_UNAUTHENTICATED = 16


def append_rows_request(write_stream: str, descriptor: bytes,
                        rows: list[bytes], trace_id: str,
                        offset: int | None = None) -> bytes:
    """Serialized AppendRowsRequest: write_stream=1, offset=2 (Int64Value),
    proto_rows=4 (writer_schema.proto_descriptor + rows.serialized_rows),
    trace_id=6."""
    proto_schema = f_bytes(1, descriptor)  # ProtoSchema.proto_descriptor=1
    proto_rows = b"".join(f_bytes(1, r) for r in rows)  # ProtoRows
    proto_data = f_bytes(1, proto_schema) + f_bytes(2, proto_rows)
    out = f_string(1, write_stream)
    if offset is not None:
        out += f_bytes(2, f_int(1, offset))  # google.protobuf.Int64Value
    out += f_bytes(4, proto_data)
    out += f_string(6, trace_id)
    return out


@dataclass
class DecodedAppendRequest:
    """Fake-server view of one AppendRowsRequest."""

    write_stream: str
    trace_id: str
    descriptor_fields: list[tuple[str, int, int, int]]  # name, number, label, type
    serialized_rows: list[bytes]
    offset: int | None = None

    def decode_rows(self) -> list[dict[str, object]]:
        """Decode each row against the carried descriptor — the framing
        validation a real Storage Write backend performs."""
        by_number = {num: (name, label, ftype)
                     for name, num, label, ftype in self.descriptor_fields}
        rows = []
        for raw in self.serialized_rows:
            msg = parse_message(raw)
            doc: dict[str, object] = {}
            for num, entries in msg.items():
                if num not in by_number:
                    raise EtlError(
                        ErrorKind.SERIALIZATION_FAILED,
                        f"append row has field {num} absent from the "
                        "writer schema")
                name, label, ftype = by_number[num]
                vals = []
                for wire, value in entries:
                    if ftype in (_T_STRING,):
                        vals.append(value.decode("utf-8"))  # type: ignore
                    elif ftype is _T_BYTES:
                        if label == _L_REPEATED and wire == _WIRE_LEN:
                            vals.append(value)
                        else:
                            vals.append(value)
                    elif ftype in (_T_INT32, _T_INT64):
                        if label == _L_REPEATED and wire == _WIRE_LEN:
                            vals.extend(_unpack_varints(value, signed=True))
                        else:
                            vals.append(_to_i64(value))  # type: ignore
                    elif ftype is _T_UINT32:
                        if label == _L_REPEATED and wire == _WIRE_LEN:
                            vals.extend(_unpack_varints(value, signed=False))
                        else:
                            vals.append(value)
                    elif ftype is _T_BOOL:
                        if label == _L_REPEATED and wire == _WIRE_LEN:
                            vals.extend(bool(x) for x in
                                        _unpack_varints(value, signed=False))
                        else:
                            vals.append(bool(value))
                    elif ftype is _T_DOUBLE:
                        if wire == _WIRE_LEN:  # packed
                            vals.extend(struct.unpack(
                                f"<{len(value)//8}d", value))
                        else:
                            vals.append(struct.unpack("<d", value)[0])
                    elif ftype is _T_FLOAT:
                        if wire == _WIRE_LEN:
                            vals.extend(struct.unpack(
                                f"<{len(value)//4}f", value))
                        else:
                            vals.append(struct.unpack("<f", value)[0])
                    else:
                        vals.append(value)
                doc[name] = vals if label == _L_REPEATED or len(vals) > 1 \
                    else vals[0]
            rows.append(doc)
        return rows


def _unpack_varints(data: bytes, signed: bool) -> list[int]:
    out = []
    i, n = 0, len(data)
    while i < n:
        v = 0
        shift = 0
        while True:
            b = data[i]
            i += 1
            v |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        out.append(_to_i64(v) if signed else v)
    return out


def decode_append_rows_request(data: bytes) -> DecodedAppendRequest:
    msg = parse_message(data)
    write_stream = _first_bytes(msg, 1).decode("utf-8")
    trace_id = _first_bytes(msg, 6).decode("utf-8")
    offset = None
    if 2 in msg:
        offset = _to_i64(_first_int(parse_message(_first_bytes(msg, 2)), 1))
    fields: list[tuple[str, int, int, int]] = []
    serialized: list[bytes] = []
    if 4 in msg:
        proto_data = parse_message(_first_bytes(msg, 4))
        if 1 in proto_data:  # writer_schema
            schema_msg = parse_message(_first_bytes(proto_data, 1))
            descriptor = parse_message(_first_bytes(schema_msg, 1))
            for _, fd in descriptor.get(2, []):
                f = parse_message(fd)  # type: ignore[arg-type]
                fields.append((
                    _first_bytes(f, 1).decode("utf-8"),
                    _to_i64(_first_int(f, 3)),
                    _first_int(f, 4, _L_OPTIONAL),
                    _first_int(f, 5, _T_STRING)))
        if 2 in proto_data:  # rows
            rows_msg = parse_message(_first_bytes(proto_data, 2))
            serialized = [v for _, v in rows_msg.get(1, [])]  # type: ignore
    return DecodedAppendRequest(write_stream=write_stream, trace_id=trace_id,
                                descriptor_fields=fields,
                                serialized_rows=serialized, offset=offset)


@dataclass
class RowError:
    index: int
    code: int
    message: str


@dataclass
class RpcStatus:
    code: int
    message: str
    storage_error_codes: list[int] = field(default_factory=list)


@dataclass
class AppendResponse:
    offset: int | None = None
    error: RpcStatus | None = None
    row_errors: list[RowError] = field(default_factory=list)


def encode_rpc_status(code: int, message: str,
                      storage_error_code: int | None = None) -> bytes:
    out = f_int(1, code) + f_string(2, message)
    if storage_error_code is not None:
        detail = f_varint(1, storage_error_code) + f_string(3, message)
        any_msg = f_string(1, STORAGE_ERROR_TYPE_URL) + f_bytes(2, detail)
        out += f_bytes(3, any_msg)
    return out


def encode_append_rows_response(offset: int | None = None,
                                error: bytes | None = None,
                                row_errors: list[RowError] | None = None
                                ) -> bytes:
    out = b""
    if offset is not None:
        out += f_bytes(1, f_bytes(1, f_int(1, offset)))  # AppendResult
    if error is not None:
        out += f_bytes(2, error)
    for re in row_errors or []:
        out += f_bytes(4, f_int(1, re.index) + f_varint(2, re.code)
                       + f_string(3, re.message))
    return out


def decode_append_rows_response(data: bytes) -> AppendResponse:
    msg = parse_message(data)
    resp = AppendResponse()
    if 1 in msg:
        result = parse_message(_first_bytes(msg, 1))
        if 1 in result:
            resp.offset = _to_i64(
                _first_int(parse_message(_first_bytes(result, 1)), 1))
    if 2 in msg:
        status = parse_message(_first_bytes(msg, 2))
        codes = []
        for _, any_bytes in status.get(3, []):
            any_msg = parse_message(any_bytes)  # type: ignore[arg-type]
            if _first_bytes(any_msg, 1).decode("utf-8") \
                    == STORAGE_ERROR_TYPE_URL:
                storage_err = parse_message(_first_bytes(any_msg, 2))
                codes.append(_first_int(storage_err, 1))
        resp.error = RpcStatus(
            code=_to_i64(_first_int(status, 1)),
            message=_first_bytes(status, 2).decode("utf-8"),
            storage_error_codes=codes)
    for _, re_bytes in msg.get(4, []):
        re_msg = parse_message(re_bytes)  # type: ignore[arg-type]
        resp.row_errors.append(RowError(
            index=_to_i64(_first_int(re_msg, 1)),
            code=_first_int(re_msg, 2),
            message=_first_bytes(re_msg, 3).decode("utf-8")))
    return resp
