"""BigQuery destination: Storage-Write-style CDC appends.

Reference parity: crates/etl-destinations/src/bigquery/ (6.6k LoC):
  - CDC appends carrying `_CHANGE_TYPE` (UPSERT/DELETE) and
    `_CHANGE_SEQUENCE_NUMBER` = commit_lsn/tx_ordinal/ordinal hex keys
    (core.rs:42-45,980-996) so BigQuery's CDC engine orders at-least-once
    deliveries correctly;
  - per-table batching between Relation/Truncate barriers
    (core.rs:956-978);
  - truncate → versioned successor tables `table`, `table_1`, … with a
    stable view over the latest generation (core.rs:55-106);
  - appends speak the REAL Storage Write wire format (bq_proto): an
    AppendRowsRequest proto carrying a self-describing DescriptorProto
    and per-row serialized proto messages, posted as
    `application/x-protobuf` against the table's `_default` stream —
    gRPC framing is the only transport difference from the reference
    (no gRPC stack in this environment; payload bytes are identical);
  - bounded LOCAL retry of Storage Write schema-propagation and
    NOT-FOUND-while-table-exists errors with exponential equal-jitter
    backoff (client.rs:58-68,551-650,1224-1285), on top of the transport
    retry policy for HTTP-level transient failures;
  - background TaskSet with the ack resolving to Durable when the append
    lands (core.rs:1371-1388) — `write_events` returns an *Accepted* ack
    immediately, letting the apply loop build the next batch while the
    upload is in flight.

Table/dataset DDL stays on the REST v2 JSON surface, which is what the
reference's client library uses for DDL as well.
"""

from __future__ import annotations

import asyncio
import base64
import datetime as dt
import json
from dataclasses import dataclass
from typing import Any, Sequence

import aiohttp

from ..models.cell import (JSON_NULL, PgInterval, PgNumeric, PgSpecialDate,
                           PgSpecialTimestamp, PgTimeTz, TOAST_UNCHANGED)
from ..models.errors import ErrorKind, EtlError
from ..models.event import (ChangeType, DecodedBatchEvent, DeleteEvent,
                            Event, InsertEvent, SchemaChangeEvent,
                            TruncateEvent, UpdateEvent)
from ..models.pgtypes import CellKind
from ..models.schema import (ReplicatedTableSchema, SchemaDiff, TableId)
from ..models.table_row import ColumnarBatch, TableRow
from ..models.default_expression import column_default_sql
from ..analysis.annotations import transactional_commit
from . import bq_proto
from .base import CommitRange, Destination, WriteAck, expand_batch_events
from .util import (CHANGE_SEQUENCE_COLUMN, CHANGE_TYPE_COLUMN,
                   DestinationRetryPolicy, TaskSet, change_type_label,
                   classify_http_error, count_egress_write,
                   escaped_table_name, require_full_batch, require_full_row,
                   sequential_event_program, versioned_table_name,
                   with_retries)


@dataclass(frozen=True)
class BigQueryConfig:
    project_id: str
    dataset_id: str
    base_url: str  # endpoint root (emulator/fake in tests)
    auth_token: str = ""
    max_concurrent_appends: int = 4
    # Storage Write local-retry window (reference client.rs:58-70: schema
    # updates propagate to append streams "on the order of minutes")
    storage_write_retry_timeout_s: float = 600.0
    storage_write_retry_delay_s: float = 1.0
    storage_write_max_retry_delay_s: float = 30.0


_BQ_TYPES: dict[CellKind, str] = {
    CellKind.BOOL: "BOOL",
    CellKind.I16: "INT64", CellKind.I32: "INT64", CellKind.U32: "INT64",
    CellKind.I64: "INT64",
    CellKind.F32: "FLOAT64", CellKind.F64: "FLOAT64",
    CellKind.NUMERIC: "BIGNUMERIC",
    CellKind.DATE: "DATE", CellKind.TIME: "TIME",
    CellKind.TIMETZ: "STRING",
    CellKind.TIMESTAMP: "DATETIME", CellKind.TIMESTAMPTZ: "TIMESTAMP",
    CellKind.UUID: "STRING", CellKind.JSON: "JSON",
    CellKind.BYTES: "BYTES", CellKind.STRING: "STRING",
    CellKind.ARRAY: "JSON", CellKind.INTERVAL: "STRING",
}


def bq_field(col, identity: set[str]) -> dict:
    # non-identity columns stay NULLABLE so key-only DELETE rows append
    required = not col.nullable and col.name in identity
    out = {"name": col.name, "type": _BQ_TYPES.get(col.kind, "STRING"),
           "mode": "REQUIRED" if required else "NULLABLE"}
    # portable literal defaults (reference default_expression.rs →
    # bigquery/schema.rs:28-36); unsupported source defaults are omitted
    default = column_default_sql(col, "bigquery")
    if default is not None:
        out["defaultValueExpression"] = default
    return out


def encode_value(v: Any, kind: CellKind) -> Any:
    """Python value → BigQuery JSON value (reference bigquery/encoding.rs)."""
    if v is None or v is TOAST_UNCHANGED:
        return None
    if v is JSON_NULL:
        return "null"
    if isinstance(v, PgNumeric):
        return v.pg_text()
    if isinstance(v, (PgTimeTz, PgInterval, PgSpecialDate,
                      PgSpecialTimestamp)):
        return v.pg_text()
    if isinstance(v, dt.datetime):
        if v.tzinfo is not None:
            return v.isoformat()
        return v.isoformat(sep=" ")
    if isinstance(v, (dt.date, dt.time)):
        return v.isoformat()
    if isinstance(v, bytes):
        return base64.b64encode(v).decode()
    if isinstance(v, (dict, list)):
        return json.dumps(v)
    if kind is CellKind.UUID:
        return str(v)
    if isinstance(v, (bool, int, float, str)):
        return v
    return str(v)


class BigQueryDestination(Destination):
    egress_encoder = "tsv"  # device text feeds string-typed proto cells

    def __init__(self, config: BigQueryConfig,
                 retry: DestinationRetryPolicy | None = None):
        self.config = config
        self.retry = retry or DestinationRetryPolicy()
        self._session: aiohttp.ClientSession | None = None
        self._tasks = TaskSet()
        self._generations: dict[TableId, int] = {}
        self._created: dict[TableId, ReplicatedTableSchema] = {}
        self._names: dict[TableId, str] = {}
        self._append_sem: asyncio.Semaphore | None = None
        self._marker_ready = False
        self._marker_lock = asyncio.Lock()

    # -- REST transport ----------------------------------------------------------

    async def _api(self, method: str, path: str,
                   body: dict | None = None) -> dict:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        headers = {}
        if self.config.auth_token:
            headers["Authorization"] = f"Bearer {self.config.auth_token}"

        async def attempt() -> dict:
            async with self._session.request(
                    method, f"{self.config.base_url}{path}",
                    json=body, headers=headers) as resp:
                text = await resp.text()
                if resp.status == 409:  # duplicate → idempotent success
                    return {"alreadyExists": True}
                if resp.status >= 400:
                    # shared status→kind map (util.classify_http_error):
                    # permanent 4xx become the poison-trigger kinds
                    raise classify_http_error(
                        "bigquery", resp.status, f"{path}: {text[:300]}")
                return json.loads(text) if text else {}

        def retryable(e: BaseException) -> bool:
            if isinstance(e, EtlError):
                return e.kind is ErrorKind.DESTINATION_THROTTLED
            return isinstance(e, (aiohttp.ClientError, OSError))

        return await with_retries(attempt, self.retry, retryable)

    def _dataset_path(self) -> str:
        return (f"/projects/{self.config.project_id}/datasets/"
                f"{self.config.dataset_id}")

    # -- lifecycle ---------------------------------------------------------------

    async def startup(self) -> None:
        self._append_sem = asyncio.Semaphore(
            self.config.max_concurrent_appends)
        await self._api("POST", f"/projects/{self.config.project_id}/datasets",
                        {"datasetReference":
                         {"datasetId": self.config.dataset_id}})

    def _base_name(self, schema: ReplicatedTableSchema) -> str:
        return self._names.setdefault(schema.id,
                                      escaped_table_name(schema.name))

    def _current_table(self, schema: ReplicatedTableSchema) -> str:
        gen = self._generations.get(schema.id, 0)
        return versioned_table_name(self._base_name(schema), gen)

    async def _ensure_table(self, schema: ReplicatedTableSchema) -> str:
        table = self._current_table(schema)
        known = self._created.get(schema.id)
        if known == schema:
            return table
        key_cols = [c.name for c in schema.identity_columns()]
        fields = [bq_field(c, set(key_cols))
                  for c in schema.replicated_columns]
        await self._api("POST", f"{self._dataset_path()}/tables", {
            "tableReference": {"tableId": table},
            "schema": {"fields": fields},
            "clustering": {"fields": key_cols[:4]} if key_cols else None,
            # storage-write CDC: primary keys drive UPSERT semantics
            "tableConstraints": {"primaryKey": {"columns": key_cols}}
            if key_cols else None,
        })
        self._created[schema.id] = schema
        return table

    # -- writes ------------------------------------------------------------------

    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        table = await self._ensure_table(schema)
        rows = self._rows_from_batch(schema, batch, None)
        ack, fut = WriteAck.accepted()
        self._tasks.spawn(self._append_and_resolve(table, schema, rows, fut))
        return ack

    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        """Build the ordered program (row runs split at truncate/DDL
        barriers), then execute it IN ORDER in one background task; the
        Accepted ack resolves when the whole program lands."""
        program = list(sequential_event_program(expand_batch_events(events)))
        if not program:
            return WriteAck.durable()
        # resolve table names up front (current generation at build time is
        # wrong for post-truncate runs — the executor re-resolves)
        ack, fut = WriteAck.accepted()

        async def execute() -> None:
            try:
                ordinal = 0
                for op in program:
                    if op[0] == "rows":
                        _, schema, evs = op
                        table = await self._ensure_table(schema)
                        rows = []
                        for e in evs:
                            if isinstance(e, DeleteEvent):
                                rows.append(self._row_tuple(
                                    schema, e.old_row, ChangeType.DELETE,
                                    e.sequence_key.with_ordinal(ordinal)))
                            else:
                                rows.append(self._row_tuple(
                                    schema, e.row, ChangeType.INSERT,
                                    e.sequence_key.with_ordinal(ordinal)))
                            ordinal += 1
                        await self._append_rows(table, schema, rows)
                    elif op[0] == "truncate":
                        for sch in op[1].schemas:
                            await self.truncate_table(sch.id)
                    else:
                        await self._apply_schema_change(op[1])
                if not fut.done():
                    fut.set_result(None)
            except BaseException as e:  # etl-lint: ignore[cancellation-swallow] — transferred to the ack future, not dropped
                if not fut.done():
                    fut.set_exception(e)

        self._tasks.spawn(execute())
        return ack

    # -- columnar seam --------------------------------------------------------

    async def write_table_batch(self, schema: ReplicatedTableSchema,
                                batch: ColumnarBatch) -> WriteAck:
        """Copy path, columnar: proto rows serialized column-at-a-time
        (bq_proto.encode_batch), byte-identical to the row path."""
        import numpy as np

        from .util import sequence_number_batch

        table = await self._ensure_table(schema)
        require_full_batch("bigquery", schema, batch)
        n = batch.num_rows
        zeros = np.zeros(n, dtype=np.uint64)
        seqs = sequence_number_batch(zeros, zeros,
                                     np.arange(n, dtype=np.uint64))
        egress = getattr(batch, "device_egress", None)
        encoded = bq_proto.encode_batch(schema, batch, [b"UPSERT"] * n, seqs,
                                        egress=egress)
        count_egress_write(egress is not None)
        ack, fut = WriteAck.accepted()
        self._tasks.spawn(self._append_encoded_and_resolve(
            table, schema, encoded, fut))
        return ack

    async def write_event_batches(self, events: Sequence[Event]) -> WriteAck:
        """CDC path, columnar: the ordered program executes in one
        background task like write_events, but simple decoded batch runs
        encode column-at-a-time; the global ordinal keeps
        `_CHANGE_SEQUENCE_NUMBER` identical to the expanded row path."""
        import numpy as np

        from .base import sequential_batch_program
        from .util import change_type_batch, sequence_number_batch

        program = list(sequential_batch_program(events))
        if not program:
            return WriteAck.durable()
        ack, fut = WriteAck.accepted()

        async def execute() -> None:
            try:
                ordinal = 0
                for op in program:
                    if op[0] == "batch":
                        _, schema, cb = op
                        table = await self._ensure_table(schema)
                        require_full_batch("bigquery", schema, cb.batch,
                                           cb.change_types)
                        n = cb.num_rows
                        seqs = sequence_number_batch(
                            cb.commit_lsns, cb.tx_ordinals,
                            np.arange(ordinal, ordinal + n, dtype=np.uint64))
                        labels = change_type_batch(cb.change_types).tolist()
                        ordinal += n
                        encoded = bq_proto.encode_batch(schema, cb.batch,
                                                        labels, seqs,
                                                        egress=cb.egress)
                        count_egress_write(cb.egress is not None)
                        await self._append_encoded(table, schema, encoded)
                    elif op[0] == "rows":
                        _, schema, evs = op
                        table = await self._ensure_table(schema)
                        rows = []
                        for e in evs:
                            if isinstance(e, DeleteEvent):
                                rows.append(self._row_tuple(
                                    schema, e.old_row, ChangeType.DELETE,
                                    e.sequence_key.with_ordinal(ordinal)))
                            else:
                                rows.append(self._row_tuple(
                                    schema, e.row, ChangeType.INSERT,
                                    e.sequence_key.with_ordinal(ordinal)))
                            ordinal += 1
                        await self._append_rows(table, schema, rows)
                    elif op[0] == "truncate":
                        for sch in op[1].schemas:
                            await self.truncate_table(sch.id)
                    else:
                        await self._apply_schema_change(op[1])
                if not fut.done():
                    fut.set_result(None)
            except BaseException as e:  # etl-lint: ignore[cancellation-swallow] — transferred to the ack future, not dropped
                if not fut.done():
                    fut.set_exception(e)

        self._tasks.spawn(execute())
        return ack

    # -- transactional seam (docs/destinations.md exactly-once contract) ------
    #
    # BigQuery's CDC tables already MERGE on `_CHANGE_SEQUENCE_NUMBER`
    # (commit_lsn/tx_ordinal/ordinal), so a re-streamed duplicate row
    # collapses at query time; what the seam ADDS is the recoverable
    # coordinate record: a `_etl_commit_marker` table whose description
    # metadata holds the acked high-water JSON, PATCHed only after the
    # flush's storage-write appends are durable. Recovery reads it back
    # through the same REST surface.

    _COMMIT_MARKER = "_etl_commit_marker"
    _MAX_REPLAY_TOKENS = 256

    def supports_transactional_commit(self) -> bool:
        return True

    def _marker_path(self) -> str:
        return f"{self._dataset_path()}/tables/{self._COMMIT_MARKER}"

    async def _ensure_marker(self) -> None:
        if self._marker_ready:
            return
        await self._api("POST", f"{self._dataset_path()}/tables", {
            "tableReference": {"tableId": self._COMMIT_MARKER},
            "schema": {"fields": [{"name": "unused", "type": "STRING"}]},
        })  # 409 → alreadyExists: idempotent
        self._marker_ready = True

    async def _marker_state(self) -> dict:
        doc = await self._api("GET", self._marker_path())
        desc = doc.get("description") or ""
        try:
            state = json.loads(desc)
        except ValueError:
            state = {}
        return state if isinstance(state, dict) else {}

    async def _advance_marker(self, commit: CommitRange) -> None:
        """Read-modify-write under the marker lock: concurrent in-flight
        flushes finalize out of order, and the recorded high-water must
        stay monotone regardless."""
        async with self._marker_lock:
            state = await self._marker_state()
            if commit.replay:
                tokens = list(state.get("replay_tokens", []))
                if commit.token() not in tokens:
                    tokens.append(commit.token())
                state["replay_tokens"] = tokens[-self._MAX_REPLAY_TOKENS:]
            else:
                cur = state.get("high")
                high = list(commit.high)
                if cur is None or high > list(cur):
                    state["high"] = high
                    if commit.commit_end_lsn:
                        state["commit_end_lsn"] = commit.commit_end_lsn
            await self._api("PATCH", self._marker_path(),
                            {"description": json.dumps(state,
                                                       sort_keys=True)})

    async def _finalize_commit(self, inner: "WriteAck | None",
                               commit: CommitRange,
                               fut: asyncio.Future) -> None:
        try:
            if inner is not None:
                await inner.wait_durable()
            await self._advance_marker(commit)
            if not fut.done():
                fut.set_result(None)
        except BaseException as e:  # etl-lint: ignore[cancellation-swallow] — transferred to the ack future, not dropped
            if not fut.done():
                fut.set_exception(e)

    @transactional_commit
    async def write_event_batches_committed(
            self, events: Sequence[Event], commit: CommitRange) -> WriteAck:
        """Committed CDC write: the data program ships first (storage-
        write appends, MERGE-keyed), then the WAL range PATCHes the
        marker — the outer ack only resolves durable once BOTH landed.
        A crash between them re-streams a flush the sequence-number
        MERGE absorbs."""
        await self._ensure_marker()
        if commit.replay:
            state = await self._marker_state()
            if commit.token() in state.get("replay_tokens", []):
                return WriteAck.durable()
        inner = await self.write_event_batches(events)
        # plain ack, not accepted(): the inner write already fired the
        # DESTINATION_WRITE chaos site for this flush
        fut = asyncio.get_event_loop().create_future()
        self._tasks.spawn(self._finalize_commit(inner, commit, fut))
        return WriteAck(fut)

    async def recover_high_water(self) -> "CommitRange | None":
        await self._ensure_marker()
        state = await self._marker_state()
        high = state.get("high")
        if not high:
            return None
        return CommitRange(high=(int(high[0]), int(high[1])),
                           commit_end_lsn=state.get("commit_end_lsn"))

    async def _append_encoded_and_resolve(self, table: str,
                                          schema: ReplicatedTableSchema,
                                          encoded: list[bytes],
                                          fut: asyncio.Future) -> None:
        try:
            await self._append_encoded(table, schema, encoded)
            if not fut.done():
                fut.set_result(None)
        except BaseException as e:  # etl-lint: ignore[cancellation-swallow] — transferred to the ack future, not dropped
            if not fut.done():
                fut.set_exception(e)

    async def _append_and_resolve(self, table: str,
                                  schema: ReplicatedTableSchema,
                                  rows: list[tuple],
                                  fut: asyncio.Future) -> None:
        try:
            await self._append_rows(table, schema, rows)
            if not fut.done():
                fut.set_result(None)
        except BaseException as e:  # etl-lint: ignore[cancellation-swallow] — transferred to the ack future, not dropped
            if not fut.done():
                fut.set_exception(e)

    def _write_stream(self, table: str) -> str:
        return (f"projects/{self.config.project_id}/datasets/"
                f"{self.config.dataset_id}/tables/{table}/streams/_default")

    async def _post_append_proto(self, table: str, body: bytes) -> bytes:
        """POST the serialized AppendRowsRequest; transport-level transient
        failures retry under the destination policy (the gRPC library's
        internal retries in the reference); Storage Write STATUS errors come
        back inside the response proto and are classified by the caller."""
        if self._session is None:
            self._session = aiohttp.ClientSession()
        headers = {"Content-Type": "application/x-protobuf"}
        if self.config.auth_token:
            headers["Authorization"] = f"Bearer {self.config.auth_token}"
        path = (f"{self._dataset_path()}/tables/{table}"
                "/streams/_default:appendRows")

        async def attempt() -> bytes:
            async with self._session.post(
                    f"{self.config.base_url}{path}", data=body,
                    headers=headers) as resp:
                payload = await resp.read()
                if resp.status >= 400:
                    raise classify_http_error(
                        "bigquery", resp.status,
                        f"{path}: {payload[:200]!r}")
                return payload

        def retryable(e: BaseException) -> bool:
            if isinstance(e, EtlError):
                return e.kind is ErrorKind.DESTINATION_THROTTLED
            return isinstance(e, (aiohttp.ClientError, OSError))

        return await with_retries(attempt, self.retry, retryable)

    async def _table_exists(self, table: str) -> bool:
        """GET the table resource (the probe behind NOT_FOUND retry
        classification, client.rs:600-615). Transient probe failures retry
        under the destination policy — a flaky probe must not demote a
        retryable NOT_FOUND into a permanent failure."""
        if self._session is None:
            self._session = aiohttp.ClientSession()
        headers = {}
        if self.config.auth_token:
            headers["Authorization"] = f"Bearer {self.config.auth_token}"

        async def attempt() -> bool:
            async with self._session.get(
                    f"{self.config.base_url}{self._dataset_path()}"
                    f"/tables/{table}", headers=headers) as resp:
                await resp.read()
                if resp.status == 200:
                    return True
                if resp.status == 404:
                    return False
                raise classify_http_error(
                    "bigquery", resp.status, f"table probe for {table}")

        def retryable(e: BaseException) -> bool:
            if isinstance(e, EtlError):
                return e.kind is ErrorKind.DESTINATION_THROTTLED
            return isinstance(e, (aiohttp.ClientError, OSError))

        return await with_retries(attempt, self.retry, retryable)

    def _retryable_storage_write_detail(self, status) -> str | None:
        """Schema-propagation classification (client.rs:557-579): structured
        SCHEMA_MISMATCH_EXTRA_FIELDS in the status details, or the
        documented message forms when no structured code is present."""
        if status.code != bq_proto.GRPC_INVALID_ARGUMENT:
            return None
        if bq_proto.STORAGE_ERROR_SCHEMA_MISMATCH_EXTRA_FIELDS \
                in status.storage_error_codes:
            return status.message or "schema mismatch (structured)"
        msg = status.message.lower()
        if ("missing in the proto message" in msg
                or "extra proto fields" in msg
                or "schema_mismatch_extra_field" in msg):
            return status.message
        return None

    async def _append_rows(self, table: str,
                           schema: ReplicatedTableSchema,
                           rows: list[tuple]) -> None:
        """Proto-encode and append, absorbing locally retryable Storage
        Write errors (schema propagation; NOT_FOUND while the table exists)
        within a bounded window — exponential backoff with equal jitter
        (client.rs:197-216,1224-1285). Row-level errors are permanent."""
        encoded = [bq_proto.encode_row(schema, values, ct, seq)
                   for values, ct, seq in rows]
        await self._append_encoded(table, schema, encoded)

    async def _append_encoded(self, table: str,
                              schema: ReplicatedTableSchema,
                              encoded: list[bytes]) -> None:
        """Append pre-serialized proto rows (the columnar encoder's output
        or encode_row's) under the bounded Storage Write retry loop."""
        import random
        import time as _time

        assert self._append_sem is not None
        cfg = self.config
        descriptor = bq_proto.row_descriptor(schema)
        stream = self._write_stream(table)
        started = _time.monotonic()
        delay = cfg.storage_write_retry_delay_s
        attempt = 0
        while True:
            attempt += 1
            trace = (f"etl_tpu_{table}_{attempt}_"
                     f"{random.randrange(2**32)}")
            body = bq_proto.append_rows_request(
                stream, descriptor, encoded, trace)
            # concurrency slot held only for the POST itself — a
            # propagation backoff (minutes) must not starve other tables'
            # appends of their slots
            async with self._append_sem:
                payload = await self._post_append_proto(table, body)
            resp = bq_proto.decode_append_rows_response(payload)
            if resp.row_errors:
                # permanent: bad data / schema mismatch per row
                # (client.rs:222-244); row values are NOT echoed.
                # DESTINATION_REJECTED — the per-row refusal is THE
                # poison-pill trigger (docs/dead-letter.md): the
                # isolation protocol bisects the batch to the rejected
                # row(s) instead of blind-retrying the same bytes
                first = resp.row_errors[0]
                raise EtlError(
                    ErrorKind.DESTINATION_REJECTED,
                    f"bigquery rejected {len(resp.row_errors)} row(s); "
                    f"first: row {first.index} code {first.code}")
            status = resp.error
            if status is None or status.code == bq_proto.GRPC_OK:
                return
            detail = self._retryable_storage_write_detail(status)
            if detail is None \
                    and status.code == bq_proto.GRPC_NOT_FOUND \
                    and await self._table_exists(table):
                # stale default-stream routing after delete/recreate
                detail = status.message or "storage write NOT_FOUND"
            if detail is None:
                raise self._status_to_error(status)
            elapsed = _time.monotonic() - started
            remaining = cfg.storage_write_retry_timeout_s - elapsed
            if remaining <= 0:
                raise EtlError(
                    ErrorKind.DESTINATION_THROTTLED,
                    "bigquery storage write retry timed out after "
                    f"{cfg.storage_write_retry_timeout_s:.0f}s: {detail}")
            # equal jitter: [delay/2, delay], capped by the window
            sleep_s = min(delay / 2 + random.random() * (delay / 2),
                          remaining)
            await asyncio.sleep(sleep_s)
            delay = min(delay * 2, cfg.storage_write_max_retry_delay_s)

    @staticmethod
    def _status_to_error(status) -> EtlError:
        """gRPC code → error kind (client.rs:416-470): transient server
        conditions map to the retryable kind so the worker-level timed
        retry policy takes over; precondition/auth failures are final."""
        transient = {bq_proto.GRPC_UNAVAILABLE, bq_proto.GRPC_INTERNAL,
                     bq_proto.GRPC_ABORTED, bq_proto.GRPC_CANCELLED,
                     bq_proto.GRPC_DEADLINE_EXCEEDED,
                     bq_proto.GRPC_RESOURCE_EXHAUSTED}
        if status.code in transient:
            kind = ErrorKind.DESTINATION_THROTTLED
        elif status.code in (bq_proto.GRPC_PERMISSION_DENIED,):
            kind = ErrorKind.DESTINATION_AUTH_FAILED
        elif status.code == bq_proto.GRPC_NOT_FOUND:
            kind = ErrorKind.DESTINATION_SCHEMA_FAILED
        elif status.code in (bq_proto.GRPC_INVALID_ARGUMENT,
                             bq_proto.GRPC_FAILED_PRECONDITION):
            # the payload was refused — permanent for these bytes, the
            # poison-isolation trigger kind (docs/dead-letter.md)
            kind = ErrorKind.DESTINATION_REJECTED
        else:
            kind = ErrorKind.DESTINATION_FAILED
        return EtlError(kind, f"bigquery storage write error "
                              f"(grpc code {status.code}): {status.message}")

    def _row_tuple(self, schema: ReplicatedTableSchema, row: TableRow,
                   ct: ChangeType, seq: str) -> tuple:
        if ct is not ChangeType.DELETE:
            require_full_row("bigquery", schema, row)
        return (list(row.values), change_type_label(ct), seq)

    def _rows_from_batch(self, schema: ReplicatedTableSchema,
                         batch: ColumnarBatch,
                         ev: DecodedBatchEvent | None) -> list[tuple]:
        require_full_batch("bigquery", schema, batch,
                           ev.change_types if ev is not None else None)
        out = []
        for i in range(batch.num_rows):
            values = [c.value(i) for c in batch.columns]
            if ev is not None:
                ct = change_type_label(ChangeType(int(ev.change_types[i])))
                seq = (f"{int(ev.commit_lsns[i]):016x}/"
                       f"{int(ev.tx_ordinals[i]):016x}/{i:016x}")
            else:
                ct = "UPSERT"
                seq = f"{0:016x}/{0:016x}/{i:016x}"
            out.append((values, ct, seq))
        return out

    async def _apply_schema_change(self, ev: SchemaChangeEvent) -> None:
        old = self._created.get(ev.table_id)
        new = ev.new_schema
        assert new is not None
        if old is None or SchemaDiff.between(old.table_schema,
                                             new.table_schema).is_empty():
            self._created[ev.table_id] = new
            return
        table = self._current_table(new)
        keys = {c.name for c in new.identity_columns()}
        fields = [bq_field(c, keys) for c in new.replicated_columns]
        await self._api("PATCH", f"{self._dataset_path()}/tables/{table}",
                        {"schema": {"fields": fields}})
        self._created[ev.table_id] = new

    # -- truncate / drop ----------------------------------------------------------

    async def truncate_table(self, table_id: TableId) -> None:
        """Versioned successor table (core.rs:55-106): bump the generation,
        create `base_N`, repoint the stable view."""
        schema = self._created.get(table_id)
        if schema is None:
            return
        self._generations[table_id] = self._generations.get(table_id, 0) + 1
        self._created.pop(table_id, None)  # force re-create at new gen
        table = await self._ensure_table(schema)
        base = self._base_name(schema)
        await self._api("POST", f"{self._dataset_path()}/views", {
            "viewId": f"{base}_view",
            "query": f"SELECT * FROM `{self.config.dataset_id}.{table}`"})

    async def drop_table(self, table_id: TableId,
                         schema: ReplicatedTableSchema | None = None) -> None:
        if table_id not in self._names and schema is not None:
            self._base_name(schema)  # restart recovery: rebuild the mapping
        name = self._names.get(table_id)
        if name is None:
            return
        gen = self._generations.get(table_id, 0)
        table = versioned_table_name(name, gen)
        await self._api("DELETE", f"{self._dataset_path()}/tables/{table}")
        self._created.pop(table_id, None)

    async def shutdown(self) -> None:
        await self._tasks.join()
        if self._session is not None:
            await self._session.close()
            self._session = None
