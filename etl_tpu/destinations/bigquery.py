"""BigQuery destination: Storage-Write-style CDC appends.

Reference parity: crates/etl-destinations/src/bigquery/ (6.6k LoC):
  - CDC appends carrying `_CHANGE_TYPE` (UPSERT/DELETE) and
    `_CHANGE_SEQUENCE_NUMBER` = commit_lsn/tx_ordinal/ordinal hex keys
    (core.rs:42-45,980-996) so BigQuery's CDC engine orders at-least-once
    deliveries correctly;
  - per-table batching between Relation/Truncate barriers
    (core.rs:956-978);
  - truncate → versioned successor tables `table`, `table_1`, … with a
    stable view over the latest generation (core.rs:55-106);
  - local retry of transient append errors (client.rs:58-68,317-450);
  - background TaskSet with the ack resolving to Durable when the append
    lands (core.rs:1371-1388) — `write_events` returns an *Accepted* ack
    immediately, letting the apply loop build the next batch while the
    upload is in flight.

Transport: a JSON/REST adapter with a pluggable base URL (tests run a fake
server). Production deployments swap the transport for the gRPC Storage
Write API; everything above `_append_rows`/`_api` is transport-agnostic.
"""

from __future__ import annotations

import asyncio
import base64
import datetime as dt
import json
from dataclasses import dataclass
from typing import Any, Sequence

import aiohttp

from ..models.cell import (JSON_NULL, PgInterval, PgNumeric, PgSpecialDate,
                           PgSpecialTimestamp, PgTimeTz, TOAST_UNCHANGED)
from ..models.errors import ErrorKind, EtlError
from ..models.event import (ChangeType, DecodedBatchEvent, DeleteEvent,
                            Event, InsertEvent, SchemaChangeEvent,
                            TruncateEvent, UpdateEvent)
from ..models.pgtypes import CellKind
from ..models.schema import (ReplicatedTableSchema, SchemaDiff, TableId)
from ..models.table_row import ColumnarBatch, TableRow
from .base import Destination, WriteAck, expand_batch_events
from .util import (CHANGE_SEQUENCE_COLUMN, CHANGE_TYPE_COLUMN,
                   DestinationRetryPolicy, TaskSet, change_type_label,
                   escaped_table_name, http_status_retryable,
                   require_full_batch, require_full_row,
                   sequential_event_program, versioned_table_name,
                   with_retries)


@dataclass(frozen=True)
class BigQueryConfig:
    project_id: str
    dataset_id: str
    base_url: str  # REST endpoint (fake server in tests)
    auth_token: str = ""
    max_concurrent_appends: int = 4


_BQ_TYPES: dict[CellKind, str] = {
    CellKind.BOOL: "BOOL",
    CellKind.I16: "INT64", CellKind.I32: "INT64", CellKind.U32: "INT64",
    CellKind.I64: "INT64",
    CellKind.F32: "FLOAT64", CellKind.F64: "FLOAT64",
    CellKind.NUMERIC: "BIGNUMERIC",
    CellKind.DATE: "DATE", CellKind.TIME: "TIME",
    CellKind.TIMETZ: "STRING",
    CellKind.TIMESTAMP: "DATETIME", CellKind.TIMESTAMPTZ: "TIMESTAMP",
    CellKind.UUID: "STRING", CellKind.JSON: "JSON",
    CellKind.BYTES: "BYTES", CellKind.STRING: "STRING",
    CellKind.ARRAY: "JSON", CellKind.INTERVAL: "STRING",
}


def bq_field(col, identity: set[str]) -> dict:
    # non-identity columns stay NULLABLE so key-only DELETE rows append
    required = not col.nullable and col.name in identity
    return {"name": col.name, "type": _BQ_TYPES.get(col.kind, "STRING"),
            "mode": "REQUIRED" if required else "NULLABLE"}


def encode_value(v: Any, kind: CellKind) -> Any:
    """Python value → BigQuery JSON value (reference bigquery/encoding.rs)."""
    if v is None or v is TOAST_UNCHANGED:
        return None
    if v is JSON_NULL:
        return "null"
    if isinstance(v, PgNumeric):
        return v.pg_text()
    if isinstance(v, (PgTimeTz, PgInterval, PgSpecialDate,
                      PgSpecialTimestamp)):
        return v.pg_text()
    if isinstance(v, dt.datetime):
        if v.tzinfo is not None:
            return v.isoformat()
        return v.isoformat(sep=" ")
    if isinstance(v, (dt.date, dt.time)):
        return v.isoformat()
    if isinstance(v, bytes):
        return base64.b64encode(v).decode()
    if isinstance(v, (dict, list)):
        return json.dumps(v)
    if kind is CellKind.UUID:
        return str(v)
    if isinstance(v, (bool, int, float, str)):
        return v
    return str(v)


class BigQueryDestination(Destination):
    def __init__(self, config: BigQueryConfig,
                 retry: DestinationRetryPolicy | None = None):
        self.config = config
        self.retry = retry or DestinationRetryPolicy()
        self._session: aiohttp.ClientSession | None = None
        self._tasks = TaskSet()
        self._generations: dict[TableId, int] = {}
        self._created: dict[TableId, ReplicatedTableSchema] = {}
        self._names: dict[TableId, str] = {}
        self._append_sem: asyncio.Semaphore | None = None

    # -- REST transport ----------------------------------------------------------

    async def _api(self, method: str, path: str,
                   body: dict | None = None) -> dict:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        headers = {}
        if self.config.auth_token:
            headers["Authorization"] = f"Bearer {self.config.auth_token}"

        async def attempt() -> dict:
            async with self._session.request(
                    method, f"{self.config.base_url}{path}",
                    json=body, headers=headers) as resp:
                text = await resp.text()
                if resp.status == 409:  # duplicate → idempotent success
                    return {"alreadyExists": True}
                if resp.status >= 400:
                    raise EtlError(
                        ErrorKind.DESTINATION_THROTTLED
                        if http_status_retryable(resp.status)
                        else ErrorKind.DESTINATION_FAILED,
                        f"bigquery {resp.status} {path}: {text[:300]}")
                return json.loads(text) if text else {}

        def retryable(e: BaseException) -> bool:
            if isinstance(e, EtlError):
                return e.kind is ErrorKind.DESTINATION_THROTTLED
            return isinstance(e, (aiohttp.ClientError, OSError))

        return await with_retries(attempt, self.retry, retryable)

    def _dataset_path(self) -> str:
        return (f"/projects/{self.config.project_id}/datasets/"
                f"{self.config.dataset_id}")

    # -- lifecycle ---------------------------------------------------------------

    async def startup(self) -> None:
        self._append_sem = asyncio.Semaphore(
            self.config.max_concurrent_appends)
        await self._api("POST", f"/projects/{self.config.project_id}/datasets",
                        {"datasetReference":
                         {"datasetId": self.config.dataset_id}})

    def _base_name(self, schema: ReplicatedTableSchema) -> str:
        return self._names.setdefault(schema.id,
                                      escaped_table_name(schema.name))

    def _current_table(self, schema: ReplicatedTableSchema) -> str:
        gen = self._generations.get(schema.id, 0)
        return versioned_table_name(self._base_name(schema), gen)

    async def _ensure_table(self, schema: ReplicatedTableSchema) -> str:
        table = self._current_table(schema)
        known = self._created.get(schema.id)
        if known == schema:
            return table
        key_cols = [c.name for c in schema.identity_columns()]
        fields = [bq_field(c, set(key_cols))
                  for c in schema.replicated_columns]
        await self._api("POST", f"{self._dataset_path()}/tables", {
            "tableReference": {"tableId": table},
            "schema": {"fields": fields},
            "clustering": {"fields": key_cols[:4]} if key_cols else None,
            # storage-write CDC: primary keys drive UPSERT semantics
            "tableConstraints": {"primaryKey": {"columns": key_cols}}
            if key_cols else None,
        })
        self._created[schema.id] = schema
        return table

    # -- writes ------------------------------------------------------------------

    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        table = await self._ensure_table(schema)
        rows = self._rows_from_batch(schema, batch, None)
        ack, fut = WriteAck.accepted()
        self._tasks.spawn(self._append_and_resolve(table, rows, fut))
        return ack

    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        """Build the ordered program (row runs split at truncate/DDL
        barriers), then execute it IN ORDER in one background task; the
        Accepted ack resolves when the whole program lands."""
        program = list(sequential_event_program(expand_batch_events(events)))
        if not program:
            return WriteAck.durable()
        # resolve table names up front (current generation at build time is
        # wrong for post-truncate runs — the executor re-resolves)
        ack, fut = WriteAck.accepted()

        async def execute() -> None:
            try:
                ordinal = 0
                for op in program:
                    if op[0] == "rows":
                        _, schema, evs = op
                        table = await self._ensure_table(schema)
                        rows = []
                        for e in evs:
                            if isinstance(e, DeleteEvent):
                                rows.append(self._row_json(
                                    schema, e.old_row, ChangeType.DELETE,
                                    e.sequence_key.with_ordinal(ordinal)))
                            else:
                                rows.append(self._row_json(
                                    schema, e.row, ChangeType.INSERT,
                                    e.sequence_key.with_ordinal(ordinal)))
                            ordinal += 1
                        await self._append_rows(table, rows)
                    elif op[0] == "truncate":
                        for sch in op[1].schemas:
                            await self.truncate_table(sch.id)
                    else:
                        await self._apply_schema_change(op[1])
                if not fut.done():
                    fut.set_result(None)
            except BaseException as e:
                if not fut.done():
                    fut.set_exception(e)

        self._tasks.spawn(execute())
        return ack

    async def _append_and_resolve(self, table: str, rows: list[dict],
                                  fut: asyncio.Future) -> None:
        try:
            await self._append_rows(table, rows)
            if not fut.done():
                fut.set_result(None)
        except BaseException as e:
            if not fut.done():
                fut.set_exception(e)

    async def _append_rows(self, table: str, rows: list[dict]) -> None:
        assert self._append_sem is not None
        async with self._append_sem:
            await self._api(
                "POST", f"{self._dataset_path()}/tables/{table}/appendRows",
                {"rows": rows})

    def _row_json(self, schema: ReplicatedTableSchema, row: TableRow,
                  ct: ChangeType, seq: str) -> dict:
        if ct is not ChangeType.DELETE:
            require_full_row("bigquery", schema, row)
        doc = {c.name: encode_value(v, c.kind)
               for c, v in zip(schema.replicated_columns, row.values)}
        doc[CHANGE_TYPE_COLUMN] = change_type_label(ct)
        doc[CHANGE_SEQUENCE_COLUMN] = seq
        return doc

    def _rows_from_batch(self, schema: ReplicatedTableSchema,
                         batch: ColumnarBatch,
                         ev: DecodedBatchEvent | None) -> list[dict]:
        require_full_batch("bigquery", schema, batch,
                           ev.change_types if ev is not None else None)
        cols = schema.replicated_columns
        out = []
        for i in range(batch.num_rows):
            doc = {c.schema.name: encode_value(c.value(i), c.schema.kind)
                   for c in batch.columns}
            if ev is not None:
                doc[CHANGE_TYPE_COLUMN] = change_type_label(
                    ChangeType(int(ev.change_types[i])))
                doc[CHANGE_SEQUENCE_COLUMN] = (
                    f"{int(ev.commit_lsns[i]):016x}/"
                    f"{int(ev.tx_ordinals[i]):016x}/{i:016x}")
            else:
                doc[CHANGE_TYPE_COLUMN] = "UPSERT"
                doc[CHANGE_SEQUENCE_COLUMN] = f"{0:016x}/{0:016x}/{i:016x}"
            out.append(doc)
        return out

    async def _apply_schema_change(self, ev: SchemaChangeEvent) -> None:
        old = self._created.get(ev.table_id)
        new = ev.new_schema
        assert new is not None
        if old is None or SchemaDiff.between(old.table_schema,
                                             new.table_schema).is_empty():
            self._created[ev.table_id] = new
            return
        table = self._current_table(new)
        keys = {c.name for c in new.identity_columns()}
        fields = [bq_field(c, keys) for c in new.replicated_columns]
        await self._api("PATCH", f"{self._dataset_path()}/tables/{table}",
                        {"schema": {"fields": fields}})
        self._created[ev.table_id] = new

    # -- truncate / drop ----------------------------------------------------------

    async def truncate_table(self, table_id: TableId) -> None:
        """Versioned successor table (core.rs:55-106): bump the generation,
        create `base_N`, repoint the stable view."""
        schema = self._created.get(table_id)
        if schema is None:
            return
        self._generations[table_id] = self._generations.get(table_id, 0) + 1
        self._created.pop(table_id, None)  # force re-create at new gen
        table = await self._ensure_table(schema)
        base = self._base_name(schema)
        await self._api("POST", f"{self._dataset_path()}/views", {
            "viewId": f"{base}_view",
            "query": f"SELECT * FROM `{self.config.dataset_id}.{table}`"})

    async def drop_table(self, table_id: TableId) -> None:
        name = self._names.get(table_id)
        if name is None:
            return
        gen = self._generations.get(table_id, 0)
        table = versioned_table_name(name, gen)
        await self._api("DELETE", f"{self._dataset_path()}/tables/{table}")
        self._created.pop(table_id, None)

    async def shutdown(self) -> None:
        await self._tasks.join()
        if self._session is not None:
            await self._session.close()
            self._session = None
