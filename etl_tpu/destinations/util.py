"""Shared destination helpers.

Reference parity: crates/etl-destinations/src/{retry.rs (classify-and-
backoff), table_name.rs (underscore-escaped naming), recovery.rs} and the
CDC metadata conventions shared by the cloud writers (BigQuery
`_CHANGE_TYPE`/`_CHANGE_SEQUENCE_NUMBER`, Snowflake CdcMeta/CdcOperation).
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, TypeVar

from ..models.errors import ErrorKind, EtlError
from ..models.event import ChangeType, EventSequenceKey
from ..models.schema import TableName
from ..retry import DESTINATION_TRANSIENT_KINDS, RetryPolicy

T = TypeVar("T")

# CDC metadata column names (reference bigquery/core.rs:42-45)
CHANGE_TYPE_COLUMN = "_CHANGE_TYPE"
CHANGE_SEQUENCE_COLUMN = "_CHANGE_SEQUENCE_NUMBER"

CDC_UPSERT = "UPSERT"
CDC_DELETE = "DELETE"
# column-wise partial update: only the columns NOT listed in the row's
# `_PATCH_MISSING` metadata overwrite the stored row (the lake analogue of
# reference ducklake/batches.rs UpdatedTableRow::Partial → SQL UPDATE)
CDC_PATCH = "PATCH"
PATCH_MISSING_COLUMN = "_PATCH_MISSING"


def change_type_label(ct: ChangeType) -> str:
    return CDC_DELETE if ct is ChangeType.DELETE else CDC_UPSERT


def require_full_row(destination: str, schema, row) -> None:
    """Full-row UPSERT destinations cannot preserve omitted columns: an
    update row still carrying TOAST_UNCHANGED values (source has default
    replica identity and didn't ship the old image) must fail typed rather
    than overwrite stored values with NULL (reference
    bigquery/core.rs:1477-1495 bigquery_update_new_row; ADVICE r1 high).
    Remedy: ALTER TABLE ... REPLICA IDENTITY FULL on the source."""
    from ..models.cell import TOAST_UNCHANGED

    if any(v is TOAST_UNCHANGED for v in row.values):
        missing = [c.name for c, v in zip(schema.replicated_columns,
                                          row.values)
                   if v is TOAST_UNCHANGED]
        raise EtlError(
            ErrorKind.SOURCE_REPLICA_IDENTITY,
            f"{destination}: update for {schema.name} omits TOASTed "
            f"column(s) {missing} (unchanged-TOAST without an old image); "
            f"full-row upsert would overwrite them with NULL. Set REPLICA "
            f"IDENTITY FULL on the source table.")


def require_full_batch(destination: str, schema, batch,
                       change_types=None) -> None:
    """Columnar-path variant of `require_full_row`: reject TOAST-unchanged
    cells in non-DELETE rows of a ColumnarBatch."""
    for c in batch.columns:
        if c.toast_unchanged is None or not c.toast_unchanged.any():
            continue
        for i in range(batch.num_rows):
            if not c.toast_unchanged[i]:
                continue
            if change_types is not None \
                    and int(change_types[i]) == int(ChangeType.DELETE):
                continue
            raise EtlError(
                ErrorKind.SOURCE_REPLICA_IDENTITY,
                f"{destination}: update for {schema.name} omits TOASTed "
                f"column {c.schema.name} (unchanged-TOAST without an old "
                f"image); full-row upsert would overwrite it with NULL. "
                f"Set REPLICA IDENTITY FULL on the source table.")


def sequence_number(key: EventSequenceKey, ordinal: int) -> str:
    """Hex ordering key commit_lsn/tx_ordinal/ordinal
    (reference bigquery/core.rs:980-996)."""
    return key.with_ordinal(ordinal)


# -- batch-granularity CDC metadata (columnar egress) -------------------------
#
# The row path renders `_CHANGE_SEQUENCE_NUMBER` with an f-string per row —
# at 41k ev/s that formatting was measurable in the streamed-CDC profile.
# These build the same `%016x/%016x/%016x` keys for a WHOLE batch as numpy
# nibble-lookup ops: one (n, 50)-byte buffer, no per-row Python.

import numpy as np

_HEX_DIGITS = np.frombuffer(b"0123456789abcdef", dtype=np.uint8)
_SEQ_WIDTH = 50  # 16 hex + '/' + 16 hex + '/' + 16 hex


def _hex16(arr: np.ndarray, out: np.ndarray) -> None:
    """(n,) uint64 → 16 lowercase ASCII hex bytes per value, into `out`
    (an (n, 16) uint8 view)."""
    b = np.ascontiguousarray(arr, dtype=">u8").view(np.uint8).reshape(-1, 8)
    out[:, 0::2] = _HEX_DIGITS[b >> 4]
    out[:, 1::2] = _HEX_DIGITS[b & 0x0F]


def sequence_number_buffer(commit_lsns, tx_ordinals, ordinals) -> np.ndarray:
    """Vectorized CDC sequence keys: (n, 50) uint8 buffer of
    `{commit:016x}/{tx_ordinal:016x}/{ordinal:016x}` rows — byte-identical
    to `EventSequenceKey.with_ordinal` output."""
    commit_lsns = np.asarray(commit_lsns, dtype=np.uint64)
    n = len(commit_lsns)
    out = np.empty((n, _SEQ_WIDTH), dtype=np.uint8)
    _hex16(commit_lsns, out[:, 0:16])
    out[:, 16] = ord("/")
    _hex16(np.asarray(tx_ordinals, dtype=np.uint64), out[:, 17:33])
    out[:, 33] = ord("/")
    _hex16(np.asarray(ordinals, dtype=np.uint64), out[:, 34:50])
    return out


def sequence_number_batch(commit_lsns, tx_ordinals, ordinals) -> list[bytes]:
    """Per-row sequence keys as a list of ASCII bytes (TSV/proto form)."""
    buf = sequence_number_buffer(commit_lsns, tx_ordinals, ordinals)
    return buf.reshape(-1).view(f"S{_SEQ_WIDTH}").tolist()


def sequence_number_arrow(commit_lsns, tx_ordinals, ordinals):
    """Per-row sequence keys as a pyarrow StringArray built straight from
    the fixed-width buffer (no per-row Python strings)."""
    import pyarrow as pa

    buf = sequence_number_buffer(commit_lsns, tx_ordinals, ordinals)
    n = buf.shape[0]
    offsets = np.arange(0, (n + 1) * _SEQ_WIDTH, _SEQ_WIDTH, dtype=np.int32)
    return pa.StringArray.from_buffers(
        n, pa.py_buffer(offsets.tobytes()), pa.py_buffer(buf.tobytes()))


def hex16_arrow(values):
    """Vectorized `{v:016x}` strings as a pyarrow StringArray (the
    Iceberg copy path's per-row sequence suffix)."""
    import pyarrow as pa

    arr = np.asarray(values, dtype=np.uint64)
    n = len(arr)
    out = np.empty((n, 16), dtype=np.uint8)
    _hex16(arr, out)
    offsets = np.arange(0, (n + 1) * 16, 16, dtype=np.int32)
    return pa.StringArray.from_buffers(
        n, pa.py_buffer(offsets.tobytes()), pa.py_buffer(out.tobytes()))


def change_type_batch(change_types) -> np.ndarray:
    """Vectorized `_CHANGE_TYPE` labels for a batch: (n,) bytes array
    (S6) of UPSERT/DELETE matching `change_type_label` per row."""
    cts = np.asarray(change_types)
    return np.where(cts == int(ChangeType.DELETE),
                    np.bytes_(CDC_DELETE), np.bytes_(CDC_UPSERT))


def change_type_arrow(change_types):
    """Vectorized `_CHANGE_TYPE` labels as a pyarrow StringArray."""
    import pyarrow as pa

    return pa.array(change_type_batch(change_types).astype("U6"))


def count_egress_write(used_device: bool) -> None:
    """Account one columnar wire write: path=device when any field came
    from device-rendered egress buffers, path=host when every field was
    rendered host-side (OPERATIONS.md egress telemetry)."""
    from ..telemetry.metrics import ETL_EGRESS_WRITES_TOTAL, registry

    registry.counter_inc(ETL_EGRESS_WRITES_TOTAL,
                         labels={"path": "device" if used_device
                                 else "host"})


def fixed_width_string_arrow(buf: np.ndarray):
    """pyarrow StringArray from an (n, W) uint8 buffer where every row is
    exactly W bytes (the sequence-key / hex-token shape) — offsets are an
    arange, values the buffer itself. Lets callers that already rendered
    the buffer (watermark comparisons) reuse it instead of re-rendering
    through `sequence_number_arrow`."""
    import pyarrow as pa

    n, width = buf.shape
    offsets = np.arange(0, (n + 1) * width, width, dtype=np.int32)
    return pa.StringArray.from_buffers(
        n, pa.py_buffer(offsets.tobytes()),
        pa.py_buffer(np.ascontiguousarray(buf).tobytes()))


def string_array_from_fixed(buf: np.ndarray, lens: np.ndarray):
    """pyarrow StringArray straight from a left-aligned fixed-width byte
    buffer (the DeviceEgress field shape: (n, W) uint8 + per-row lengths)
    — offsets from one cumsum, values gathered without per-row Python.
    The Arrow-consuming destinations (BigQuery proto string cells,
    lake/Iceberg Parquet) turn device-rendered text columns into arrays
    through this one helper."""
    import pyarrow as pa

    n, width = buf.shape
    lens = np.asarray(lens, dtype=np.int64)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    total = int(offsets[-1])
    pos = np.arange(total, dtype=np.int64) - np.repeat(offsets[:-1], lens)
    src = np.repeat(np.arange(n, dtype=np.int64) * width, lens) + pos
    values = buf.reshape(-1)[src]
    return pa.StringArray.from_buffers(
        n, pa.py_buffer(offsets.astype(np.int32).tobytes()),
        pa.py_buffer(values.tobytes()))


def escaped_table_name(name: TableName) -> str:
    """`schema_table` with underscores in parts doubled so the mapping is
    injective (reference table_name.rs)."""
    return (name.schema.replace("_", "__") + "_"
            + name.name.replace("_", "__"))


def versioned_table_name(base: str, generation: int) -> str:
    """Truncate-versioned successor tables `base`, `base_1`, `base_2`…
    (reference bigquery/core.rs:55-106)."""
    return base if generation == 0 else f"{base}_{generation}"


# transient classification (reference retry.rs)
_RETRYABLE_HTTP = frozenset({408, 409, 429, 500, 502, 503, 504})


def http_status_retryable(status: int) -> bool:
    return status in _RETRYABLE_HTTP


def classify_http_error(destination: str, status: int,
                        text: str = "") -> "EtlError":
    """HTTP status → concrete ErrorKind — ONE classification shared by
    every HTTP destination, so permanent-vs-transient can never drift
    per sink (docs/dead-letter.md: this is the trigger signal the
    poison-isolation protocol keys on).

      retryable statuses (408/409/429/5xx)  → DESTINATION_THROTTLED
                                              (transient: writer retries
                                              in place, then the worker
                                              re-streams)
      401 / 403                             → DESTINATION_AUTH_FAILED
      404 / 410                             → DESTINATION_SCHEMA_FAILED
                                              (the table/dataset/channel
                                              the write names is gone —
                                              schema drift)
      413                                   → DESTINATION_PAYLOAD_TOO_LARGE
      every other 4xx                       → DESTINATION_REJECTED
                                              (the payload was refused:
                                              permanent for these bytes,
                                              the poison-pill kind)
    """
    if http_status_retryable(status) or status >= 500:
        kind = ErrorKind.DESTINATION_THROTTLED
    elif status in (401, 403):
        kind = ErrorKind.DESTINATION_AUTH_FAILED
    elif status in (404, 410):
        kind = ErrorKind.DESTINATION_SCHEMA_FAILED
    elif status == 413:
        kind = ErrorKind.DESTINATION_PAYLOAD_TOO_LARGE
    elif 400 <= status < 500:
        kind = ErrorKind.DESTINATION_REJECTED
    else:
        kind = ErrorKind.DESTINATION_FAILED
    return EtlError(kind, f"{destination} {status}: {text[:300]}")


def classify_write_exception(destination: str,
                             exc: BaseException) -> "EtlError":
    """Any non-EtlError escaping a destination write path → a concrete
    ErrorKind, so nothing unclassified ever reaches the retry layer
    (etl-lint rule 18 `unclassified-destination-error` enforces the
    call-site discipline). Transport failures are transient connection
    kinds; everything else is the ambiguous DESTINATION_FAILED."""
    if isinstance(exc, EtlError):
        return exc
    if isinstance(exc, (asyncio.TimeoutError, TimeoutError)):
        return EtlError(ErrorKind.TIMEOUT,
                        f"{destination}: {exc!r}")
    if isinstance(exc, (ConnectionError, OSError, EOFError,
                        asyncio.IncompleteReadError)):
        return EtlError(ErrorKind.DESTINATION_CONNECTION_FAILED,
                        f"{destination}: {exc!r}")
    try:
        import aiohttp

        if isinstance(exc, aiohttp.ClientError):
            return EtlError(ErrorKind.DESTINATION_CONNECTION_FAILED,
                            f"{destination}: {exc!r}")
    except ImportError:  # aiohttp-less deployments (lake/iceberg only)
        pass
    return EtlError(ErrorKind.DESTINATION_FAILED,
                    f"{destination}: {exc!r}")


class DestinationRetryPolicy(RetryPolicy):
    """Writer-scoped alias of the unified RetryPolicy (etl_tpu/retry.py):
    in-place retries for transient transport/capacity errors only
    (DESTINATION_TRANSIENT_KINDS) — rejected payloads escalate to the
    worker retry loop, which re-streams from durable progress."""


async def with_retries(op: Callable[[], Awaitable[T]],
                       policy: RetryPolicy,
                       retryable: "Callable[[BaseException], bool] | None"
                       = None, destination: str = "destination") -> T:
    """Classify-and-backoff retry wrapper (reference retry.rs:classify).
    Delegates to RetryPolicy.execute; `retryable=None` uses the policy's
    own per-ErrorKind classification. Whatever finally escapes is
    GUARANTEED to be an EtlError with a concrete kind: a raw transport
    exception surviving the in-place retries wraps through
    `classify_write_exception` instead of reaching the worker retry
    layer bare (the poison-isolation trigger contract)."""
    try:
        return await policy.execute(op, retryable)
    except (asyncio.CancelledError, EtlError):
        raise
    except Exception as e:
        # Exception, NOT BaseException: KeyboardInterrupt/SystemExit
        # must terminate the process, not become retryable
        # destination failures
        if type(e).__module__.partition(".")[0] == "etl_tpu":
            # internal control-flow exceptions (iceberg._CasConflict,
            # snowpipe.SnowpipeWireError, chaos.SimulatedCrash) are
            # caught-and-handled by their own call sites — wrapping them
            # would break those protocols, and they never reach the
            # worker retry layer
            raise
        raise classify_write_exception(destination, e) from e


class TaskSet:
    """Background destination tasks with joined shutdown
    (reference concurrency/task_set.rs)."""

    def __init__(self) -> None:
        self._tasks: set[asyncio.Task] = set()

    def spawn(self, coro) -> asyncio.Task:
        t = asyncio.ensure_future(coro)
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)
        return t

    async def join(self) -> None:
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)

    async def cancel_all(self) -> None:
        for t in list(self._tasks):
            t.cancel()
        await self.join()


def _identity_values(schema, row):
    """Identity-column values of a row, in replicated order."""
    idx = schema.replicated_indices
    identity = schema.identity_mask
    return tuple(v for i, v in enumerate(row.values) if identity[idx[i]])


def split_key_changing_update(e):
    """An UPDATE whose old image shows a different replica identity leaves
    the old-identity row stale in upsert-keyed destinations. Emit
    DELETE(old identity) + the update, mirroring reference
    ducklake/batches.rs `Full → Delete{origin: update} + Upsert`
    (ADVICE r1: key-changing updates leave duplicate rows in _current
    views). Returns [events…] to apply in order."""
    from ..models.event import DeleteEvent, UpdateEvent

    if not isinstance(e, UpdateEvent) or e.old_row is None:
        return [e]
    if _identity_values(e.schema, e.old_row) == \
            _identity_values(e.schema, e.row):
        return [e]
    return [DeleteEvent(e.start_lsn, e.commit_lsn, e.tx_ordinal, e.schema,
                        e.old_row), e]


def sequential_event_program(events):
    """Order-preserving destination program: yields ("rows", schema, [row
    events…]) runs and ("truncate", event) / ("schema_change", event)
    barriers, splitting runs so WAL order is preserved — rows preceding a
    truncate in the batch must land before it executes. Key-changing
    updates expand to DELETE(old identity) + update.

    Accepts expanded per-row events (use expand_batch_events first)."""
    from ..models.event import (DeleteEvent, InsertEvent, SchemaChangeEvent,
                                TruncateEvent, UpdateEvent)

    run_schema = None
    run: list = []
    flat = (e for outer in events
            for e in (split_key_changing_update(outer)
                      if isinstance(outer, UpdateEvent) else (outer,)))
    for e in flat:
        if isinstance(e, (InsertEvent, UpdateEvent, DeleteEvent)):
            if run_schema is not None and (run_schema.id != e.schema.id
                                           or run_schema != e.schema):
                yield ("rows", run_schema, run)
                run = []
            run_schema = e.schema
            run.append(e)
        elif isinstance(e, (TruncateEvent, SchemaChangeEvent)):
            if run:
                yield ("rows", run_schema, run)
                run, run_schema = [], None
            if isinstance(e, TruncateEvent):
                yield ("truncate", e)
            elif e.new_schema is not None:
                yield ("schema_change", e)
        # Begin/Commit/Relation: ordering barriers with no destination op
    if run:
        yield ("rows", run_schema, run)
