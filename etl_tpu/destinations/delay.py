"""DelayedAckDestination: a latency model for the ack round trip.

Wraps any destination and delays every ack's DURABILITY by `delay_s`
while the write itself applies immediately — exactly the shape of a real
destination (BigQuery commit, ClickHouse insert quorum, an object-store
PUT) where `write_*` hands the payload off fast and crash-safety is
signalled one round trip later. The apply loop's bounded write window
(runtime/ack_window.py) exists to hide this latency; `bench.py
--ack-latency` wraps the null destination with this class and measures
windowed vs window=1 throughput, and the chaos K-in-flight crash
scenario uses it to hold ≥2 acks in flight deterministically at the
kill point.

Accounting for assertions: `pending` / `max_pending` count unresolved
delayed acks — `max_pending >= 2` is the evidence that a run actually
overlapped ack round trips (window=1 can never exceed 1)."""

from __future__ import annotations

import asyncio
from typing import Sequence

from ..models.errors import ErrorKind, EtlError
from .base import Destination, WriteAck
from .util import TaskSet


class DelayedAckDestination(Destination):
    def __init__(self, inner: Destination, delay_s: float):
        self.inner = inner
        self.delay_s = delay_s
        # egress/billing labels must name the REAL sink, not the wrapper
        self.telemetry_name = getattr(inner, "telemetry_name",
                                      type(inner).__name__)
        self.pending = 0
        self.max_pending = 0
        self.acks_issued = 0
        self._tasks = TaskSet()
        self._shut_down = False

    async def _delayed(self, inner_ack: WriteAck) -> WriteAck:
        self.acks_issued += 1
        if self.delay_s <= 0:
            return inner_ack
        ack, fut = WriteAck.accepted()
        self.pending += 1
        self.max_pending = max(self.max_pending, self.pending)

        async def settle() -> None:
            try:
                await inner_ack.wait_durable()
                await asyncio.sleep(self.delay_s)
            except asyncio.CancelledError:
                if not fut.done():
                    fut.set_exception(EtlError(
                        ErrorKind.DESTINATION_FAILED,
                        "destination shut down with a delayed ack "
                        "pending"))
                    fut.exception()  # retrieved: consumer may be gone
                raise
            except Exception as e:
                if not fut.done():
                    fut.set_exception(e)
                    fut.exception()
            else:
                if not fut.done():
                    fut.set_result(None)
            finally:
                self.pending -= 1

        if self._shut_down:
            self.pending -= 1
            fut.set_exception(EtlError(
                ErrorKind.DESTINATION_FAILED,
                "destination already shut down"))
            fut.exception()
            return ack
        self._tasks.spawn(settle())
        return ack

    # -- Destination ----------------------------------------------------------

    async def startup(self) -> None:
        self._shut_down = False
        await self.inner.startup()

    async def write_table_rows(self, schema, batch) -> WriteAck:
        return await self._delayed(
            await self.inner.write_table_rows(schema, batch))

    async def write_events(self, events: Sequence) -> WriteAck:
        return await self._delayed(await self.inner.write_events(events))

    async def write_table_batch(self, schema, batch) -> WriteAck:
        return await self._delayed(
            await self.inner.write_table_batch(schema, batch))

    async def write_event_batches(self, events: Sequence) -> WriteAck:
        return await self._delayed(
            await self.inner.write_event_batches(events))

    # transactional seam: the inner sink commits data + coordinate range
    # immediately, only the ACK is delayed — exactly the crash window the
    # exactly-once chaos matrix kills inside (sink has the range, the
    # pipeline never saw the ack, recovery must not double-apply)
    def supports_transactional_commit(self) -> bool:
        return self.inner.supports_transactional_commit()

    async def write_event_batches_committed(self, events: Sequence,
                                            commit) -> WriteAck:
        return await self._delayed(
            await self.inner.write_event_batches_committed(events, commit))

    async def recover_high_water(self):
        return await self.inner.recover_high_water()

    async def drop_table(self, table_id, schema=None) -> None:
        await self.inner.drop_table(table_id, schema)

    async def truncate_table(self, table_id) -> None:
        await self.inner.truncate_table(table_id)

    async def shutdown(self) -> None:
        self._shut_down = True
        await self._tasks.cancel_all()
        await self.inner.shutdown()
