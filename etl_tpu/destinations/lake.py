"""Lake destination: a local lakehouse — Parquet data + SQL catalog.

The DuckLake-analogue (reference crates/etl-destinations/src/ducklake/,
13.5k LoC: DuckDB writing Parquet to S3 with a Postgres-backed catalog).
Here: pyarrow Parquet files in a warehouse directory with a sqlite catalog
— the same architecture with the embedded pieces this environment has.
Carried over semantics:

  - batch mutation application with retry (ducklake/batches.rs): every
    write lands as an immutable Parquet file recorded in the catalog;
  - replay-epoch markers for at-least-once dedup (replay_epoch.rs): CDC
    files carry their max sequence key; a re-delivered batch whose max
    sequence ≤ the table's high watermark is skipped;
  - truncate handling via generations; snapshot reads collapse CDC files
    by identity + sequence order (the `_current` semantics);
  - external maintenance handoff (external_maintenance.rs): `compact()`
    merges CDC files into a new base file under a catalog transaction,
    coordinated with writers through a catalog maintenance flag.

TPU-first payoff: ColumnarBatch → Arrow RecordBatch → Parquet without any
per-row Python objects for device-decoded columns.
"""

from __future__ import annotations

import asyncio
import json
import sqlite3
import uuid
from dataclasses import dataclass
from pathlib import Path
from typing import Sequence

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from ..analysis.annotations import hot_loop, transactional_commit

from ..models.errors import ErrorKind, EtlError
from ..models.event import (ChangeType, DecodedBatchEvent, DeleteEvent,
                            Event, InsertEvent, SchemaChangeEvent,
                            TruncateEvent, UpdateEvent)
from ..models.schema import ReplicatedTableSchema, TableId
from ..models.table_row import ColumnarBatch
from .base import CommitRange, Destination, WriteAck, expand_batch_events
from .util import (CHANGE_SEQUENCE_COLUMN, CHANGE_TYPE_COLUMN, CDC_DELETE,
                   CDC_PATCH, CDC_UPSERT, PATCH_MISSING_COLUMN,
                   _identity_values, change_type_label, escaped_table_name,
                   sequential_event_program)


@dataclass(frozen=True)
class LakeConfig:
    warehouse_path: str  # directory for parquet files + catalog
    compact_min_files: int = 8  # compaction trigger threshold
    # data inlining (reference ducklake/inline_size.rs): CDC batches whose
    # Arrow payload is below inline_max_bytes are stored IN the catalog
    # (Arrow IPC blob) instead of as tiny Parquet files; when a table's
    # accumulated inlined bytes exceed inline_flush_bytes they flush into
    # one Parquet file. 0 disables inlining.
    inline_max_bytes: int = 0
    inline_flush_bytes: int = 256 * 1024


# replay epoch assigned to rows written before epoch tracking existed
# (reference replay_epoch.rs LEGACY_REPLAY_EPOCH)
LEGACY_REPLAY_EPOCH = "__legacy__"

# maintenance-policy sampling predicates — ONE definition shared with the
# coordination agent's off-thread sampler (maintenance_coordination.py),
# so the replicator side and the controller side can never drift on what
# counts as a compactable CDC file or pending inlined bytes
TABLE_GENERATION_SQL = "SELECT generation FROM lake_tables WHERE table_id = ?"
CDC_FILE_COUNT_SQL = (
    "SELECT COUNT(*) FROM lake_files WHERE table_id = ? AND "
    "generation = ? AND kind = 'cdc' AND inline_payload IS NULL")
PENDING_INLINE_BYTES_SQL = (
    "SELECT COALESCE(SUM(LENGTH(inline_payload)), 0) FROM "
    "lake_files WHERE table_id = ? AND generation = ? AND "
    "inline_payload IS NOT NULL")


def _concat_cdc_batches(batches: "list[pa.RecordBatch]") -> pa.Table:
    """Concatenate CDC record batches whose schemas may differ only in the
    optional PATCH-missing column: align on the column union, null-filling
    the absentees (Arrow's schema unification does exactly this)."""
    return pa.concat_tables([pa.Table.from_batches([b]) for b in batches],
                            promote_options="default")


class LakeDestination(Destination):
    def __init__(self, config: LakeConfig):
        self.config = config
        self.root = Path(config.warehouse_path)
        self._db: sqlite3.Connection | None = None

    # -- catalog ----------------------------------------------------------------

    async def startup(self) -> None:
        self.root.mkdir(parents=True, exist_ok=True)
        self._db = sqlite3.connect(self.root / "catalog.db")
        # WAL keeps readers unblocked during commits; the generous busy
        # timeout covers compact()'s observe→merge→swap transaction so a
        # concurrent writer (external maintenance binary vs replicator)
        # waits instead of failing with a raw 'database is locked'
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA busy_timeout=60000")
        self._db.executescript("""
CREATE TABLE IF NOT EXISTS lake_tables (
    table_id BIGINT PRIMARY KEY,
    name TEXT NOT NULL,
    schema_json TEXT NOT NULL,
    generation BIGINT NOT NULL DEFAULT 0,
    max_seq TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS lake_files (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    table_id BIGINT NOT NULL,
    generation BIGINT NOT NULL,
    path TEXT NOT NULL,
    kind TEXT NOT NULL,          -- 'base' | 'cdc'
    row_count BIGINT NOT NULL,
    max_seq TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS lake_maintenance (
    table_id BIGINT PRIMARY KEY,
    in_progress INTEGER NOT NULL DEFAULT 0
);
CREATE TABLE IF NOT EXISTS lake_maintenance_history (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    table_id BIGINT NOT NULL,
    operation TEXT NOT NULL,        -- 'compact' | 'vacuum'
    started_at TEXT NOT NULL,
    finished_at TEXT,
    files_affected BIGINT NOT NULL DEFAULT 0,
    outcome TEXT NOT NULL DEFAULT 'running'  -- running|ok|skipped|failed
);
CREATE TABLE IF NOT EXISTS lake_replay_epochs (
    table_id BIGINT PRIMARY KEY,
    replay_epoch TEXT NOT NULL,
    pending_replay_epoch TEXT,
    updated_at TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS lake_commit_log (
    id INTEGER PRIMARY KEY CHECK (id = 1),  -- singleton high-water row
    commit_lsn BIGINT NOT NULL,
    tx_ordinal BIGINT NOT NULL,
    commit_end_lsn BIGINT
);
CREATE TABLE IF NOT EXISTS lake_replay_tokens (
    token TEXT PRIMARY KEY
);
""")
        # older catalogs: add per-file epoch + inline payload columns
        cols = {r[1] for r in self._db.execute(
            "PRAGMA table_info(lake_files)")}
        if "replay_epoch" not in cols:
            self._db.execute(
                "ALTER TABLE lake_files ADD COLUMN replay_epoch TEXT "
                f"NOT NULL DEFAULT '{LEGACY_REPLAY_EPOCH}'")
        if "inline_payload" not in cols:
            self._db.execute(
                "ALTER TABLE lake_files ADD COLUMN inline_payload BLOB")
        self._db.commit()
        # resume an interrupted replay-epoch transition (two-phase:
        # begin→reset→complete; a crash between begin and complete re-runs
        # the reset — an extra empty generation is harmless — and promotes)
        for (tid,) in self._db.execute(
                "SELECT table_id FROM lake_replay_epochs "
                "WHERE pending_replay_epoch IS NOT NULL").fetchall():
            await self._finish_replay_reset(tid)

    def _catalog(self) -> sqlite3.Connection:
        if self._db is None:
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           "lake destination not started")
        return self._db

    def _table_row(self, table_id: TableId):
        return self._catalog().execute(
            "SELECT name, schema_json, generation, max_seq FROM lake_tables "
            "WHERE table_id = ?", (table_id,)).fetchone()

    def _ensure_table(self, schema: ReplicatedTableSchema) -> tuple[str, int]:
        row = self._table_row(schema.id)
        name = escaped_table_name(schema.name)
        db = self._catalog()
        if row is None:
            db.execute(
                "INSERT INTO lake_tables (table_id, name, schema_json) "
                "VALUES (?, ?, ?)",
                (schema.id, name, json.dumps(schema.to_json())))
            db.commit()
            return name, 0
        if json.loads(row[1]) != schema.to_json():
            db.execute("UPDATE lake_tables SET schema_json = ? "
                       "WHERE table_id = ?",
                       (json.dumps(schema.to_json()), schema.id))
            db.commit()
        return row[0], row[2]

    # -- file writing -------------------------------------------------------------

    def _write_parquet(self, table_dir: Path, rb: pa.RecordBatch) -> Path:
        table_dir.mkdir(parents=True, exist_ok=True)
        path = table_dir / f"data-{uuid.uuid4().hex}.parquet"
        pq.write_table(pa.Table.from_batches([rb]), path)
        return path

    def _record_file(self, table_id: TableId, generation: int,
                     path: "Path | str", kind: str, rows: int, max_seq: str,
                     epoch: str = LEGACY_REPLAY_EPOCH,
                     inline_payload: "bytes | None" = None) -> None:
        db = self._catalog()
        db.execute(
            "INSERT INTO lake_files (table_id, generation, path, kind, "
            "row_count, max_seq, replay_epoch, inline_payload) "
            "VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
            (table_id, generation, str(path), kind, rows, max_seq, epoch,
             inline_payload))
        if max_seq:
            db.execute("UPDATE lake_tables SET max_seq = MAX(max_seq, ?) "
                       "WHERE table_id = ?", (max_seq, table_id))
        db.commit()

    # -- Destination ---------------------------------------------------------------

    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        await self._wait_maintenance_clear(schema.id)
        name, gen = self._ensure_table(schema)
        if batch.num_rows:
            rb = batch.to_arrow()
            path = self._write_parquet(self.root / name, rb)
            self._record_file(schema.id, gen, path, "base", batch.num_rows,
                              "", self.current_replay_epoch(schema.id))
        return WriteAck.durable()

    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        for op in sequential_event_program(expand_batch_events(events)):
            if op[0] == "rows":
                _, schema, evs = op
                await self._write_cdc_file(schema, evs)
            elif op[0] == "truncate":
                for sch in op[1].schemas:
                    await self.truncate_table(sch.id)
            else:
                self._ensure_table(op[1].new_schema)
        return WriteAck.durable()

    # -- columnar seam --------------------------------------------------------

    async def write_table_batch(self, schema: ReplicatedTableSchema,
                                batch: ColumnarBatch) -> WriteAck:
        # write_table_rows is already Arrow-native; the seam override just
        # keeps the copy path's op label distinct for wrappers
        return await self.write_table_rows(schema, batch)

    async def write_event_batches(self, events: Sequence[Event]) -> WriteAck:
        """CDC path, columnar: decoded batch runs go ColumnarBatch → Arrow
        → Parquet/IPC with vectorized CDC metadata columns — no TableRow
        objects, no from_rows re-transpose. Old-tuple/TOAST batches and
        per-row events drop to the row path in place."""
        from .base import sequential_batch_program

        for op in sequential_batch_program(events):
            if op[0] == "batch":
                _, schema, cb = op
                await self._write_cdc_batch(schema, cb)
            elif op[0] == "rows":
                _, schema, evs = op
                await self._write_cdc_file(schema, evs)
            elif op[0] == "truncate":
                for sch in op[1].schemas:
                    await self.truncate_table(sch.id)
            else:
                self._ensure_table(op[1].new_schema)
        return WriteAck.durable()

    # -- transactional seam (docs/destinations.md exactly-once contract) ------

    def supports_transactional_commit(self) -> bool:
        return True

    @transactional_commit
    async def write_event_batches_committed(
            self, events: Sequence[Event], commit: CommitRange) -> WriteAck:
        """Committed CDC write: data files land first, then the WAL
        range commits to the sqlite catalog (`lake_commit_log`, the
        same transaction domain as the file records). A crash between
        them re-streams a flush whose duplicate rows the CDC sequence
        collapse absorbs at read time; replays dedup by exact token in
        `lake_replay_tokens` and never touch the high-water row."""
        db = self._catalog()
        if commit.replay:
            seen = db.execute(
                "SELECT 1 FROM lake_replay_tokens WHERE token = ?",
                (commit.token(),)).fetchone()
            if seen:
                return WriteAck.durable()
        ack = await self.write_event_batches(events)
        if commit.replay:
            db.execute("INSERT OR IGNORE INTO lake_replay_tokens "
                       "(token) VALUES (?)", (commit.token(),))
        else:
            lsn, ordinal = commit.high
            # monotone guard in SQL: out-of-order finalization must not
            # move the recorded high-water backwards
            db.execute(
                "INSERT INTO lake_commit_log "
                "(id, commit_lsn, tx_ordinal, commit_end_lsn) "
                "VALUES (1, ?, ?, ?) ON CONFLICT(id) DO UPDATE SET "
                "commit_lsn = excluded.commit_lsn, "
                "tx_ordinal = excluded.tx_ordinal, "
                "commit_end_lsn = excluded.commit_end_lsn "
                "WHERE excluded.commit_lsn > lake_commit_log.commit_lsn "
                "OR (excluded.commit_lsn = lake_commit_log.commit_lsn "
                "AND excluded.tx_ordinal > lake_commit_log.tx_ordinal)",
                (lsn, ordinal, commit.commit_end_lsn))
        db.commit()
        return ack

    async def recover_high_water(self) -> "CommitRange | None":
        row = self._catalog().execute(
            "SELECT commit_lsn, tx_ordinal, commit_end_lsn "
            "FROM lake_commit_log WHERE id = 1").fetchone()
        if row is None:
            return None
        return CommitRange(high=(int(row[0]), int(row[1])),
                           commit_end_lsn=int(row[2]) if row[2] else None)

    @hot_loop
    async def _write_cdc_batch(self, schema: ReplicatedTableSchema,
                               cb) -> None:
        """@hot_loop: the lake CDC egress hot path — ColumnarBatch → Arrow
        with vectorized metadata, no row objects (etl-lint rule 13)."""
        from .util import (change_type_arrow, fixed_width_string_arrow,
                           sequence_number_buffer)

        await self._wait_maintenance_clear(schema.id)
        name, gen = self._ensure_table(schema)
        row = self._table_row(schema.id)
        watermark = row[3] if row else ""
        n = cb.num_rows
        ordinals = np.arange(n, dtype=np.uint64)
        seq_buf = sequence_number_buffer(cb.commit_lsns, cb.tx_ordinals,
                                         ordinals)
        max_seq = max(seq_buf.reshape(-1).view("S50").tolist()).decode() \
            if n else ""
        if watermark and max_seq <= watermark:
            return  # replay-epoch dedup: whole batch already applied
        rb = cb.batch.to_arrow()
        rb = rb.append_column(CHANGE_TYPE_COLUMN,
                              change_type_arrow(cb.change_types))
        rb = rb.append_column(
            CHANGE_SEQUENCE_COLUMN,
            # the watermark render above already produced the (n, 50)
            # buffer — build the Arrow column from it instead of
            # re-rendering (the device-egress fixed-buffer idiom)
            fixed_width_string_arrow(seq_buf))
        await self._store_cdc_rb(schema, name, gen, rb, n, max_seq)

    async def _write_cdc_file(self, schema: ReplicatedTableSchema,
                              evs: list) -> None:
        from ..models.cell import TOAST_UNCHANGED

        await self._wait_maintenance_clear(schema.id)
        name, gen = self._ensure_table(schema)
        row = self._table_row(schema.id)
        watermark = row[3] if row else ""
        seqs, types, rows, missing = [], [], [], []
        for i, e in enumerate(evs):
            seq = e.sequence_key.with_ordinal(i)
            seqs.append(seq)
            if isinstance(e, DeleteEvent):
                types.append(CDC_DELETE)
                rows.append(e.old_row)
                missing.append(None)
            else:
                omitted = [c.name for c, v
                           in zip(schema.replicated_columns, e.row.values)
                           if v is TOAST_UNCHANGED]
                if omitted and isinstance(e, UpdateEvent) \
                        and e.old_row is not None \
                        and _identity_values(schema, e.old_row) \
                        != _identity_values(schema, e.row):
                    # the old-identity row is deleted by the split program;
                    # a patch keyed by the NEW identity has no stored row
                    # to preserve columns from — unreconstructable
                    raise EtlError(
                        ErrorKind.SOURCE_REPLICA_IDENTITY,
                        f"lake: identity-changing update for {schema.name} "
                        f"omits TOASTed column(s) {omitted}; set REPLICA "
                        f"IDENTITY FULL on the source table.")
                if omitted:
                    # unchanged-TOAST without an old image: column-wise
                    # patch — stored values for the omitted columns are
                    # preserved at collapse (ducklake/batches.rs Partial)
                    types.append(CDC_PATCH)
                    missing.append(json.dumps(omitted))
                else:
                    types.append(CDC_UPSERT)
                    missing.append(None)
                rows.append(e.row)
        max_seq = max(seqs)
        if watermark and max_seq <= watermark:
            return  # replay-epoch dedup: whole batch already applied
        batch = ColumnarBatch.from_rows(schema, rows)
        rb = batch.to_arrow()
        rb = rb.append_column(CHANGE_TYPE_COLUMN,
                              pa.array(types, type=pa.string()))
        rb = rb.append_column(CHANGE_SEQUENCE_COLUMN,
                              pa.array(seqs, type=pa.string()))
        if any(m is not None for m in missing):
            rb = rb.append_column(PATCH_MISSING_COLUMN,
                                  pa.array(missing, type=pa.string()))
        await self._store_cdc_rb(schema, name, gen, rb, len(rows), max_seq)

    async def _store_cdc_rb(self, schema: ReplicatedTableSchema, name: str,
                            gen: int, rb: pa.RecordBatch, n_rows: int,
                            max_seq: str) -> None:
        """Shared CDC storage tail (columnar + row paths): catalog-inlined
        IPC blob for tiny batches, Parquet file otherwise, then the
        inline-flush and compaction policies."""
        epoch = self.current_replay_epoch(schema.id)
        if 0 < rb.nbytes < self.config.inline_max_bytes:
            # data inlining (ducklake/inline_size.rs): tiny CDC batches go
            # into the catalog as Arrow IPC blobs, not 1-row Parquet files
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, rb.schema) as w:
                w.write_batch(rb)
            self._record_file(schema.id, gen, "", "cdc", n_rows,
                              max_seq, epoch,
                              sink.getvalue().to_pybytes())
            if self._pending_inline_bytes(schema.id, gen) \
                    >= self.config.inline_flush_bytes:
                await self.flush_inlined(schema.id)
        else:
            path = self._write_parquet(self.root / name, rb)
            self._record_file(schema.id, gen, path, "cdc", n_rows,
                              max_seq, epoch)
        if self._cdc_file_count(schema.id, gen) >= self.config.compact_min_files:
            await self.compact(schema.id)

    def _pending_inline_bytes(self, table_id: TableId, gen: int) -> int:
        """Accumulated catalog-inlined bytes for one table generation —
        the flush-policy input, exported as a gauge (reference
        DuckLakePendingInlineSizeSampler)."""
        from ..telemetry.metrics import (ETL_LAKE_INLINED_DATA_BYTES,
                                         LABEL_TABLE, registry)

        (n,) = self._catalog().execute(
            PENDING_INLINE_BYTES_SQL, (table_id, gen)).fetchone()
        registry.gauge_set(ETL_LAKE_INLINED_DATA_BYTES, n,
                           labels={LABEL_TABLE: str(table_id)})
        return int(n)

    async def flush_inlined(self, table_id: TableId) -> int:
        """Flush this table's inlined CDC batches into ONE Parquet file.
        Sequence-aware collapse makes the reordering safe: application
        order is the CHANGE_SEQUENCE sort, not catalog insertion order.
        Returns the number of inlined entries flushed."""
        db = self._catalog()
        db.execute("BEGIN IMMEDIATE")
        try:
            row = db.execute(
                "SELECT name, schema_json, generation, max_seq FROM "
                "lake_tables WHERE table_id = ?", (table_id,)).fetchone()
            if row is None:
                db.execute("ROLLBACK")
                return 0
            name, _, gen, _ = row
            entries = db.execute(
                "SELECT id, inline_payload, max_seq, replay_epoch FROM "
                "lake_files WHERE table_id = ? AND generation = ? AND "
                "inline_payload IS NOT NULL ORDER BY id",
                (table_id, gen)).fetchall()
            if not entries:
                db.execute("ROLLBACK")
                return 0
            batches = []
            for _id, payload, _seq, _ep in entries:
                with pa.ipc.open_stream(payload) as r:
                    batches.extend(r)
            merged = _concat_cdc_batches(batches)
            path = self.root / name / f"data-{uuid.uuid4().hex}.parquet"
            path.parent.mkdir(parents=True, exist_ok=True)
            pq.write_table(merged, path)
            ids = [e[0] for e in entries]
            db.execute(f"DELETE FROM lake_files WHERE id IN "
                       f"({','.join('?' * len(ids))})", ids)
            db.execute(
                "INSERT INTO lake_files (table_id, generation, path, kind, "
                "row_count, max_seq, replay_epoch) "
                "VALUES (?, ?, ?, 'cdc', ?, ?, ?)",
                (table_id, gen, str(path), merged.num_rows,
                 max(e[2] for e in entries), entries[-1][3]))
            db.commit()
        except BaseException:
            try:
                db.execute("ROLLBACK")
            except sqlite3.OperationalError:
                pass  # commit failures auto-rollback; keep the real error
            # the rollback restored the inlined entries, so the merged
            # file is unreferenced — remove it or it leaks forever
            # (vacuum only deletes cataloged paths)
            try:
                path.unlink(missing_ok=True)
            except (OSError, UnboundLocalError):
                pass
            raise
        self._pending_inline_bytes(table_id, gen)  # refresh the gauge
        return len(entries)

    def _cdc_file_count(self, table_id: TableId, gen: int) -> int:
        """Real CDC FILES only: catalog-inlined entries are the cheap tier
        flush_inlined consolidates — counting them would fire a full
        compaction after a handful of tiny batches, the exact cost
        inlining exists to avoid."""
        return self._catalog().execute(
            CDC_FILE_COUNT_SQL, (table_id, gen)).fetchone()[0]

    async def drop_table(self, table_id: TableId,
                         schema: ReplicatedTableSchema | None = None) -> None:
        # schema hint unused: the catalog is persistent, so the name
        # mapping survives restarts
        db = self._catalog()
        for (path,) in db.execute("SELECT path FROM lake_files WHERE "
                                  "table_id = ?", (table_id,)):
            if path:  # inlined entries have no file
                Path(path).unlink(missing_ok=True)
        db.execute("DELETE FROM lake_files WHERE table_id = ?", (table_id,))
        db.execute("DELETE FROM lake_tables WHERE table_id = ?", (table_id,))
        # a re-added table must start from LEGACY_REPLAY_EPOCH, not inherit
        # the dropped table's epoch chain
        db.execute("DELETE FROM lake_replay_epochs WHERE table_id = ?",
                   (table_id,))
        db.commit()
        from ..telemetry.metrics import (ETL_LAKE_INLINED_DATA_BYTES,
                                         LABEL_TABLE, registry)

        # clear the pending-inline gauge so a dropped table doesn't report
        # phantom unflushed bytes forever
        registry.gauge_set(ETL_LAKE_INLINED_DATA_BYTES, 0,
                           labels={LABEL_TABLE: str(table_id)})

    # -- replay epochs (reference ducklake/replay_epoch.rs) -------------------

    def current_replay_epoch(self, table_id: TableId) -> str:
        row = self._catalog().execute(
            "SELECT replay_epoch FROM lake_replay_epochs WHERE "
            "table_id = ?", (table_id,)).fetchone()
        return row[0] if row else LEGACY_REPLAY_EPOCH

    def _begin_replay_reset(self, table_id: TableId) -> str:
        """Start (or resume) an epoch transition: records the pending
        epoch BEFORE the reset mutates anything, so a crash mid-reset is
        detected and completed at the next startup (replay_epoch.rs
        begin_table_replay_epoch_transition; idempotent via coalesce)."""
        import datetime as _dt

        db = self._catalog()
        pending = uuid.uuid4().hex
        db.execute(
            "INSERT INTO lake_replay_epochs "
            "(table_id, replay_epoch, pending_replay_epoch, updated_at) "
            "VALUES (?, ?, ?, ?) "
            "ON CONFLICT (table_id) DO UPDATE SET "
            "pending_replay_epoch = COALESCE("
            "  lake_replay_epochs.pending_replay_epoch, "
            "  excluded.pending_replay_epoch), "
            "updated_at = excluded.updated_at",
            (table_id, LEGACY_REPLAY_EPOCH, pending,
             _dt.datetime.now(_dt.timezone.utc).isoformat()))
        db.commit()
        row = db.execute(
            "SELECT pending_replay_epoch FROM lake_replay_epochs "
            "WHERE table_id = ?", (table_id,)).fetchone()
        return row[0]

    async def _finish_replay_reset(self, table_id: TableId) -> None:
        """The reset itself + promotion: bump the generation (re-running
        after a crash just adds another empty — therefore identical —
        generation) and promote the pending epoch
        (complete_table_replay_epoch_transition)."""
        db = self._catalog()
        db.execute("UPDATE lake_tables SET generation = generation + 1, "
                   "max_seq = '' WHERE table_id = ?", (table_id,))
        db.execute(
            "UPDATE lake_replay_epochs SET "
            "replay_epoch = pending_replay_epoch, "
            "pending_replay_epoch = NULL WHERE table_id = ? "
            "AND pending_replay_epoch IS NOT NULL", (table_id,))
        db.commit()

    async def truncate_table(self, table_id: TableId) -> None:
        """Generation bump UNDER a replay-epoch transition: reads see only
        the new (empty) generation, and the rotated epoch makes the
        sequence watermark inert for re-replayed data — a re-streamed
        batch after the reset can never be deduped against pre-reset
        sequence keys (the versioned-successor stance + replay_epoch.rs)."""
        self._begin_replay_reset(table_id)
        await self._finish_replay_reset(table_id)

    async def shutdown(self) -> None:
        if self._db is not None:
            self._db.close()
            self._db = None

    # -- reads (the `_current` semantics) -----------------------------------------

    def read_current(self, table_id: TableId) -> pa.Table:
        """Collapse base + CDC files into live rows: per identity key, the
        highest sequence wins; deletes drop the key."""
        row = self._table_row(table_id)
        if row is None:
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           f"unknown table {table_id}")
        _, _, gen, _ = row
        files = self._catalog().execute(
            "SELECT path, kind, inline_payload FROM lake_files WHERE "
            "table_id = ? AND generation = ? ORDER BY id",
            (table_id, gen)).fetchall()
        return self._collapse(row, files)

    @staticmethod
    def _read_entry(path: str, payload: "bytes | None") -> pa.Table:
        """One catalog entry's rows: a Parquet file, or a catalog-inlined
        Arrow IPC blob (path == '')."""
        if payload is not None:
            with pa.ipc.open_stream(payload) as r:
                return pa.Table.from_batches(list(r))
        return pq.read_table(path)

    def _collapse(self, table_row,
                  files: "list[tuple[str, str, bytes | None]]") -> pa.Table:
        """Collapse an EXPLICIT (path, kind, inline_payload) entry list —
        the caller passes the lake_tables row and file set it observed
        (compact: under its transaction) so the merge and the catalog swap
        agree on inputs.

        Application order is base entries (catalog order) then CDC records
        sorted by CHANGE_SEQUENCE — the sequence keys are the table's
        replay order, so catalog insertion order stops mattering and an
        inline flush may merge non-contiguous entries safely."""
        name, schema_json, gen, _ = table_row
        schema = ReplicatedTableSchema.from_json(json.loads(schema_json))
        key_cols = [c.name for c in schema.identity_columns()] or \
            [c.name for c in schema.replicated_columns]
        live: dict[tuple, dict] = {}
        cdc_records: list[tuple[str, dict]] = []
        for path, kind, payload in files:
            t = self._read_entry(path, payload)
            if kind != "cdc":
                for rec in t.to_pylist():
                    live[tuple(rec[k] for k in key_cols)] = rec
                continue
            for rec in t.to_pylist():
                cdc_records.append((rec.get(CHANGE_SEQUENCE_COLUMN) or "",
                                    rec))
        cdc_records.sort(key=lambda sr: sr[0])
        for _seq, rec in cdc_records:
            key = tuple(rec[k] for k in key_cols)
            ct = rec.get(CHANGE_TYPE_COLUMN)
            if ct == CDC_DELETE:
                live.pop(key, None)
                continue
            patch_missing = rec.get(PATCH_MISSING_COLUMN)
            rec.pop(CHANGE_TYPE_COLUMN, None)
            rec.pop(CHANGE_SEQUENCE_COLUMN, None)
            rec.pop(PATCH_MISSING_COLUMN, None)
            if ct == CDC_PATCH:
                # column-wise update: omitted columns keep stored values;
                # patch for an absent key is a no-op (reference SQL
                # UPDATE-with-predicate semantics)
                prev = live.get(key)
                if prev is None:
                    continue
                omitted = set(json.loads(patch_missing or "[]"))
                for k, v in rec.items():
                    if k not in omitted:
                        prev[k] = v
            else:
                live[key] = rec
        if not live:
            return pa.table({c.name: [] for c in schema.replicated_columns})
        return pa.Table.from_pylist(list(live.values()))

    # -- maintenance (external-maintenance parity) ----------------------------------

    async def vacuum(self, table_id: TableId) -> int:
        """Delete files from superseded generations, under the maintenance
        flag (a concurrent reader of the current generation never loses
        files; old-generation files are unreachable once the bump commits,
        but the flag still serializes vs. other maintenance)."""
        db = self._catalog()
        busy = db.execute("SELECT in_progress FROM lake_maintenance WHERE "
                          "table_id = ?", (table_id,)).fetchone()
        if busy and busy[0]:
            return 0
        hid = self._history_start(table_id, "vacuum")
        db.execute("INSERT INTO lake_maintenance (table_id, in_progress) "
                   "VALUES (?, 1) ON CONFLICT (table_id) DO UPDATE SET "
                   "in_progress = 1", (table_id,))
        db.commit()
        outcome = "failed"
        n = 0
        try:
            rows = db.execute(
                "SELECT f.id, f.path FROM lake_files f JOIN lake_tables t "
                "ON t.table_id = f.table_id WHERE f.table_id = ? "
                "AND f.generation < t.generation", (table_id,)).fetchall()
            for fid, path in rows:
                if path:  # inlined entries have no file
                    Path(path).unlink(missing_ok=True)
                db.execute("DELETE FROM lake_files WHERE id = ?", (fid,))
            db.commit()
            n = len(rows)
            outcome = "ok" if n else "skipped"
            return n
        finally:
            db.execute("UPDATE lake_maintenance SET in_progress = 0 WHERE "
                       "table_id = ?", (table_id,))
            db.commit()
            self._history_finish(hid, outcome, n)

    def table_ids(self) -> "list[TableId]":
        return [r[0] for r in self._catalog().execute(
            "SELECT table_id FROM lake_tables").fetchall()]

    # writers give up on the maintenance flag after this long: a crashed
    # external maintenance process (flag never cleared) must surface as a
    # retryable error, not wedge the pipeline silently
    MAINTENANCE_WAIT_TIMEOUT_S = 60.0

    def _history_start(self, table_id: TableId, op: str) -> int:
        import datetime as _dt

        db = self._catalog()
        cur = db.execute(
            "INSERT INTO lake_maintenance_history "
            "(table_id, operation, started_at) VALUES (?, ?, ?)",
            (table_id, op, _dt.datetime.now(_dt.timezone.utc).isoformat()))
        db.commit()
        return cur.lastrowid

    def _history_finish(self, hid: int, outcome: str, files: int) -> None:
        import datetime as _dt

        db = self._catalog()
        db.execute(
            "UPDATE lake_maintenance_history SET finished_at = ?, "
            "outcome = ?, files_affected = ? WHERE id = ?",
            (_dt.datetime.now(_dt.timezone.utc).isoformat(), outcome,
             files, hid))
        db.commit()

    def current_cdc_file_count(self, table_id: TableId) -> int:
        """CDC files in the table's CURRENT generation — the compaction
        policy input (stable public surface; callers must not index
        catalog rows)."""
        row = self._table_row(table_id)
        if row is None:
            return 0
        return self._cdc_file_count(table_id, row[2])

    def pending_inline_bytes(self, table_id: TableId) -> int:
        """Catalog-inlined bytes awaiting flush in the current generation
        — the inline-flush policy input (maintenance coordination)."""
        row = self._table_row(table_id)
        if row is None:
            return 0
        return self._pending_inline_bytes(table_id, row[2])

    def record_maintenance_skip(self, table_id: TableId, op: str) -> None:
        """Audit row for a policy decision that never invoked the op."""
        self._history_finish(self._history_start(table_id, op),
                             "skipped", 0)

    def maintenance_history(self, table_id: "TableId | None" = None,
                            limit: int = 50) -> list[dict]:
        """Recent maintenance operations, newest first (reference
        etl-maintenance operation history)."""
        db = self._catalog()
        where = "WHERE table_id = ?" if table_id is not None else ""
        params = (table_id, limit) if table_id is not None else (limit,)
        rows = db.execute(
            f"SELECT table_id, operation, started_at, finished_at, "
            f"files_affected, outcome FROM lake_maintenance_history "
            f"{where} ORDER BY id DESC LIMIT ?", params).fetchall()
        return [{"table_id": t, "operation": op, "started_at": s0,
                 "finished_at": f, "files_affected": n, "outcome": o}
                for t, op, s0, f, n, o in rows]

    async def _wait_maintenance_clear(self, table_id: TableId) -> None:
        """Writers block while external maintenance holds the table
        (ADVICE r1: writers previously never checked the flag, so an
        external compaction could race a live CDC commit)."""
        import logging

        db = self._catalog()
        waited = 0.0
        warned = False
        while True:
            busy = db.execute(
                "SELECT in_progress FROM lake_maintenance WHERE "
                "table_id = ?", (table_id,)).fetchone()
            if not busy or not busy[0]:
                return
            if waited >= self.MAINTENANCE_WAIT_TIMEOUT_S:
                raise EtlError(
                    ErrorKind.DESTINATION_FAILED,
                    f"lake: maintenance flag for table {table_id} held for "
                    f">{self.MAINTENANCE_WAIT_TIMEOUT_S:.0f}s — external "
                    f"maintenance crashed without clearing it? (UPDATE "
                    f"lake_maintenance SET in_progress = 0 to recover)")
            if waited >= 5.0 and not warned:
                warned = True
                logging.getLogger("etl_tpu.destinations").warning(
                    "lake: writer waiting on maintenance flag for table %s",
                    table_id)
            await asyncio.sleep(0.05)
            waited += 0.05

    async def compact(self, table_id: TableId) -> int:
        """Merge the current generation's files into one base file.
        Returns merged file count. Guarded by the catalog maintenance flag
        (reference external_maintenance.rs coordination).

        The observe→merge→replace sequence runs inside ONE immediate
        catalog transaction and deletes ONLY the observed file ids — a CDC
        file committed concurrently (external maintenance binary vs a live
        replicator) survives the swap instead of being dropped unmerged
        (ADVICE r1 data-loss race)."""
        db = self._catalog()
        busy = db.execute("SELECT in_progress FROM lake_maintenance WHERE "
                          "table_id = ?", (table_id,)).fetchone()
        if busy and busy[0]:
            return 0
        hid = self._history_start(table_id, "compact")
        db.execute("INSERT INTO lake_maintenance (table_id, in_progress) "
                   "VALUES (?, 1) ON CONFLICT (table_id) DO UPDATE SET "
                   "in_progress = 1", (table_id,))
        db.commit()
        n_files = 0
        outcome = "skipped"
        try:
            db.execute("BEGIN IMMEDIATE")
            row = db.execute(
                "SELECT name, schema_json, generation, max_seq FROM "
                "lake_tables WHERE table_id = ?", (table_id,)).fetchone()
            if row is None:
                db.execute("ROLLBACK")
                return 0
            name, _, gen, max_seq = row
            files = db.execute(
                "SELECT id, path, kind, inline_payload FROM lake_files "
                "WHERE table_id = ? AND generation = ? ORDER BY id",
                (table_id, gen)).fetchall()
            if len(files) < 2:
                db.execute("ROLLBACK")
                return 0
            merged = self._collapse(row, [(p, k, b) for _, p, k, b in files])
            path = self.root / name / f"data-{uuid.uuid4().hex}.parquet"
            path.parent.mkdir(parents=True, exist_ok=True)
            pq.write_table(merged, path)
            ids = [fid for fid, *_ in files]
            db.execute(
                f"DELETE FROM lake_files WHERE id IN "
                f"({','.join('?' * len(ids))})", ids)
            db.execute(
                "INSERT INTO lake_files (table_id, generation, path, kind, "
                "row_count, max_seq, replay_epoch) "
                "VALUES (?, ?, ?, 'base', ?, ?, ?)",
                (table_id, gen, str(path), merged.num_rows, max_seq,
                 self.current_replay_epoch(table_id)))
            db.commit()
            self._pending_inline_bytes(table_id, gen)  # refresh the gauge
            for _id, p, _k, _b in files:
                if p:  # inlined entries have no file
                    Path(p).unlink(missing_ok=True)
            n_files = len(files)
            outcome = "ok"
            return n_files
        except BaseException:
            outcome = "failed"
            try:
                db.execute("ROLLBACK")
            except sqlite3.OperationalError:
                pass
            # rollback restored the source file rows: the merged file is
            # unreferenced — remove it or it leaks (vacuum only deletes
            # cataloged paths)
            try:
                path.unlink(missing_ok=True)
            except (OSError, UnboundLocalError):
                pass
            raise
        finally:
            db.execute("UPDATE lake_maintenance SET in_progress = 0 WHERE "
                       "table_id = ?", (table_id,))
            db.commit()
            self._history_finish(hid, outcome, n_files)
