"""Iceberg v2 table-metadata writer: Avro manifests + snapshots.

Reference parity: crates/etl-destinations/src/iceberg/{core,schema}.rs —
the reference commits Arrow/Parquet appends as REAL Iceberg snapshots:
a manifest file (Avro) listing the data files with per-column statistics,
a manifest list (Avro) naming the manifests with row-count summaries, and
a snapshot record referencing the manifest list. This module produces the
same artifacts from scratch:

- a minimal, schema-driven Avro Object Container File writer (the
  environment has no avro library — same stance as the hand-rolled
  protobuf codec in bq_proto.py);
- the Iceberg v2 `manifest_entry` / `manifest_file` Avro schemas (public
  spec, https://iceberg.apache.org/spec/ — field-id annotations kept so
  conformant readers can map columns);
- data-file statistics gathered from the Parquet footer (record counts,
  column sizes, null counts, lower/upper bounds in Iceberg's
  single-value binary serialization).

The independent READER used to verify these files lives in
etl_tpu/testing/avro_reader.py and deliberately shares no code with this
writer (VERDICT r3 #5: break the encode/decode self-confirmation loop).
"""

from __future__ import annotations

import json
import struct
import uuid
from dataclasses import dataclass, field
from pathlib import Path

# ---------------------------------------------------------------------------
# Avro binary encoding (writer side)
# ---------------------------------------------------------------------------


def _zigzag(n: int) -> bytes:
    """Avro int/long: zigzag + base-128 varint, little-endian groups."""
    u = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _encode(schema, value, out: bytearray) -> None:
    """Schema-driven Avro binary encoding (subset: the types Iceberg
    metadata uses — null/boolean/int/long/bytes/string/record/array/
    union/map)."""
    if isinstance(schema, list):  # union — here always [null, X]
        if value is None:
            out += _zigzag(schema.index("null"))
            return
        branch = next(i for i, s in enumerate(schema) if s != "null")
        out += _zigzag(branch)
        _encode(schema[branch], value, out)
        return
    t = schema["type"] if isinstance(schema, dict) else schema
    if t == "null":
        return
    if t == "boolean":
        out.append(1 if value else 0)
    elif t in ("int", "long"):
        out += _zigzag(int(value))
    elif t == "float":
        out += struct.pack("<f", value)
    elif t == "double":
        out += struct.pack("<d", value)
    elif t == "bytes":
        out += _zigzag(len(value))
        out += value
    elif t == "string":
        raw = value.encode()
        out += _zigzag(len(raw))
        out += raw
    elif t == "record":
        for f in schema["fields"]:
            _encode(f["type"], value.get(f["name"]), out)
    elif t == "array":
        items = list(value)
        if items:
            out += _zigzag(len(items))
            for item in items:
                _encode(schema["items"], item, out)
        out += _zigzag(0)
    elif t == "map":
        entries = list(value.items())
        if entries:
            out += _zigzag(len(entries))
            for k, v in entries:
                _encode("string", k, out)
                _encode(schema["values"], v, out)
        out += _zigzag(0)
    else:
        raise ValueError(f"avro writer: unsupported type {t!r}")


_OCF_MAGIC = b"Obj\x01"


def write_avro_ocf(path: str | Path, schema: dict, records: list[dict],
                   metadata: dict[str, str] | None = None) -> int:
    """Write an Avro Object Container File (null codec, one block).
    Returns the file length in bytes."""
    body = bytearray()
    for rec in records:
        _encode(schema, rec, body)
    sync = uuid.uuid4().bytes  # 16-byte sync marker
    meta = {"avro.schema": json.dumps(schema), "avro.codec": "null"}
    for k, v in (metadata or {}).items():
        meta[k] = v
    out = bytearray(_OCF_MAGIC)
    _encode({"type": "map", "values": "string"}, meta, out)
    out += sync
    out += _zigzag(len(records))
    out += _zigzag(len(body))
    out += body
    out += sync
    Path(path).write_bytes(bytes(out))
    return len(out)


# ---------------------------------------------------------------------------
# Iceberg v2 manifest schemas (public spec; field-id annotations preserved)
# ---------------------------------------------------------------------------


def _idmap(name: str, key_id: int, value_id: int, value_type: str) -> dict:
    """Iceberg serializes its int-keyed stat maps as arrays of key/value
    records (logicalType map) so Avro field-ids can annotate both sides."""
    return {"type": "array", "logicalType": "map", "items": {
        "type": "record", "name": name, "fields": [
            {"name": "key", "type": "int", "field-id": key_id},
            {"name": "value", "type": value_type, "field-id": value_id},
        ]}}


DATA_FILE_SCHEMA = {"type": "record", "name": "r2", "fields": [
    {"name": "content", "type": "int", "field-id": 134},
    {"name": "file_path", "type": "string", "field-id": 100},
    {"name": "file_format", "type": "string", "field-id": 101},
    {"name": "partition",
     "type": {"type": "record", "name": "r102", "fields": []},
     "field-id": 102},
    {"name": "record_count", "type": "long", "field-id": 103},
    {"name": "file_size_in_bytes", "type": "long", "field-id": 104},
    {"name": "column_sizes", "type": ["null", _idmap("k117_v118", 117, 118,
                                                     "long")],
     "field-id": 108},
    {"name": "value_counts", "type": ["null", _idmap("k119_v120", 119, 120,
                                                     "long")],
     "field-id": 109},
    {"name": "null_value_counts",
     "type": ["null", _idmap("k121_v122", 121, 122, "long")],
     "field-id": 110},
    {"name": "lower_bounds",
     "type": ["null", _idmap("k126_v127", 126, 127, "bytes")],
     "field-id": 125},
    {"name": "upper_bounds",
     "type": ["null", _idmap("k129_v130", 129, 130, "bytes")],
     "field-id": 128},
]}

MANIFEST_ENTRY_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int", "field-id": 0},
        {"name": "snapshot_id", "type": ["null", "long"], "field-id": 1,
         "default": None},
        {"name": "sequence_number", "type": ["null", "long"], "field-id": 3,
         "default": None},
        {"name": "file_sequence_number", "type": ["null", "long"],
         "field-id": 4, "default": None},
        {"name": "data_file", "type": DATA_FILE_SCHEMA, "field-id": 2},
    ],
}

MANIFEST_FILE_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string", "field-id": 500},
        {"name": "manifest_length", "type": "long", "field-id": 501},
        {"name": "partition_spec_id", "type": "int", "field-id": 502},
        {"name": "content", "type": "int", "field-id": 517},
        {"name": "sequence_number", "type": "long", "field-id": 515},
        {"name": "min_sequence_number", "type": "long", "field-id": 516},
        {"name": "added_snapshot_id", "type": "long", "field-id": 503},
        {"name": "added_files_count", "type": "int", "field-id": 504},
        {"name": "existing_files_count", "type": "int", "field-id": 505},
        {"name": "deleted_files_count", "type": "int", "field-id": 506},
        {"name": "added_rows_count", "type": "long", "field-id": 512},
        {"name": "existing_rows_count", "type": "long", "field-id": 513},
        {"name": "deleted_rows_count", "type": "long", "field-id": 514},
    ],
}


# ---------------------------------------------------------------------------
# Data-file statistics (from the Parquet footer) + single-value bounds
# ---------------------------------------------------------------------------


def bound_bytes(value, iceberg_type: str = "") -> bytes | None:
    """Iceberg single-value binary serialization for bounds (spec
    Appendix D): little-endian fixed width — 4 bytes for int/float/date,
    8 for long/double/timestamps — UTF-8 for strings. The declared
    `iceberg_type` picks the width; a conformant reader checks buffer
    sizes against the field type, so packing every int as 8 bytes would
    break scan planning on real catalogs. Types outside the subset
    return None (bound omitted)."""
    import datetime

    if isinstance(value, bool):
        return b"\x01" if value else b"\x00"
    if isinstance(value, int):
        return struct.pack("<i" if iceberg_type in ("int", "date")
                           else "<q", value)
    if isinstance(value, float):
        return struct.pack("<f" if iceberg_type == "float" else "<d",
                           value)
    if isinstance(value, str):
        return value.encode()
    if isinstance(value, bytes):
        return value
    if isinstance(value, datetime.datetime):
        epoch = datetime.datetime(1970, 1, 1, tzinfo=value.tzinfo)
        return struct.pack("<q", int((value - epoch).total_seconds() * 1e6))
    if isinstance(value, datetime.date):
        return struct.pack("<i", (value - datetime.date(1970, 1, 1)).days)
    return None


@dataclass
class DataFileInfo:
    """One Parquet data file plus the statistics Iceberg records for it."""

    file_path: str
    record_count: int
    file_size_in_bytes: int
    column_sizes: dict[int, int] = field(default_factory=dict)
    value_counts: dict[int, int] = field(default_factory=dict)
    null_value_counts: dict[int, int] = field(default_factory=dict)
    lower_bounds: dict[int, bytes] = field(default_factory=dict)
    upper_bounds: dict[int, bytes] = field(default_factory=dict)


def data_file_stats(parquet_path: str | Path,
                    field_ids: dict[str, int],
                    field_types: dict[int, str] | None = None
                    ) -> DataFileInfo:
    """Gather Iceberg data-file statistics from a Parquet footer.
    `field_ids` maps column name → Iceberg field id; `field_types` maps
    field id → Iceberg type string (drives bound byte widths)."""
    import pyarrow.parquet as pq

    p = Path(parquet_path)
    meta = pq.ParquetFile(p).metadata
    info = DataFileInfo(file_path=str(p), record_count=meta.num_rows,
                        file_size_in_bytes=p.stat().st_size)
    lows: dict[int, object] = {}
    highs: dict[int, object] = {}
    for rg in range(meta.num_row_groups):
        g = meta.row_group(rg)
        for ci in range(g.num_columns):
            col = g.column(ci)
            name = col.path_in_schema
            fid = field_ids.get(name)
            if fid is None:
                continue
            info.column_sizes[fid] = info.column_sizes.get(fid, 0) \
                + col.total_compressed_size
            info.value_counts[fid] = info.value_counts.get(fid, 0) \
                + col.num_values
            st = col.statistics
            if st is None:
                continue
            if st.null_count is not None:
                info.null_value_counts[fid] = \
                    info.null_value_counts.get(fid, 0) + st.null_count
            if st.has_min_max:
                if fid not in lows or st.min < lows[fid]:
                    lows[fid] = st.min
                if fid not in highs or st.max > highs[fid]:
                    highs[fid] = st.max
    types = field_types or {}
    for fid, v in lows.items():
        b = bound_bytes(v, types.get(fid, ""))
        if b is not None:
            info.lower_bounds[fid] = b
    for fid, v in highs.items():
        b = bound_bytes(v, types.get(fid, ""))
        if b is not None:
            info.upper_bounds[fid] = b
    return info


# ---------------------------------------------------------------------------
# Manifest + manifest-list + snapshot assembly
# ---------------------------------------------------------------------------


def _stat_map(d: dict[int, object]) -> list[dict] | None:
    return [{"key": k, "value": v} for k, v in sorted(d.items())] or None


@dataclass
class ManifestInfo:
    manifest_path: str
    manifest_length: int
    added_files_count: int
    added_rows_count: int
    sequence_number: int


def write_manifest(metadata_dir: str | Path, files: list[DataFileInfo],
                   snapshot_id: int, sequence_number: int,
                   table_schema_json: str) -> ManifestInfo:
    """Write one Avro manifest file listing `files` as ADDED entries."""
    d = Path(metadata_dir)
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"{uuid.uuid4().hex}-m0.avro"
    entries = [{
        "status": 1,  # ADDED
        "snapshot_id": snapshot_id,
        "sequence_number": sequence_number,
        "file_sequence_number": sequence_number,
        "data_file": {
            "content": 0,  # DATA
            "file_path": f.file_path,
            "file_format": "PARQUET",
            "partition": {},
            "record_count": f.record_count,
            "file_size_in_bytes": f.file_size_in_bytes,
            "column_sizes": _stat_map(f.column_sizes),
            "value_counts": _stat_map(f.value_counts),
            "null_value_counts": _stat_map(f.null_value_counts),
            "lower_bounds": _stat_map(f.lower_bounds),
            "upper_bounds": _stat_map(f.upper_bounds),
        },
    } for f in files]
    length = write_avro_ocf(
        path, MANIFEST_ENTRY_SCHEMA, entries,
        metadata={"schema": table_schema_json,
                  "partition-spec": "[]", "partition-spec-id": "0",
                  "format-version": "2", "content": "data"})
    return ManifestInfo(
        manifest_path=str(path), manifest_length=length,
        added_files_count=len(files),
        added_rows_count=sum(f.record_count for f in files),
        sequence_number=sequence_number)


def write_manifest_list(metadata_dir: str | Path,
                        manifests: list[ManifestInfo],
                        snapshot_id: int, sequence_number: int) -> str:
    """Write the Avro manifest list a snapshot points at."""
    d = Path(metadata_dir)
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"snap-{snapshot_id}-1-{uuid.uuid4().hex}.avro"
    records = [{
        "manifest_path": m.manifest_path,
        "manifest_length": m.manifest_length,
        "partition_spec_id": 0,
        "content": 0,
        "sequence_number": m.sequence_number,
        "min_sequence_number": m.sequence_number,
        "added_snapshot_id": snapshot_id,
        "added_files_count": m.added_files_count,
        "existing_files_count": 0,
        "deleted_files_count": 0,
        "added_rows_count": m.added_rows_count,
        "existing_rows_count": 0,
        "deleted_rows_count": 0,
    } for m in manifests]
    write_avro_ocf(path, MANIFEST_FILE_SCHEMA, records,
                   metadata={"snapshot-id": str(snapshot_id),
                             "sequence-number": str(sequence_number),
                             "format-version": "2"})
    return str(path)


def new_snapshot_id() -> int:
    # Iceberg snapshot ids are positive 63-bit values
    return uuid.uuid4().int & ((1 << 62) - 1)


def build_snapshot(snapshot_id: int, parent_snapshot_id: int | None,
                   sequence_number: int, manifest_list: str,
                   operation: str, added_files: int, added_records: int,
                   total_records: int, timestamp_ms: int,
                   schema_id: int) -> dict:
    """Snapshot JSON for the REST commit's add-snapshot update."""
    snap = {
        "snapshot-id": snapshot_id,
        "sequence-number": sequence_number,
        "timestamp-ms": timestamp_ms,
        "manifest-list": manifest_list,
        "schema-id": schema_id,
        "summary": {
            "operation": operation,
            "added-data-files": str(added_files),
            "added-records": str(added_records),
            "total-records": str(total_records),
        },
    }
    if parent_snapshot_id is not None:
        snap["parent-snapshot-id"] = parent_snapshot_id
    return snap
