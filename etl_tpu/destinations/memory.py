"""In-memory destination + the fault-scripting test wrapper.

Reference parity: `MemoryDestination` (crates/etl/src/test_utils) and
`TestDestinationWrapper` with a scripted FIFO fault queue per operation
(test_utils/faults.rs:29-70): Reject / fail-after-apply ("lost-response
ambiguity") / hold / delay — the machinery behind the faulty-destination
integration suite (SURVEY §4.3).
"""

from __future__ import annotations

import asyncio
import enum
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Sequence

from ..analysis.annotations import transactional_commit
from ..models.errors import ErrorKind, EtlError
from ..models.event import Event
from ..models.schema import ReplicatedTableSchema, TableId
from ..models.table_row import ColumnarBatch, TableRow
from .base import (CommitRange, Destination, WriteAck, event_coordinate,
                   expand_batch_events)
from .util import TaskSet


class MemoryDestination(Destination):
    """Durable-by-definition in-memory destination: rows and events are
    captured in plain lists for assertions."""

    def __init__(self) -> None:
        self.table_rows: dict[TableId, list[TableRow]] = defaultdict(list)
        self.events: list[Event] = []
        self.dropped_tables: list[TableId] = []
        self.truncated_tables: list[TableId] = []
        self.started = False

    async def startup(self) -> None:
        self.started = True

    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        self.table_rows[schema.id].extend(batch.to_rows())
        return WriteAck.durable()

    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        self.events.extend(expand_batch_events(events))
        return WriteAck.durable()

    async def drop_table(self, table_id: TableId,
                         schema=None) -> None:
        self.table_rows.pop(table_id, None)
        self.dropped_tables.append(table_id)

    async def truncate_table(self, table_id: TableId) -> None:
        self.table_rows[table_id] = []
        self.truncated_tables.append(table_id)


class TransactionalMemoryDestination(MemoryDestination):
    """Exactly-once fake sink: the in-memory analogue of a sink that
    records the acked WAL coordinate range atomically with the data
    (BigQuery MERGE, ClickHouse dedup tokens, Iceberg snapshot
    properties, Snowpipe offsets). Streamed writes dedup against the
    monotone high-water coordinate — a blind re-stream's rows at
    coordinates ≤ high-water are dropped, whatever the batch boundaries
    of the retry. Replay ranges (`commit.replay`) dedup by EXACT row key
    instead and never move the high-water mark. `high_water_log` is the
    chaos monotonicity evidence; `recover_*` knobs script recovery-query
    faults for the satellite-1 degradation tests."""

    def __init__(self) -> None:
        super().__init__()
        self.high_water: "tuple[int, int]" = (0, 0)
        self.committed_end_lsn = 0
        self.high_water_log: "list[tuple[int, int]]" = []
        self.dedup_skipped_rows = 0
        self.replayed_keys: set = set()
        self.replay_skipped_rows = 0
        self.recover_calls = 0
        # FIFO of EtlErrors the next recover_high_water() calls raise
        # (transient-recovery and degrade-to-blind-re-stream scripting)
        self.recover_faults: "deque[EtlError]" = deque()
        self.recover_delay_s = 0.0
        self.uncoordinated_writes = 0  # CDC writes that bypassed the seam

    def supports_transactional_commit(self) -> bool:
        return True

    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        self.uncoordinated_writes += 1
        return await super().write_events(events)

    @staticmethod
    def _row_key(e: Event) -> "tuple | None":
        coord = event_coordinate(e)
        if coord is None:
            return None
        tid = getattr(getattr(e, "schema", None), "id", None)
        return (tid, coord[0], coord[1], type(e).__name__)

    @transactional_commit
    async def write_event_batches_committed(
            self, events: Sequence[Event],
            commit: "CommitRange | None") -> WriteAck:
        rows = expand_batch_events(list(events))
        if commit is not None and commit.replay:
            kept = []
            for e in rows:
                key = self._row_key(e)
                if key is not None and key in self.replayed_keys:
                    self.replay_skipped_rows += 1
                    continue
                if key is not None:
                    self.replayed_keys.add(key)
                kept.append(e)
        else:
            kept = []
            for e in rows:
                coord = event_coordinate(e)
                if coord is not None and coord <= self.high_water:
                    self.dedup_skipped_rows += 1
                    continue
                kept.append(e)
        # data + coordinate range land in ONE synchronous step — no await
        # between them, so a kill can never observe data without its range
        self.events.extend(kept)
        if commit is not None and not commit.replay:
            if commit.high > self.high_water:
                self.high_water = commit.high
            self.committed_end_lsn = max(
                self.committed_end_lsn, commit.commit_end_lsn or 0)
            self.high_water_log.append(self.high_water)
        if kept or commit is None:
            return WriteAck.durable()
        # fully-deduped flush: nothing was written, so don't fire the
        # DESTINATION_WRITE chaos site for a phantom destination write
        fut = asyncio.get_event_loop().create_future()
        fut.set_result(None)
        return WriteAck(fut)

    async def recover_high_water(self) -> "CommitRange | None":
        self.recover_calls += 1
        if self.recover_delay_s > 0:
            await asyncio.sleep(self.recover_delay_s)
        if self.recover_faults:
            raise self.recover_faults.popleft()
        if not self.high_water_log:
            return None
        return CommitRange(high=self.high_water,
                           commit_end_lsn=self.committed_end_lsn or None)


class FaultKind(enum.Enum):
    REJECT = "reject"  # fail before applying
    FAIL_AFTER_APPLY = "fail_after_apply"  # apply, then report failure
    HOLD = "hold"  # apply, ack Accepted, durable only on release()
    DELAY = "delay"  # apply after a delay, then durable


@dataclass
class FaultAction:
    kind: FaultKind
    delay_s: float = 0.0
    release_event: asyncio.Event | None = None


class FaultInjectingDestination(Destination):
    """Wraps a destination with per-operation FIFO fault scripts
    (reference TestDestinationWrapper)."""

    def __init__(self, inner: Destination):
        self.inner = inner
        self._faults: dict[str, deque[FaultAction]] = defaultdict(deque)
        self.write_events_calls = 0
        self.write_rows_calls = 0
        # strong refs: a bare ensure_future handle is GC-collectable and
        # the loop may cancel the release task mid-HOLD (etl-lint:
        # orphaned-task)
        self._tasks = TaskSet()
        self._held_acks: list[asyncio.Future] = []
        self._shut_down = False
        # HOLD acks shutdown had to force-fail because nothing released
        # them — the chaos no-leaks invariant reads this (counting
        # _held_acks after shutdown would always see the cleared list)
        self.forced_held_acks = 0

    def script(self, op: str, action: FaultAction) -> None:
        """op: one of write_table_rows / write_events / drop_table /
        truncate_table."""
        self._faults[op].append(action)

    def _next_fault(self, op: str) -> FaultAction | None:
        q = self._faults.get(op)
        return q.popleft() if q else None

    async def _apply_fault(self, op: str, run) -> WriteAck:
        fault = self._next_fault(op)
        if fault is None:
            return await run()
        if fault.kind is FaultKind.REJECT:
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           f"scripted reject on {op}")
        if fault.kind is FaultKind.FAIL_AFTER_APPLY:
            await run()
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           f"scripted fail-after-apply on {op}")
        if fault.kind is FaultKind.DELAY:
            await asyncio.sleep(fault.delay_s)
            return await run()
        # HOLD: apply now, durable on release
        await run()
        ack, fut = WriteAck.accepted()
        release = fault.release_event or asyncio.Event()

        async def _release() -> None:
            await release.wait()  # etl-lint: ignore[unbounded-await] — waiting for the test script's release IS the HOLD fault; the TaskSet cancels it at shutdown
            if not fut.done():
                fut.set_result(None)
            if fut in self._held_acks:  # released: nothing to resolve at
                # shutdown (and the list must not grow per HOLD); may be
                # gone already if shutdown swept mid-release
                self._held_acks.remove(fut)

        if self._shut_down:
            # the writer was suspended in `await run()` while shutdown
            # swept _held_acks — registering now would hang the consumer
            self._fail_held(fut)
            return ack
        self._tasks.spawn(_release())
        self._held_acks.append(fut)
        return ack

    @staticmethod
    def _fail_held(fut: asyncio.Future) -> None:
        fut.set_exception(EtlError(
            ErrorKind.DESTINATION_FAILED,
            "destination shut down with HOLD pending"))
        # the consumer may be gone already (cancelled apply loop); mark
        # retrieved so GC doesn't log "exception was never retrieved" —
        # a later await still sees the error
        fut.exception()

    async def startup(self) -> None:
        # a restarted pipeline reuses the wrapper: new HOLDs must be
        # registrable again after a previous clean shutdown
        self._shut_down = False
        await self.inner.startup()

    async def shutdown(self) -> None:
        self._shut_down = True  # writers mid-`await run()` must not
        # register new held acks after the sweep below
        await self._tasks.cancel_all()
        # a cancelled (or never-started) release task can't resolve its
        # ack — a consumer awaiting durability would hang forever
        for fut in self._held_acks:
            if not fut.done():
                self.forced_held_acks += 1
                self._fail_held(fut)
        self._held_acks.clear()
        await self.inner.shutdown()

    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        self.write_rows_calls += 1
        return await self._apply_fault(
            "write_table_rows",
            lambda: self.inner.write_table_rows(schema, batch))

    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        self.write_events_calls += 1
        return await self._apply_fault(
            "write_events", lambda: self.inner.write_events(events))

    # columnar seam: SAME fault-script keys as the row entry points, so
    # every chaos scenario scripted against write_table_rows/write_events
    # exercises the batch-granularity seam unchanged
    async def write_table_batch(self, schema: ReplicatedTableSchema,
                                batch: ColumnarBatch) -> WriteAck:
        self.write_rows_calls += 1
        return await self._apply_fault(
            "write_table_rows",
            lambda: self.inner.write_table_batch(schema, batch))

    async def write_event_batches(self, events: Sequence[Event]) -> WriteAck:
        self.write_events_calls += 1
        return await self._apply_fault(
            "write_events",
            lambda: self.inner.write_event_batches(events))

    # transactional seam: same "write_events" fault key, so every chaos
    # script against the CDC path exercises the exactly-once seam too
    def supports_transactional_commit(self) -> bool:
        return self.inner.supports_transactional_commit()

    async def write_event_batches_committed(self, events: Sequence[Event],
                                            commit) -> WriteAck:
        self.write_events_calls += 1
        return await self._apply_fault(
            "write_events",
            lambda: self.inner.write_event_batches_committed(events, commit))

    async def recover_high_water(self):
        return await self._apply_fault(
            "recover_high_water",
            lambda: self.inner.recover_high_water())

    async def drop_table(self, table_id: TableId,
                         schema=None) -> None:
        async def run():
            await self.inner.drop_table(table_id, schema)
            return WriteAck.durable()

        await self._apply_fault("drop_table", run)

    async def truncate_table(self, table_id: TableId) -> None:
        async def run():
            await self.inner.truncate_table(table_id)
            return WriteAck.durable()

        await self._apply_fault("truncate_table", run)


class PoisonRejectingDestination(Destination):
    """Wraps a destination with content-based rejection: any CDC write
    whose rows contain a marked poison value fails with
    `DESTINATION_REJECTED` — the deterministic analogue of an
    unencodable value / schema-drift row a real destination 4xxes. The
    trigger the isolation protocol (runtime/poison.py) bisects on.

    Rejection is CONTENT-keyed, not call-keyed (unlike the scripted
    FaultInjectingDestination FIFO): re-writing the same poisoned batch
    fails again, a sub-batch without the poison row succeeds — exactly
    the semantics binary bisection needs. The initial-copy path passes
    through untouched (poison-pill isolation is a streaming-CDC
    boundary; copy failures keep the per-table error states)."""

    def __init__(self, inner: Destination, marker: str = "POISON",
                 is_poison=None):
        self.inner = inner
        # egress/billing labels must name the REAL sink, not the wrapper
        self.telemetry_name = getattr(inner, "telemetry_name",
                                      type(inner).__name__)
        self.marker = marker
        self._is_poison = is_poison or (
            lambda v: isinstance(v, str) and v.startswith(marker))
        self.rejections = 0
        self.rejected_values: list = []

    def _scan(self, events: Sequence[Event]) -> None:
        from ..models.event import (DecodedBatchEvent, DeleteEvent,
                                    InsertEvent, UpdateEvent)

        for ev in events:
            if isinstance(ev, (InsertEvent, UpdateEvent)):
                rows = [ev.row]
                tid = ev.schema.id
            elif isinstance(ev, DeleteEvent):
                rows = [ev.old_row]
                tid = ev.schema.id
            elif isinstance(ev, DecodedBatchEvent):
                rows = ev.batch.to_rows()
                tid = ev.schema.id
            else:
                continue
            for row in rows:
                for v in row.values:
                    if self._is_poison(v):
                        self.rejections += 1
                        self.rejected_values.append(v)
                        raise EtlError(
                            ErrorKind.DESTINATION_REJECTED,
                            f"unencodable value in table {tid}: {v!r}")

    async def startup(self) -> None:
        await self.inner.startup()

    async def shutdown(self) -> None:
        await self.inner.shutdown()

    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        return await self.inner.write_table_rows(schema, batch)

    async def write_table_batch(self, schema: ReplicatedTableSchema,
                                batch: ColumnarBatch) -> WriteAck:
        return await self.inner.write_table_batch(schema, batch)

    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        self._scan(events)
        return await self.inner.write_events(events)

    async def write_event_batches(self, events: Sequence[Event]) -> WriteAck:
        self._scan(events)
        return await self.inner.write_event_batches(events)

    def supports_transactional_commit(self) -> bool:
        return self.inner.supports_transactional_commit()

    async def write_event_batches_committed(self, events: Sequence[Event],
                                            commit) -> WriteAck:
        self._scan(events)
        return await self.inner.write_event_batches_committed(events, commit)

    async def recover_high_water(self):
        return await self.inner.recover_high_water()

    async def drop_table(self, table_id: TableId, schema=None) -> None:
        await self.inner.drop_table(table_id, schema)

    async def truncate_table(self, table_id: TableId) -> None:
        await self.inner.truncate_table(table_id)
