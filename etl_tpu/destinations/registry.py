"""Destination dispatch from configuration.

Reference parity: the replicator's destination config enum → instance
dispatch (crates/etl-replicator/src/core/destinations.rs, 417 LoC)."""

from __future__ import annotations

from typing import Any

from ..models.errors import ErrorKind, EtlError
from .base import Destination
from .memory import MemoryDestination


def build_destination(doc: dict[str, Any]) -> Destination:
    """{"type": "...", ...params} → Destination instance."""
    kind = doc.get("type")
    params = {k: v for k, v in doc.items() if k != "type"}
    try:
        if kind == "memory":
            return MemoryDestination()
        if kind == "clickhouse":
            from .clickhouse import (ClickHouseConfig, ClickHouseDestination,
                                     ClickHouseEngine)

            if "engine" in params:
                params["engine"] = ClickHouseEngine(params["engine"])
            return ClickHouseDestination(ClickHouseConfig(**params))
        if kind == "bigquery":
            from .bigquery import BigQueryConfig, BigQueryDestination

            return BigQueryDestination(BigQueryConfig(**params))
        if kind == "lake":
            from .lake import LakeConfig, LakeDestination

            return LakeDestination(LakeConfig(**params))
        if kind == "iceberg":
            from .iceberg import IcebergConfig, IcebergDestination

            return IcebergDestination(IcebergConfig(**params))
        if kind == "snowflake":
            from .snowflake import SnowflakeConfig, SnowflakeDestination

            return SnowflakeDestination(SnowflakeConfig(**params))
    except (TypeError, ValueError) as e:
        raise EtlError(ErrorKind.CONFIG_INVALID,
                       f"destination {kind!r}: {e}")
    raise EtlError(ErrorKind.CONFIG_INVALID,
                   f"unknown destination type {kind!r}")
