"""Iceberg destination: REST catalog + Parquet append writer.

Reference parity: crates/etl-destinations/src/iceberg/ ({catalog,client,
core,schema}.rs, 5.6k LoC) — REST-catalog namespace/table management and
Arrow→Parquet appends committed as table snapshots. Data files land in the
warehouse directory (local path here; object-store URI in production);
commits go through the standard Iceberg REST `/v1` API so any conformant
catalog (fake server in tests) works.
"""

from __future__ import annotations

import json
import uuid
from dataclasses import dataclass
from pathlib import Path
from typing import Sequence

import aiohttp
import pyarrow as pa
import pyarrow.parquet as pq

from ..models.errors import ErrorKind, EtlError
from ..models.event import (DeleteEvent, Event, InsertEvent,
                            SchemaChangeEvent, TruncateEvent, UpdateEvent)
from ..models.pgtypes import CellKind
from ..models.schema import ReplicatedTableSchema, TableId
from ..models.table_row import ColumnarBatch
from .base import Destination, WriteAck, expand_batch_events
from .util import (CHANGE_SEQUENCE_COLUMN, CHANGE_TYPE_COLUMN,
                   DestinationRetryPolicy, change_type_label,
                   escaped_table_name, http_status_retryable,
                   require_full_row, sequential_event_program,
                   with_retries)
from ..models.event import ChangeType

_ICEBERG_TYPES: dict[CellKind, str] = {
    CellKind.BOOL: "boolean", CellKind.I16: "int", CellKind.I32: "int",
    CellKind.U32: "long", CellKind.I64: "long", CellKind.F32: "float",
    CellKind.F64: "double", CellKind.NUMERIC: "string",
    CellKind.DATE: "date", CellKind.TIME: "time",
    CellKind.TIMESTAMP: "timestamp", CellKind.TIMESTAMPTZ: "timestamptz",
    CellKind.UUID: "uuid", CellKind.JSON: "string",
    CellKind.BYTES: "binary", CellKind.STRING: "string",
    CellKind.ARRAY: "string", CellKind.INTERVAL: "string",
}


@dataclass(frozen=True)
class IcebergConfig:
    catalog_url: str  # REST catalog base, e.g. http://host:8181
    warehouse_path: str  # where parquet data files are written
    namespace: str = "etl"
    auth_token: str = ""


class IcebergDestination(Destination):
    def __init__(self, config: IcebergConfig,
                 retry: DestinationRetryPolicy | None = None):
        self.config = config
        self.retry = retry or DestinationRetryPolicy()
        self._session: aiohttp.ClientSession | None = None
        self._created: dict[TableId, ReplicatedTableSchema] = {}
        self._names: dict[TableId, str] = {}

    async def _api(self, method: str, path: str,
                   body: dict | None = None) -> dict:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        headers = {"Authorization": f"Bearer {self.config.auth_token}"} \
            if self.config.auth_token else {}

        async def attempt() -> dict:
            async with self._session.request(
                    method, f"{self.config.catalog_url}/v1{path}",
                    json=body, headers=headers) as resp:
                text = await resp.text()
                if resp.status == 409:  # already exists → idempotent ok
                    return {"alreadyExists": True}
                if resp.status >= 400:
                    raise EtlError(
                        ErrorKind.DESTINATION_THROTTLED
                        if http_status_retryable(resp.status)
                        else ErrorKind.DESTINATION_FAILED,
                        f"iceberg {resp.status} {path}: {text[:300]}")
                return json.loads(text) if text else {}

        def retryable(e: BaseException) -> bool:
            if isinstance(e, EtlError):
                return e.kind is ErrorKind.DESTINATION_THROTTLED
            return isinstance(e, (aiohttp.ClientError, OSError))

        return await with_retries(attempt, self.retry, retryable)

    async def startup(self) -> None:
        Path(self.config.warehouse_path).mkdir(parents=True, exist_ok=True)
        await self._api("POST", "/namespaces",
                        {"namespace": [self.config.namespace]})

    def _iceberg_schema(self, schema: ReplicatedTableSchema) -> dict:
        fields = [{"id": i + 1, "name": c.name, "required": not c.nullable,
                   "type": _ICEBERG_TYPES.get(c.kind, "string")}
                  for i, c in enumerate(schema.replicated_columns)]
        n = len(fields)
        fields.append({"id": n + 1, "name": CHANGE_TYPE_COLUMN,
                       "required": False, "type": "string"})
        fields.append({"id": n + 2, "name": CHANGE_SEQUENCE_COLUMN,
                       "required": False, "type": "string"})
        return {"type": "struct", "fields": fields}

    async def _ensure_table(self, schema: ReplicatedTableSchema) -> str:
        name = self._names.setdefault(schema.id,
                                      escaped_table_name(schema.name))
        if self._created.get(schema.id) == schema:
            return name
        await self._api(
            "POST", f"/namespaces/{self.config.namespace}/tables",
            {"name": name, "schema": self._iceberg_schema(schema)})
        self._created[schema.id] = schema
        return name

    def _write_data_file(self, name: str, rb: pa.RecordBatch) -> str:
        d = Path(self.config.warehouse_path) / self.config.namespace / name
        d.mkdir(parents=True, exist_ok=True)
        path = d / f"{uuid.uuid4().hex}.parquet"
        pq.write_table(pa.Table.from_batches([rb]), path)
        return str(path)

    async def _commit_append(self, name: str, file_path: str,
                             rows: int) -> None:
        await self._api(
            "POST",
            f"/namespaces/{self.config.namespace}/tables/{name}/commit",
            {"updates": [{"action": "append", "data-files": [
                {"file-path": file_path, "record-count": rows,
                 "file-format": "PARQUET"}]}]})

    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        name = await self._ensure_table(schema)
        if batch.num_rows:
            rb = batch.to_arrow()
            n = batch.num_rows
            rb = rb.append_column(CHANGE_TYPE_COLUMN,
                                  pa.array(["UPSERT"] * n, pa.string()))
            rb = rb.append_column(CHANGE_SEQUENCE_COLUMN,
                                  pa.array([f"{i:016x}" for i in range(n)],
                                           pa.string()))
            path = self._write_data_file(name, rb)
            await self._commit_append(name, path, n)
        return WriteAck.durable()

    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        for op in sequential_event_program(expand_batch_events(events)):
            if op[0] == "rows":
                _, schema, evs = op
                await self._write_cdc_run(schema, evs)
            elif op[0] == "truncate":
                for sch in op[1].schemas:
                    await self.truncate_table(sch.id)
            else:
                await self._apply_schema_change(op[1])
        return WriteAck.durable()

    async def _write_cdc_run(self, schema: ReplicatedTableSchema,
                             evs: list) -> None:
        name = await self._ensure_table(schema)
        rows, types, seqs = [], [], []
        for i, e in enumerate(evs):
            if isinstance(e, DeleteEvent):
                rows.append(e.old_row)
                types.append(change_type_label(ChangeType.DELETE))
            else:
                require_full_row("iceberg", schema, e.row)
                rows.append(e.row)
                types.append(change_type_label(ChangeType.INSERT))
            seqs.append(e.sequence_key.with_ordinal(i))
        rb = ColumnarBatch.from_rows(schema, rows).to_arrow()
        rb = rb.append_column(CHANGE_TYPE_COLUMN, pa.array(types, pa.string()))
        rb = rb.append_column(CHANGE_SEQUENCE_COLUMN,
                              pa.array(seqs, pa.string()))
        path = self._write_data_file(name, rb)
        await self._commit_append(name, path, len(rows))

    async def _apply_schema_change(self, ev) -> None:
        """Register the new schema with the catalog via an update commit —
        table re-create 409s would silently diverge registered schema from
        data files."""
        new = ev.new_schema
        assert new is not None
        name = self._names.setdefault(new.id, escaped_table_name(new.name))
        await self._api(
            "POST",
            f"/namespaces/{self.config.namespace}/tables/{name}/commit",
            {"updates": [{"action": "set-schema",
                          "schema": self._iceberg_schema(new)}]})
        self._created[new.id] = new

    async def drop_table(self, table_id: TableId,
                         schema: ReplicatedTableSchema | None = None) -> None:
        if table_id not in self._names and schema is not None:
            # restart recovery: rebuild the name mapping from the hint
            self._names.setdefault(table_id, escaped_table_name(schema.name))
        name = self._names.get(table_id)
        if name is not None:
            await self._api(
                "DELETE",
                f"/namespaces/{self.config.namespace}/tables/{name}")
            self._created.pop(table_id, None)

    async def truncate_table(self, table_id: TableId) -> None:
        name = self._names.get(table_id)
        if name is not None:
            await self._api(
                "POST",
                f"/namespaces/{self.config.namespace}/tables/{name}/commit",
                {"updates": [{"action": "truncate"}]})

    async def shutdown(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
