"""Iceberg destination: REST catalog + Parquet appends committed as REAL
Iceberg v2 snapshots.

Reference parity: crates/etl-destinations/src/iceberg/ ({catalog,client,
core,schema}.rs, 5.6k LoC). Each append:

1. writes the Parquet data file into the warehouse;
2. gathers data-file statistics from the Parquet footer (record counts,
   per-column sizes/null counts, lower/upper bounds — iceberg_meta.py);
3. writes an Avro manifest file + manifest list (hand-rolled Avro OCF
   writer; no avro library in the environment);
4. commits through the standard Iceberg REST protocol:
   `POST /v1/namespaces/{ns}/tables/{t}` with an
   assert-ref-snapshot-id requirement (optimistic CAS against the main
   branch) and add-snapshot + set-snapshot-ref updates.

Schema evolution rides add-schema + set-current-schema updates; truncate
is a `delete`-operation snapshot whose manifest list is empty. The fake
catalog used in tests (testing/fake_iceberg.py) parses the manifest
chain with an INDEPENDENT Avro reader and rejects commits whose
metadata doesn't hold together.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from dataclasses import dataclass
from pathlib import Path
from typing import Sequence

import aiohttp
import pyarrow as pa
import pyarrow.parquet as pq

from ..analysis.annotations import hot_loop, transactional_commit
from ..models.errors import ErrorKind, EtlError
from ..models.event import ChangeType, DeleteEvent, Event
from ..models.pgtypes import CellKind
from ..models.schema import ReplicatedTableSchema, TableId
from ..models.table_row import ColumnarBatch
from .base import CommitRange, Destination, WriteAck, expand_batch_events
from .iceberg_meta import (DataFileInfo, build_snapshot, data_file_stats,
                           new_snapshot_id, write_manifest,
                           write_manifest_list)
from .util import (CHANGE_SEQUENCE_COLUMN, CHANGE_TYPE_COLUMN,
                   DestinationRetryPolicy, change_type_label,
                   classify_http_error, escaped_table_name,
                   require_full_row, sequential_event_program,
                   with_retries)

_ICEBERG_TYPES: dict[CellKind, str] = {
    CellKind.BOOL: "boolean", CellKind.I16: "int", CellKind.I32: "int",
    CellKind.U32: "long", CellKind.I64: "long", CellKind.F32: "float",
    CellKind.F64: "double", CellKind.NUMERIC: "string",
    CellKind.DATE: "date", CellKind.TIME: "time",
    CellKind.TIMESTAMP: "timestamp", CellKind.TIMESTAMPTZ: "timestamptz",
    CellKind.UUID: "uuid", CellKind.JSON: "string",
    CellKind.BYTES: "binary", CellKind.STRING: "string",
    CellKind.ARRAY: "string", CellKind.INTERVAL: "string",
}


class _CasConflict(Exception):
    """assert-ref-snapshot-id lost the optimistic race (another writer
    advanced the branch) — recoverable by re-adopting catalog state."""


@dataclass(frozen=True)
class IcebergConfig:
    catalog_url: str  # REST catalog base, e.g. http://host:8181
    warehouse_path: str  # where parquet data files are written
    namespace: str = "etl"
    auth_token: str = ""


@dataclass
class _TableState:
    """Catalog-side state tracked per table between commits."""

    name: str
    snapshot_id: int | None = None  # main-branch head (CAS token)
    sequence_number: int = 0
    schema_id: int = 0
    schema_count: int = 1  # schemas registered (add-schema ids are dense)
    total_records: int = 0
    schema: ReplicatedTableSchema | None = None
    # column name → Iceberg field id. Ids are assigned once and NEVER
    # reused or reassigned (spec: schema evolution must keep existing
    # ids stable; manifests key statistics by id, so an ordinal
    # reassignment would silently corrupt scan pruning on old files)
    field_ids: dict[str, int] = None  # type: ignore[assignment]
    last_column_id: int = 0  # high-water mark; fresh ids start past it
    # the catalog's CURRENT schema fields (adopt path): lets a restarted
    # destination decide whether a SchemaChangeEvent still needs an
    # add-schema commit or the catalog already caught up
    catalog_fields: list | None = None


class IcebergDestination(Destination):
    def __init__(self, config: IcebergConfig,
                 retry: DestinationRetryPolicy | None = None):
        self.config = config
        self.retry = retry or DestinationRetryPolicy()
        self._session: aiohttp.ClientSession | None = None
        self._tables: dict[TableId, _TableState] = {}
        # exactly-once seam: the in-flight committed write's range,
        # stamped into every snapshot summary _commit_snapshot builds
        # while it is set (atomic with the catalog CAS commit)
        self._pending_commit = None

    async def _api(self, method: str, path: str,
                   body: dict | None = None,
                   conflict_ok: bool = False,
                   conflict_raises: bool = False) -> dict:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        headers = {"Authorization": f"Bearer {self.config.auth_token}"} \
            if self.config.auth_token else {}

        async def attempt() -> dict:
            async with self._session.request(
                    method, f"{self.config.catalog_url}/v1{path}",
                    json=body, headers=headers) as resp:
                text = await resp.text()
                if resp.status == 409 and conflict_ok:
                    return {"alreadyExists": True}
                if resp.status == 409 and conflict_raises:
                    # optimistic-CAS loss: blind HTTP retry would replay
                    # the SAME stale requirement forever — the caller
                    # must re-adopt catalog state and rebuild the commit
                    raise _CasConflict(text[:300])
                if resp.status >= 400:
                    raise classify_http_error(
                        "iceberg", resp.status, f"{path}: {text[:300]}")
                return json.loads(text) if text else {}

        def retryable(e: BaseException) -> bool:
            if isinstance(e, EtlError):
                return e.kind is ErrorKind.DESTINATION_THROTTLED
            return isinstance(e, (aiohttp.ClientError, OSError))

        return await with_retries(attempt, self.retry, retryable)

    async def startup(self) -> None:
        Path(self.config.warehouse_path).mkdir(parents=True, exist_ok=True)
        await self._api("POST", "/namespaces",
                        {"namespace": [self.config.namespace]},
                        conflict_ok=True)

    # -- schema ---------------------------------------------------------------

    @staticmethod
    def _assign_field_ids(schema: ReplicatedTableSchema,
                          prev: dict[str, int] | None = None,
                          last: int = 0) -> tuple[dict[str, int], int]:
        """Stable field-id assignment: columns present in `prev` keep
        their ids; new columns get fresh ids past `last` (the table's
        last-column-id). Ids are never reused — a dropped-then-re-added
        column gets a NEW id, as the spec requires."""
        ids: dict[str, int] = {}
        names = [c.name for c in schema.replicated_columns]
        names += [CHANGE_TYPE_COLUMN, CHANGE_SEQUENCE_COLUMN]
        for name in names:
            if prev and name in prev:
                ids[name] = prev[name]
            else:
                last += 1
                ids[name] = last
        return ids, last

    def _iceberg_schema(self, schema: ReplicatedTableSchema,
                        field_ids: dict[str, int],
                        schema_id: int = 0) -> dict:
        fields = [{"id": field_ids[c.name], "name": c.name,
                   "required": not c.nullable,
                   "type": _ICEBERG_TYPES.get(c.kind, "string")}
                  for c in schema.replicated_columns]
        fields.append({"id": field_ids[CHANGE_TYPE_COLUMN],
                       "name": CHANGE_TYPE_COLUMN,
                       "required": False, "type": "string"})
        fields.append({"id": field_ids[CHANGE_SEQUENCE_COLUMN],
                       "name": CHANGE_SEQUENCE_COLUMN,
                       "required": False, "type": "string"})
        identifiers = [field_ids[c.name] for c in
                       schema.replicated_columns
                       if c.primary_key_ordinal is not None]
        return {"type": "struct", "schema-id": schema_id,
                "identifier-field-ids": identifiers, "fields": fields}

    def _field_meta(self, st: _TableState
                    ) -> tuple[dict[str, int], dict[int, str]]:
        """(column name → field id, field id → iceberg type), derived
        from the SAME schema document the catalog sees — one source of
        truth for the id assignment."""
        assert st.schema is not None
        doc = self._iceberg_schema(st.schema, st.field_ids, st.schema_id)
        ids = {f["name"]: f["id"] for f in doc["fields"]}
        types = {f["id"]: f["type"] for f in doc["fields"]}
        return ids, types

    async def _ensure_table(self, schema: ReplicatedTableSchema
                            ) -> _TableState:
        st = self._tables.get(schema.id)
        if st is not None and st.schema == schema:
            return st
        name = escaped_table_name(schema.name)
        field_ids, last_id = self._assign_field_ids(schema)
        schema_doc = self._iceberg_schema(schema, field_ids)
        doc = await self._api(
            "POST", f"/namespaces/{self.config.namespace}/tables",
            {"name": name, "schema": schema_doc,
             "partition-spec": {"spec-id": 0, "fields": []},
             "properties": {"format-version": "2"}},
            conflict_ok=True)
        st = _TableState(name=name, schema=schema, field_ids=field_ids,
                         last_column_id=last_id,
                         catalog_fields=schema_doc["fields"])
        if doc.get("alreadyExists"):
            await self._adopt_catalog_state(st, schema)
        self._tables[schema.id] = st
        return st

    async def _adopt_catalog_state(self, st: _TableState,
                                   schema: ReplicatedTableSchema) -> dict:
        """Refresh st from the catalog's CURRENT metadata (restart
        recovery, and CAS-conflict recovery — another writer advanced
        the branch, so the cached head/sequence/totals are stale).
        Returns the metadata document (conflict recovery inspects the
        snapshot list for its own lost-response commit)."""
        loaded = await self._api(
            "GET",
            f"/namespaces/{self.config.namespace}/tables/{st.name}")
        meta = loaded.get("metadata", {})
        st.snapshot_id = meta.get("current-snapshot-id")
        st.sequence_number = meta.get("last-sequence-number", 0)
        st.schema_id = meta.get("current-schema-id", 0)
        st.schema_count = max(1, len(meta.get("schemas", [])))
        st.catalog_fields = None  # unknown until found below
        adopted: dict[str, int] = {}
        all_ids = [0]
        for s in meta.get("schemas", []):
            all_ids += [f["id"] for f in s.get("fields", [])]
            if s.get("schema-id") == st.schema_id:
                st.catalog_fields = s.get("fields")
                adopted = {f["name"]: f["id"] for f in s["fields"]}
        # keep the catalog's ids; columns the target schema adds on
        # top get fresh ids past EVERY id any schema ever used
        st.field_ids, st.last_column_id = self._assign_field_ids(
            schema, adopted or None, max(all_ids))
        st.total_records = 0
        for snap in meta.get("snapshots", []):
            if snap.get("snapshot-id") == st.snapshot_id:
                st.total_records = int(
                    snap.get("summary", {}).get("total-records", 0))
        return meta

    # -- data + snapshot commit ------------------------------------------------

    def _table_dir(self, name: str) -> Path:
        return Path(self.config.warehouse_path) / self.config.namespace \
            / name

    def _write_data_file(self, st: _TableState,
                         rb: pa.RecordBatch) -> DataFileInfo:
        d = self._table_dir(st.name) / "data"
        d.mkdir(parents=True, exist_ok=True)
        path = d / f"{uuid.uuid4().hex}.parquet"
        field_ids, field_types = self._field_meta(st)
        # stamp Iceberg field ids into the Parquet schema
        # (PARQUET:field_id metadata → parquet field_id on write): the
        # spec requires data-file columns to resolve by ID, not name —
        # without this a conformant engine cannot project any column
        fields = [pa.field(f.name, f.type, f.nullable,
                           metadata={b"PARQUET:field_id":
                                     str(field_ids[f.name]).encode()})
                  for f in rb.schema]
        rb = pa.RecordBatch.from_arrays(list(rb.columns),
                                        schema=pa.schema(fields))
        pq.write_table(pa.Table.from_batches([rb]), path)
        return data_file_stats(path, field_ids, field_types)

    async def _commit_snapshot(self, st: _TableState,
                               files: list[DataFileInfo],
                               operation: str = "append") -> None:
        # all state transitions are staged LOCALLY and applied only after
        # the catalog accepts the commit — a failed commit (CAS 409,
        # exhausted retries) must leave the table's sequence number and
        # row totals untouched or every later commit would be rejected.
        # A lost CAS race (another writer advanced the branch) re-adopts
        # the catalog state and REBUILDS the commit on the new head —
        # blind retry would replay the stale requirement forever.
        meta_dir = self._table_dir(st.name) / "metadata"
        # the files in this commit were ALREADY written (parquet field
        # ids stamped) under the pre-conflict schema identity — the
        # rebuilt manifest must keep describing them with that identity
        # (schemas are append-only, so the id stays valid) even though
        # adoption refreshes st for FUTURE writes
        commit_schema_id = st.schema_id
        commit_field_ids = dict(st.field_ids)
        assert st.schema is not None
        commit_schema_json = json.dumps(self._iceberg_schema(
            st.schema, commit_field_ids, commit_schema_id))
        snapshot_id = new_snapshot_id()  # stable across retries: a lost
        # RESPONSE re-POSTs, 409s on our own head, and is recognized below
        for attempt in range(4):
            sequence_number = st.sequence_number + 1
            manifests = []
            if files:
                manifests.append(write_manifest(
                    meta_dir, files, snapshot_id, sequence_number,
                    commit_schema_json))
            manifest_list = write_manifest_list(
                meta_dir, manifests, snapshot_id, sequence_number)
            added = sum(f.record_count for f in files)
            new_total = added if operation == "delete" \
                else st.total_records + added
            snapshot = build_snapshot(
                snapshot_id, st.snapshot_id, sequence_number, manifest_list,
                operation, len(files), added, new_total,
                int(time.time() * 1000), commit_schema_id)
            if self._pending_commit is not None:
                # exactly-once: the WAL range rides the snapshot summary
                # (the Flink/Iceberg checkpoint-id idiom) — data files
                # and coordinates land in ONE catalog CAS commit, and
                # recover_high_water reads them back from the snapshot
                # log
                pc = self._pending_commit
                if pc.replay:
                    snapshot["summary"]["etl-replay-token"] = pc.token()
                else:
                    snapshot["summary"]["etl-high-water"] = pc.token()
                    if pc.commit_end_lsn:
                        snapshot["summary"]["etl-commit-end-lsn"] = \
                            str(pc.commit_end_lsn)
            body = {
                "requirements": [{
                    "type": "assert-ref-snapshot-id", "ref": "main",
                    "snapshot-id": st.snapshot_id,
                }],
                "updates": [
                    {"action": "add-snapshot", "snapshot": snapshot},
                    {"action": "set-snapshot-ref", "ref-name": "main",
                     "type": "branch", "snapshot-id": snapshot_id},
                ],
            }
            def _drop_attempt_files() -> None:
                # a commit the catalog did NOT take leaves this
                # attempt's manifest files unreachable — drop them
                # instead of leaving orphans
                for p in ([manifest_list]
                          + [m.manifest_path for m in manifests]):
                    Path(p).unlink(missing_ok=True)

            try:
                await self._api(
                    "POST",
                    f"/namespaces/{self.config.namespace}/tables/{st.name}",
                    body, conflict_raises=True)
            except _CasConflict as e:
                meta = await self._adopt_catalog_state(st, st.schema)
                if any(s.get("snapshot-id") == snapshot_id
                       for s in meta.get("snapshots", [])):
                    # the commit APPLIED but its response was lost: the
                    # conflicting head is our own snapshot (or a later
                    # one on top of it) — committing again would
                    # double-write every row. Adoption already set
                    # st.snapshot_id/sequence/totals from the catalog;
                    # the metadata files stay (the catalog references
                    # them).
                    return
                _drop_attempt_files()
                if attempt == 3:
                    raise EtlError(
                        ErrorKind.DESTINATION_FAILED,
                        f"iceberg: commit lost the CAS race 4 times "
                        f"on {st.name}: {e}")
                # jittered backoff before racing the other writer again
                # (instant retries let a steady writer win every round)
                await asyncio.sleep(self.retry.delay(attempt))
                continue
            except BaseException:
                _drop_attempt_files()
                raise
            break
        st.snapshot_id = snapshot_id
        st.sequence_number = sequence_number
        st.total_records = new_total

    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        from .util import hex16_arrow

        st = await self._ensure_table(schema)
        if batch.num_rows:
            import numpy as np

            rb = batch.to_arrow()
            n = batch.num_rows
            rb = rb.append_column(CHANGE_TYPE_COLUMN,
                                  pa.array(["UPSERT"] * n, pa.string()))
            rb = rb.append_column(
                CHANGE_SEQUENCE_COLUMN,
                # vectorized hex render (same bytes as the f-string form)
                hex16_arrow(np.arange(n, dtype=np.uint64)))
            f = self._write_data_file(st, rb)
            await self._commit_snapshot(st, [f])
        return WriteAck.durable()

    # -- columnar seam --------------------------------------------------------

    async def write_table_batch(self, schema: ReplicatedTableSchema,
                                batch: ColumnarBatch) -> WriteAck:
        """Copy path, columnar: Arrow-native with vectorized CDC metadata
        (the row path's per-row f-string sequence suffixes were measurable
        at copy rates)."""
        from .util import hex16_arrow

        st = await self._ensure_table(schema)
        if batch.num_rows:
            import numpy as np

            rb = batch.to_arrow()
            n = batch.num_rows
            rb = rb.append_column(CHANGE_TYPE_COLUMN,
                                  pa.array(["UPSERT"] * n, pa.string()))
            rb = rb.append_column(
                CHANGE_SEQUENCE_COLUMN,
                hex16_arrow(np.arange(n, dtype=np.uint64)))
            f = self._write_data_file(st, rb)
            await self._commit_snapshot(st, [f])
        return WriteAck.durable()

    async def write_event_batches(self, events: Sequence[Event]) -> WriteAck:
        """CDC path, columnar: decoded batch runs commit as Parquet +
        snapshot without row expansion; old-tuple/TOAST batches and
        per-row events drop to the row path in place."""
        from .base import sequential_batch_program

        for op in sequential_batch_program(events):
            if op[0] == "batch":
                _, schema, cb = op
                await self._write_cdc_batch(schema, cb)
            elif op[0] == "rows":
                _, schema, evs = op
                await self._write_cdc_run(schema, evs)
            elif op[0] == "truncate":
                for sch in op[1].schemas:
                    await self._ensure_table(sch)
                    await self.truncate_table(sch.id)
            else:
                await self._apply_schema_change(op[1])
        return WriteAck.durable()

    @hot_loop
    async def _write_cdc_batch(self, schema: ReplicatedTableSchema,
                               cb) -> None:
        """@hot_loop: the Iceberg CDC egress hot path (etl-lint rule 13)."""
        from .util import (change_type_arrow, require_full_batch,
                           sequence_number_arrow)

        import numpy as np

        st = await self._ensure_table(schema)
        require_full_batch("iceberg", schema, cb.batch, cb.change_types)
        n = cb.num_rows
        rb = cb.batch.to_arrow()
        rb = rb.append_column(CHANGE_TYPE_COLUMN,
                              change_type_arrow(cb.change_types))
        rb = rb.append_column(
            CHANGE_SEQUENCE_COLUMN,
            sequence_number_arrow(cb.commit_lsns, cb.tx_ordinals,
                                  np.arange(n, dtype=np.uint64)))
        f = self._write_data_file(st, rb)
        await self._commit_snapshot(st, [f])

    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        for op in sequential_event_program(expand_batch_events(events)):
            if op[0] == "rows":
                _, schema, evs = op
                await self._write_cdc_run(schema, evs)
            elif op[0] == "truncate":
                for sch in op[1].schemas:
                    # ensure first: after a restart the table may not be
                    # in the in-memory map, and silently skipping a
                    # truncate the source applied would leave stale data
                    await self._ensure_table(sch)
                    await self.truncate_table(sch.id)
            else:
                await self._apply_schema_change(op[1])
        return WriteAck.durable()

    # -- transactional seam (docs/destinations.md exactly-once contract) ------

    def supports_transactional_commit(self) -> bool:
        return True

    @transactional_commit
    async def write_event_batches_committed(
            self, events: Sequence[Event], commit: CommitRange) -> WriteAck:
        """Committed CDC write: the flush's WAL range is stamped into
        every snapshot summary the write commits (`_commit_snapshot`
        reads `_pending_commit`), so data files and coordinates land in
        ONE catalog CAS commit per table. Replays dedup by their exact
        token against the snapshot log and never stamp the streaming
        high-water key."""
        if commit.replay and await self._replay_seen(commit.token()):
            return WriteAck.durable()
        self._pending_commit = commit
        try:
            return await self.write_event_batches(events)
        finally:
            self._pending_commit = None

    async def _catalog_table_names(self) -> list[str]:
        doc = await self._api(
            "GET", f"/namespaces/{self.config.namespace}/tables")
        return [t["name"] for t in doc.get("identifiers", [])]

    async def _replay_seen(self, token: str) -> bool:
        for name in await self._catalog_table_names():
            loaded = await self._api(
                "GET",
                f"/namespaces/{self.config.namespace}/tables/{name}")
            for snap in loaded.get("metadata", {}).get("snapshots", []):
                if snap.get("summary", {}).get("etl-replay-token") \
                        == token:
                    return True
        return False

    async def recover_high_water(self) -> "CommitRange | None":
        """Max `etl-high-water` token across every table's snapshot log
        in the catalog — the committed truth survives a hard kill
        because it rides the snapshot commits themselves."""
        best: "tuple[int, int] | None" = None
        best_end: "int | None" = None
        for name in await self._catalog_table_names():
            loaded = await self._api(
                "GET",
                f"/namespaces/{self.config.namespace}/tables/{name}")
            for snap in loaded.get("metadata", {}).get("snapshots", []):
                summary = snap.get("summary", {})
                tok = summary.get("etl-high-water")
                if not tok:
                    continue
                lsn_hex, _, ord_hex = tok.partition("/")
                coord = (int(lsn_hex, 16), int(ord_hex, 16))
                if best is None or coord > best:
                    best = coord
                    end = summary.get("etl-commit-end-lsn")
                    best_end = int(end) if end else None
        if best is None:
            return None
        return CommitRange(high=best, commit_end_lsn=best_end)

    async def _write_cdc_run(self, schema: ReplicatedTableSchema,
                             evs: list) -> None:
        st = await self._ensure_table(schema)
        rows, types, seqs = [], [], []
        for i, e in enumerate(evs):
            if isinstance(e, DeleteEvent):
                rows.append(e.old_row)
                types.append(change_type_label(ChangeType.DELETE))
            else:
                require_full_row("iceberg", schema, e.row)
                rows.append(e.row)
                types.append(change_type_label(ChangeType.INSERT))
            seqs.append(e.sequence_key.with_ordinal(i))
        rb = ColumnarBatch.from_rows(schema, rows).to_arrow()
        rb = rb.append_column(CHANGE_TYPE_COLUMN, pa.array(types, pa.string()))
        rb = rb.append_column(CHANGE_SEQUENCE_COLUMN,
                              pa.array(seqs, pa.string()))
        f = self._write_data_file(st, rb)
        await self._commit_snapshot(st, [f])

    async def _apply_schema_change(self, ev) -> None:
        """Schema evolution: add-schema + set-current-schema updates on
        the SAME commit path (a table re-create 409 would silently
        diverge the registered schema from the data files)."""
        new = ev.new_schema
        assert new is not None
        st = self._tables.get(new.id)
        if st is not None and st.schema == new:
            # in-process redelivery (apply-worker timed retry): the
            # add-schema already committed — registering it again would
            # append a duplicate schema on every retry
            return
        if st is None:
            # restart recovery: adopt the catalog's state first, then
            # decide by comparing FIELDS whether the catalog's current
            # schema already matches the evolved one (st.schema alone
            # can't tell — _ensure_table stores the target schema)
            st = await self._ensure_table(new)
            desired = self._iceberg_schema(new, st.field_ids,
                                           st.schema_id)["fields"]
            if st.catalog_fields == desired:
                return
        for attempt in range(4):
            # existing columns keep their ids; additions get fresh ones
            ids, last = self._assign_field_ids(new, st.field_ids,
                                               st.last_column_id)
            new_schema_id = st.schema_count
            body = {
                "requirements": [{
                    "type": "assert-ref-snapshot-id", "ref": "main",
                    "snapshot-id": st.snapshot_id,
                }],
                "updates": [
                    {"action": "add-schema",
                     "schema": self._iceberg_schema(new, ids,
                                                    new_schema_id)},
                    {"action": "set-current-schema",
                     "schema-id": new_schema_id},
                ],
            }
            try:
                await self._api(
                    "POST",
                    f"/namespaces/{self.config.namespace}/tables/{st.name}",
                    body, conflict_raises=True)
            except (_CasConflict, EtlError) as e:
                # staleness here wears TWO shapes: a 409 when a data
                # commit moved the ref, and a 400 stale-schema-count
                # when a concurrent add-schema registered first (it
                # moves NO ref, so the CAS requirement still passes).
                # Both recover the same way: re-adopt, return if the
                # catalog already matches, else retry with the
                # refreshed count — a genuinely deterministic error
                # just fails again and surfaces on the last attempt.
                await self._adopt_catalog_state(st, new)
                desired = self._iceberg_schema(new, st.field_ids,
                                               st.schema_id)["fields"]
                if st.catalog_fields == desired:
                    st.schema = new  # catalog already caught up
                    return
                if attempt == 3:
                    raise
                await asyncio.sleep(self.retry.delay(attempt))
                continue
            break
        st.schema = new
        st.field_ids, st.last_column_id = ids, last
        st.schema_id = new_schema_id
        st.schema_count += 1
        st.catalog_fields = None

    async def drop_table(self, table_id: TableId,
                         schema: ReplicatedTableSchema | None = None) -> None:
        if table_id not in self._tables and schema is not None:
            # restart recovery: rebuild the name mapping from the hint
            self._tables[table_id] = _TableState(
                name=escaped_table_name(schema.name))
        st = self._tables.get(table_id)
        if st is not None:
            await self._api(
                "DELETE",
                f"/namespaces/{self.config.namespace}/tables/{st.name}")
            self._tables.pop(table_id, None)

    async def truncate_table(self, table_id: TableId) -> None:
        st = self._tables.get(table_id)
        if st is not None:
            # a delete-operation snapshot with an EMPTY manifest list:
            # readers of the new snapshot see zero data files
            await self._commit_snapshot(st, [], operation="delete")

    async def shutdown(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
